"""TPU serving plane end-to-end: dynamic batching + device verification.

Boots the gRPC auth service with the JAX data plane behind it (TPU when
available, any JAX backend otherwise), registers a population of users,
then fires concurrent logins — the dynamic batcher coalesces them into
device batches while each caller sees ordinary per-RPC semantics.

Run: python examples/tpu_serving.py [--users 12] [--device-chain]

--device-chain additionally turns on the opt-in all-device stages
(mod-l RLC prep on device; device Keccak challenge derivation was
removed after round-5 calibration measured it 18-37x slower than the
threaded native pool).
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def main(n_users: int) -> None:
    from cpzk_tpu.client import AuthClient
    from cpzk_tpu.client.__main__ import do_login, do_register
    from cpzk_tpu.ops.backend import TpuBackend
    from cpzk_tpu.protocol.batch import CpuBackend, FailoverBackend
    from cpzk_tpu.server import RateLimiter, ServerState
    from cpzk_tpu.server.batching import DynamicBatcher
    from cpzk_tpu.server.service import serve

    import jax

    print(f"JAX backend: {jax.devices()[0].platform} ({jax.device_count()} device(s))")

    state = ServerState()
    backend = FailoverBackend(TpuBackend(mesh_devices=0), CpuBackend())
    batcher = DynamicBatcher(backend, max_batch=256, window_ms=10.0, pipeline_depth=2)
    server, port = await serve(
        state, RateLimiter(100_000, 100_000), port=0,
        backend=backend, batcher=batcher,
    )
    batcher.start()
    print(f"auth service with TPU data plane on 127.0.0.1:{port}")

    async with AuthClient(f"127.0.0.1:{port}") as client:
        t0 = time.perf_counter()
        for i in range(n_users):
            await do_register(client, f"user{i}", f"pw-{i}")
        print(f"registered {n_users} users in {time.perf_counter() - t0:.2f}s")

        # concurrent logins: the batcher coalesces these into device batches
        t0 = time.perf_counter()
        results = await asyncio.gather(
            *[do_login(client, f"user{i}", f"pw-{i}") for i in range(n_users)]
        )
        dt = time.perf_counter() - t0
        ok = sum("Login OK" in r for r in results)
        print(f"{ok}/{n_users} concurrent logins in {dt:.2f}s "
              f"({n_users / dt:.1f} logins/s incl. Argon2id client KDF)")
        assert ok == n_users

        # a wrong password still fails, through the same batched path
        bad = await do_login(client, "user0", "nope")
        assert "Login OK" not in bad
        print("wrong password rejected (opaque error) — batched semantics intact")

        assert not backend.degraded, "device plane failed over to CPU"
        print("device plane served every verification (no failover)")

    await batcher.stop()
    await server.stop(None)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--users", type=int, default=12)
    ap.add_argument("--device-chain", action="store_true",
                    help="enable the opt-in all-device stages "
                         "(device mod-l RLC prep)")
    ap.add_argument("--platform", default=None,
                    help="force a jax backend (e.g. cpu) — env vars alone "
                         "don't reach jax under the axon sitecustomize, and "
                         "a wedged accelerator tunnel would hang the demo")
    args = ap.parse_args()
    if args.device_chain:
        os.environ["CPZK_DEVICE_RLC"] = "1"
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    asyncio.run(main(args.users))
