"""A complete password-based authentication flow, simulated in-process.

Didactic twin of the reference's ``examples/auth_system.rs`` (17-124): a
tiny in-memory "server" registers users by their public statements and
authenticates login attempts with single-use challenges; the "client"
derives its secret from a password.  Demonstrates the two attacks the
protocol defeats:

- replay: re-sending a captured proof fails because the challenge context
  is single-use and bound into the transcript;
- wrong secret: proving with the wrong password fails verification.

Run: python examples/auth_system.py
"""

import os
import secrets
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cpzk_tpu import (  # noqa: E402
    Error,
    Parameters,
    Proof,
    Prover,
    SecureRng,
    Statement,
    Transcript,
    Verifier,
    Witness,
)
from cpzk_tpu.client.kdf import password_to_scalar  # noqa: E402


class TinyAuthServer:
    """In-memory registry + single-use challenges (the gRPC server's logic
    without the transport; see cpzk_tpu.server for the real one)."""

    def __init__(self):
        self.params = Parameters.new()
        self.users: dict[str, Statement] = {}
        self.challenges: dict[bytes, str] = {}

    def register(self, user: str, statement: Statement) -> None:
        if user in self.users:
            raise ValueError(f"user {user!r} already registered")
        statement.validate()
        self.users[user] = statement

    def issue_challenge(self, user: str) -> bytes:
        challenge_id = secrets.token_bytes(32)
        self.challenges[challenge_id] = user
        return challenge_id

    def verify_login(self, user: str, challenge_id: bytes, wire: bytes) -> bool:
        # consume-once BEFORE verification: a replayed id is already gone
        owner = self.challenges.pop(challenge_id, None)
        if owner != user or user not in self.users:
            return False
        try:
            proof = Proof.from_bytes(wire)
            transcript = Transcript()
            transcript.append_context(challenge_id)
            Verifier(self.params, self.users[user]).verify_with_transcript(
                proof, transcript
            )
            return True
        except Error:
            return False


def login(server: TinyAuthServer, user: str, password: str, rng: SecureRng) -> tuple[bytes, bytes]:
    """Client side: challenge -> proof bound to it. Returns (cid, wire)."""
    x = password_to_scalar(password, user)
    prover = Prover(server.params, Witness(x))
    challenge_id = server.issue_challenge(user)
    transcript = Transcript()
    transcript.append_context(challenge_id)
    proof = prover.prove_with_transcript(rng, transcript)
    return challenge_id, proof.to_bytes()


def main() -> None:
    rng = SecureRng()
    server = TinyAuthServer()

    # --- registration: the server only ever sees the public statement
    x = password_to_scalar("correct horse battery staple", "alice")
    statement = Prover(server.params, Witness(x)).statement
    server.register("alice", statement)
    print("registered alice (server stores y1, y2 — never the password)")

    # --- successful login
    cid, wire = login(server, "alice", "correct horse battery staple", rng)
    assert server.verify_login("alice", cid, wire)
    print("login ok: correct password produces an accepted proof")

    # --- attack 1: replaying the captured proof fails (challenge consumed)
    assert not server.verify_login("alice", cid, wire)
    print("replay defeated: the challenge is single-use")

    # --- attack 2: wrong password fails verification
    cid2, wire2 = login(server, "alice", "hunter2", rng)
    assert not server.verify_login("alice", cid2, wire2)
    print("wrong secret defeated: proof does not match the registered statement")

    # --- attack 3: proof for one challenge cannot answer another
    cid3, wire3 = login(server, "alice", "correct horse battery staple", rng)
    cid4 = server.issue_challenge("alice")
    assert not server.verify_login("alice", cid4, wire3)
    del cid3
    print("context binding holds: a proof answers exactly one challenge")


if __name__ == "__main__":
    main()
