"""Batch verification with self-timed speedup numbers.

Didactic twin of the reference's ``examples/batch_verification.rs``
(59-104, the timing comparison) — with one honest difference: the
reference's batch equation has a coefficient bug that silently forces
per-proof fallback, so its printed "speedup" never came from the batch
path (SURVEY.md §3.2).  This framework implements the corrected
random-linear-combination check, so the speedup below is real.

By default times the host CPU backend; pass --tpu to also time the JAX
data plane (add --platform cpu to smoke-run it without a TPU).

Run: python examples/batch_verification.py [--n 32] [--tpu [--platform cpu]]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cpzk_tpu import (  # noqa: E402
    BatchVerifier,
    Parameters,
    Prover,
    SecureRng,
    Transcript,
    Verifier,
    Witness,
)
from cpzk_tpu.core.ristretto import Ristretto255  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=32)
    ap.add_argument("--tpu", action="store_true")
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()

    rng = SecureRng()
    params = Parameters.new()

    print(f"generating {args.n} proofs...")
    rows = []
    for i in range(args.n):
        prover = Prover(params, Witness(Ristretto255.random_scalar(rng)))
        ctx = f"batch-demo-{i}".encode()
        t = Transcript()
        t.append_context(ctx)
        rows.append((prover.statement, prover.prove_with_transcript(rng, t), ctx))

    # individual verification
    t0 = time.perf_counter()
    for st, pr, ctx in rows:
        t = Transcript()
        t.append_context(ctx)
        Verifier(params, st).verify_with_transcript(pr, t)
    individual = time.perf_counter() - t0
    print(f"individual: {individual * 1e3:7.1f} ms "
          f"({individual / args.n * 1e6:6.0f} us/proof)")

    def batch_with(backend, label):
        bv = BatchVerifier(backend=backend)
        for st, pr, ctx in rows:
            bv.add_with_context(params, st, pr, ctx)
        t0 = time.perf_counter()
        results = bv.verify(rng)
        dt = time.perf_counter() - t0
        assert results == [None] * args.n
        speedup = individual / dt
        print(f"{label}: {dt * 1e3:7.1f} ms "
              f"({dt / args.n * 1e6:6.0f} us/proof, {speedup:4.1f}x vs individual)")

    batch_with(None, "batch[cpu] ")

    if args.tpu:
        if args.platform:
            import jax

            jax.config.update("jax_platforms", args.platform)
        from cpzk_tpu.ops.backend import TpuBackend

        backend = TpuBackend()
        # warm the jit cache so the timing shows steady-state throughput
        warm = BatchVerifier(backend=backend)
        for st, pr, ctx in rows:
            warm.add_with_context(params, st, pr, ctx)
        warm.verify(rng)
        batch_with(backend, "batch[tpu] ")

    # a corrupted batch still reports per-proof results
    bad = BatchVerifier()
    for st, pr, ctx in rows[:-1]:
        bad.add_with_context(params, st, pr, ctx)
    bad.add_with_context(params, rows[0][0], rows[1][1], rows[0][2])
    results = bad.verify(rng)
    n_ok = sum(r is None for r in results)
    print(f"mixed batch: {n_ok}/{args.n} accepted, "
          f"bad proof rejected at index {args.n - 1}")


if __name__ == "__main__":
    main()
