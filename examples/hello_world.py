"""Hello, Chaum-Pedersen: prove knowledge of a secret and verify it.

Didactic twin of the reference's ``examples/hello_world.rs`` (1-59): create
a witness, derive the public statement, produce a non-interactive proof,
round-trip it through the 109-byte wire format, and verify.

Run: python examples/hello_world.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cpzk_tpu import (  # noqa: E402
    Parameters,
    Proof,
    Prover,
    SecureRng,
    Transcript,
    Verifier,
    Witness,
)
from cpzk_tpu.core.ristretto import Ristretto255  # noqa: E402


def main() -> None:
    rng = SecureRng()

    # 1. Public parameters: the two independent group generators (g, h).
    params = Parameters.new()

    # 2. The prover's secret x and its public statement (y1, y2) = (g^x, h^x).
    witness = Witness(Ristretto255.random_scalar(rng))
    prover = Prover(params, witness)
    statement = prover.statement
    print("statement y1:", Ristretto255.element_to_bytes(statement.y1).hex())
    print("statement y2:", Ristretto255.element_to_bytes(statement.y2).hex())

    # 3. Non-interactive proof via the Fiat-Shamir transcript.
    proof = prover.prove_with_transcript(rng, Transcript())
    wire = proof.to_bytes()
    print(f"proof: {len(wire)} bytes on the wire")

    # 4. Anyone holding the statement can verify the proof.
    verifier = Verifier(params, statement)
    verifier.verify_with_transcript(Proof.from_bytes(wire), Transcript())
    print("proof verified: the prover knows x with y1 = g^x AND y2 = h^x")
    print("...without revealing x.")


if __name__ == "__main__":
    main()
