"""Multi-chip parallelism: mesh construction and sharded batch verification.

The reference is a single-process CPU program (SURVEY.md §2.3); its only
scaling axis is proof-batch size. The TPU-native analog shards that batch
axis across a ``jax.sharding.Mesh`` — per-chip partial work runs locally,
and the combined-check reduction rides ICI collectives (``psum`` under
``shard_map``), never DCN, matching the scaling-book recipe.

Re-exports resolve lazily: importing this package must NOT initialize the
XLA backend (``ops.limbs`` materializes device constants at import), or
``jax.distributed.initialize`` — which must run before any backend use —
could never be called after ``import cpzk_tpu.parallel``.
"""

from . import multihost

__all__ = [
    "multihost",
    "batch_mesh",
    "make_sharded_combined_check",
    "make_sharded_msm_check",
    "make_sharded_prove",
    "make_sharded_verify_each",
    "resolve_lane_devices",
    "resolve_mesh_devices",
    "sharded_combined_check",
    "sharded_msm_check",
    "sharded_prove",
    "sharded_verify_each",
]

_MESH_NAMES = frozenset(__all__) - {"multihost"}


def __getattr__(name: str):
    if name in _MESH_NAMES:
        from . import mesh

        return getattr(mesh, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
