"""Multi-chip parallelism: mesh construction and sharded batch verification.

The reference is a single-process CPU program (SURVEY.md §2.3); its only
scaling axis is proof-batch size. The TPU-native analog shards that batch
axis across a ``jax.sharding.Mesh`` — per-chip partial work runs locally,
and the combined-check reduction rides ICI collectives (``psum`` under
``shard_map``), never DCN, matching the scaling-book recipe.
"""

from . import multihost
from .mesh import (
    batch_mesh,
    make_sharded_combined_check,
    make_sharded_msm_check,
    make_sharded_verify_each,
    sharded_combined_check,
    sharded_msm_check,
    sharded_verify_each,
)

__all__ = [
    "multihost",
    "batch_mesh",
    "make_sharded_combined_check",
    "make_sharded_msm_check",
    "make_sharded_verify_each",
    "sharded_combined_check",
    "sharded_msm_check",
    "sharded_verify_each",
]
