"""Mesh-sharded batch verification (shard_map + ICI collectives).

Design (SURVEY.md §2.3, §5 long-context entry): proofs are embarrassingly
parallel along the batch axis, so every row array (`[n, ...]` points and
`[n, 64]` scalar windows) is sharded over a 1-D device mesh. The per-proof
kernel needs no communication at all; the combined RLC check reduces each
device's shard to one partial point locally, then combines the ``D`` partial
points with one tiny cross-device gather — the multi-chip analog of the
reference's accumulation loop at ``src/verifier/batch.rs:271-312``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ..ops import curve, verify

AXIS = "batch"


def batch_mesh(devices=None) -> Mesh:
    """1-D data-parallel mesh over all (or the given) devices."""
    if devices is None:
        devices = jax.devices()
    import numpy as np

    return Mesh(np.asarray(devices), (AXIS,))


def _point_specs(spec):
    return (spec, spec, spec, spec)


def sharded_verify_each(mesh: Mesh, g, h, y1, y2, r1, r2, ws, wc):
    """Per-proof checks over a batch-sharded mesh -> [n] bool.

    ``g``/``h`` unbatched (replicated); row arrays sharded on axis 0.
    Batch size must be divisible by the mesh size (pad with identity rows
    and zero windows; padded rows verify True).
    """
    rows = P(AXIS)
    rep = P()
    fn = shard_map(
        verify.verify_each_kernel,
        mesh=mesh,
        in_specs=(
            _point_specs(rep),
            _point_specs(rep),
            _point_specs(rows),
            _point_specs(rows),
            _point_specs(rows),
            _point_specs(rows),
            rows,
            rows,
        ),
        out_specs=rows,
        check_rep=False,
    )
    return jax.jit(fn)(g, h, y1, y2, r1, r2, ws, wc)


def _combined_partial(r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac):
    rows = verify._msm_rows(
        [
            verify.build_table(r1),
            verify.build_table(y1),
            verify.build_table(r2),
            verify.build_table(y2),
        ],
        [w_a, w_ac, w_ba, w_bac],
    )
    partial = curve.tree_sum(rows, axis=0)
    return tuple(c[None] for c in partial)  # [1, 20] per device


def sharded_combined_check(mesh: Mesh, r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac):
    """Combined RLC check over a batch-sharded mesh -> scalar bool.

    Each device reduces its shard to one partial point (local tree-sum);
    the ``D`` partials are then combined and tested against the identity.
    The caller has already appended the ``(-sum a s) G + (-b sum a s) H``
    correction row (see :meth:`cpzk_tpu.ops.backend.TpuBackend.verify_combined`).
    """
    rows = P(AXIS)
    partial_fn = shard_map(
        _combined_partial,
        mesh=mesh,
        in_specs=(
            _point_specs(rows),
            _point_specs(rows),
            _point_specs(rows),
            _point_specs(rows),
            rows,
            rows,
            rows,
            rows,
        ),
        out_specs=_point_specs(P(AXIS)),
        check_rep=False,
    )

    def check(*args):
        partials = partial_fn(*args)  # [D, 20] coords, one row per device
        total = curve.tree_sum(partials, axis=0)
        return curve.is_identity(total)

    return jax.jit(check)(r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)
