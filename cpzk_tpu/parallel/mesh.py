"""Mesh-sharded batch verification (shard_map + ICI collectives).

Design (SURVEY.md §2.3, §5 long-context entry): proofs are embarrassingly
parallel along the batch axis, so every row array ([20, n] limb-major point
coords and [64, n] scalar windows — batch rides the minor axis / vector
lanes) is sharded over a 1-D device mesh along that batch axis.  The
per-proof kernel needs no communication at all; the combined RLC check
reduces each device's shard to one partial point locally, then combines the
``D`` partial points with one tiny cross-device gather — the multi-chip
analog of the reference's accumulation loop at
``src/verifier/batch.rs:271-312``.

``pad_to_multiple`` handles ragged batches here (instead of at every call
site): identity points with zero windows are verified-true rows in the
per-proof kernel and contribute the identity to the combined sum.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8 top-level API (check_vma); experimental kept for older jax
    from jax import shard_map as _new_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False):
        return _new_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_rep,
        )
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..ops import curve, msm, verify

AXIS = "batch"


def batch_mesh(devices=None) -> Mesh:
    """1-D data-parallel mesh over all (or the given) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (AXIS,))


def resolve_mesh_devices(mesh_devices: int | None):
    """The shared ``mesh_devices`` convention: ``None`` -> no mesh
    (single-device), ``0`` -> all visible devices, ``k`` -> the first k.
    Returns a device list when a real (>1) mesh should be built, else
    None — one policy for every mesh-capable component (TpuBackend,
    BatchProver, the serving lane router).

    Asking for more devices than exist is a deployment error, not a
    preference: it used to clamp silently, so a config written for an
    8-chip host "worked" on a 1-chip box at 1/8 the capacity with no
    signal.  Rejected loudly instead."""
    if mesh_devices is None:
        return None
    n_avail = jax.device_count()
    if mesh_devices > n_avail:
        raise ValueError(
            f"mesh_devices={mesh_devices} exceeds the {n_avail} visible "
            f"jax device(s) on this host — fix the topology knob or the "
            "deployment (a silent clamp would serve at a fraction of the "
            "configured capacity)"
        )
    want = n_avail if mesh_devices == 0 else mesh_devices
    if want <= 1:
        return None
    return jax.devices()[:want]


def resolve_lane_devices(lanes: int):
    """Lane-count discovery for the per-device serving plane (``[tpu]
    lanes``): ``1`` -> None (the single-lane fast path, today's
    behavior), ``-1`` -> one lane per local device, ``k > 1`` -> the
    first k local devices (rejected when k exceeds the local count, same
    policy as :func:`resolve_mesh_devices`).  Returns a device list only
    when a real multi-lane router should be built."""
    if lanes == 1:
        return None
    if lanes == -1:
        devices = jax.local_devices()
        return devices if len(devices) > 1 else None
    n_local = jax.local_device_count()
    if lanes > n_local:
        raise ValueError(
            f"lanes={lanes} exceeds the {n_local} local jax device(s) on "
            "this host — one dispatch lane pins one local chip"
        )
    return jax.local_devices()[:lanes]


def pad_to_multiple(pt: curve.Point, n_to: int) -> curve.Point:
    """Pad a [20, n] point SoA with identity rows up to n_to lanes."""
    n = pt[0].shape[-1]
    if n == n_to:
        return pt
    pad = curve.identity((n_to - n,))
    return tuple(jnp.concatenate([c, pc], axis=-1) for c, pc in zip(pt, pad))


def pad_windows(w: jnp.ndarray, n_to: int) -> jnp.ndarray:
    """Pad a [64, n] window array with zero-scalar lanes up to n_to."""
    n = w.shape[-1]
    if n == n_to:
        return w
    return jnp.concatenate(
        [w, jnp.zeros(w.shape[:-1] + (n_to - n,), dtype=w.dtype)], axis=-1
    )


def _mesh_step(d: int, n: int) -> tuple[int, int]:
    """(step, n_to): the per-slice lane count d*LANE_CHUNK that keeps every
    per-device program at or under the TPU large-lane miscompile bound
    (ops/backend.py LANE_CHUNK), and the padded total.  Single source for
    all three sharded wrappers.

    Padding is a d-multiple in BOTH regimes (ROADMAP item 2 fix): below
    one step, the next d-multiple; above, each device's lane count is
    rounded up to a LANE_QUANTUM multiple instead of a full LANE_CHUNK —
    the old full-step rounding burned up to d*LANE_CHUNK-1 identity lanes
    (2x device work at one-past-a-step sizes, e.g. 140k rows on 8 chips
    padded 262,144 instead of 147,456).  The remainder slice is shorter
    than ``step`` but stays a d-multiple with quantum-aligned per-device
    programs, so the jit cache stays bounded exactly like the
    single-device remainder-chunk schedule."""
    from ..ops import backend as _backend  # lazy: no import cycle

    step = d * _backend.LANE_CHUNK
    if n <= step:
        n_to = -(-n // d) * d
    else:
        q = min(_backend.LANE_QUANTUM, _backend.LANE_CHUNK)
        per_device = -(-n // d)               # ceil lanes per device
        per_device = -(-per_device // q) * q  # quantum-align its program
        n_to = per_device * d
    _note_occupancy(n, n_to)
    return step, n_to


def _note_occupancy(n: int, n_to: int) -> None:
    """Mesh lane-occupancy telemetry (``tpu.batch.occupancy``): true rows
    over padded mesh lanes.  Metrics live in the server layer; this
    module stays importable without it."""
    try:
        from ..server import metrics

        metrics.gauge("tpu.batch.occupancy").set(n / n_to if n_to else 1.0)
    except Exception:  # pragma: no cover - server layer unavailable
        pass


def _point_specs(spec):
    return (spec, spec, spec, spec)


def _row_spec():
    # [20, n] coords / [64, n] windows: shard the minor (batch) axis
    return P(None, AXIS)


def make_sharded_verify_each(mesh: Mesh):
    """Reusable (jit-cached) sharded per-proof checker for ``mesh``.

    Returns ``call(g, h, y1, y2, r1, r2, ws, wc) -> [n] bool``; ``g``/``h``
    [20, 1] (replicated), row arrays sharded on the batch axis.  Ragged
    batches are padded to a mesh-size multiple (identity rows with zero
    windows verify True and are sliced off the result).
    """
    rows = _row_spec()
    rep = P()
    fn = jax.jit(
        shard_map(
            verify.verify_each_kernel,
            mesh=mesh,
            in_specs=(
                _point_specs(rep),
                _point_specs(rep),
                _point_specs(rows),
                _point_specs(rows),
                _point_specs(rows),
                _point_specs(rows),
                rows,
                rows,
            ),
            out_specs=P(AXIS),
            check_rep=False,
        )
    )
    d = mesh.devices.size

    def call(g, h, y1, y2, r1, r2, ws, wc):
        n = ws.shape[-1]
        step, n_to = _mesh_step(d, n)
        y1, y2, r1, r2 = (pad_to_multiple(p, n_to) for p in (y1, y2, r1, r2))
        ws, wc = pad_windows(ws, n_to), pad_windows(wc, n_to)
        if n_to <= step:
            return fn(g, h, y1, y2, r1, r2, ws, wc)[:n]
        chunks = []
        for lo in range(0, n_to, step):
            # the last slice may be a short (but d-multiple) remainder
            hi = min(lo + step, n_to)
            chunks.append(fn(
                g, h,
                *(tuple(c[..., lo:hi] for c in p) for p in (y1, y2, r1, r2)),
                ws[:, lo:hi], wc[:, lo:hi]))
        return jnp.concatenate(chunks, axis=-1)[:n]

    return call


def sharded_verify_each(mesh: Mesh, g, h, y1, y2, r1, r2, ws, wc):
    """One-shot convenience wrapper over :func:`make_sharded_verify_each`."""
    return make_sharded_verify_each(mesh)(g, h, y1, y2, r1, r2, ws, wc)


def make_sharded_prove(mesh: Mesh):
    """Sharded bulk commitment generation — the proving-side DP shard
    (BASELINE config 3 at mesh scale; reference analog
    ``prover/mod.rs:115-121``).  Comb tables are replicated, the digit
    batch axis is sharded, and because proofs are independent there are
    NO collectives: pure data parallelism over the mesh.

    Returns ``call(tables_g, tables_h, digits) -> (r1_bytes, r2_bytes)``
    with digits [64, n] (LSB window first) and [32, n] wire-byte outputs.
    Ragged batches pad with zero-digit lanes (identity commitments,
    sliced off)."""
    from ..ops import prove as prove_mod

    rows = _row_spec()
    fn = jax.jit(
        shard_map(
            prove_mod._commitments_kernel.__wrapped__,
            mesh=mesh,
            in_specs=(_point_specs(P()), _point_specs(P()), rows),
            out_specs=(rows, rows),
            check_rep=False,
        )
    )
    d = mesh.devices.size

    def call(tg, th, digits):
        n = digits.shape[-1]
        # proofs are independent, so over-cap batches run as mesh slices
        step, n_to = _mesh_step(d, n)
        digits = pad_windows(digits, n_to)
        if n_to <= step:
            b1, b2 = fn(tg, th, digits)
            return b1[:, :n], b2[:, :n]
        parts = [fn(tg, th, digits[:, lo:min(lo + step, n_to)])
                 for lo in range(0, n_to, step)]
        b1 = jnp.concatenate([p[0] for p in parts], axis=-1)
        b2 = jnp.concatenate([p[1] for p in parts], axis=-1)
        return b1[:, :n], b2[:, :n]

    return call


def sharded_prove(mesh: Mesh, tg, th, digits):
    """One-shot convenience wrapper over :func:`make_sharded_prove`."""
    return make_sharded_prove(mesh)(tg, th, digits)


def _combined_partial(r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac):
    rows = verify._msm_rows(
        [
            verify.build_table(r1),
            verify.build_table(y1),
            verify.build_table(r2),
            verify.build_table(y2),
        ],
        [w_a, w_ac, w_ba, w_bac],
    )
    partial = curve.tree_sum(rows, axis=-1)
    return tuple(c[:, None] for c in partial)  # [20, 1] per device


def make_sharded_combined_check(mesh: Mesh):
    """Reusable (jit-cached) sharded combined-RLC checker for ``mesh``.

    Each device reduces its shard to one partial point (local tree-sum);
    the ``D`` partials are then combined and tested against the identity.
    The caller has already appended the ``(-sum a s) G + (-b sum a s) H``
    correction row (see :meth:`cpzk_tpu.ops.backend.TpuBackend.verify_combined`);
    ragged batches are padded to a mesh-size multiple (identity rows with
    zero windows contribute the identity to the sum).
    """
    rows = _row_spec()
    partial_fn = shard_map(
        _combined_partial,
        mesh=mesh,
        in_specs=(
            _point_specs(rows),
            _point_specs(rows),
            _point_specs(rows),
            _point_specs(rows),
            rows,
            rows,
            rows,
            rows,
        ),
        out_specs=_point_specs(P(None, AXIS)),
        check_rep=False,
    )

    def check(*args):
        partials = partial_fn(*args)  # [20, D] coords, one lane per device
        total = curve.tree_sum(partials, axis=-1)
        return curve.is_identity(total)

    jcheck = jax.jit(check)
    d = mesh.devices.size

    def call(r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac):
        n = w_a.shape[-1]
        n_to = -(-n // d) * d
        r1, y1, r2, y2 = (pad_to_multiple(p, n_to) for p in (r1, y1, r2, y2))
        w_a, w_ac, w_ba, w_bac = (
            pad_windows(w, n_to) for w in (w_a, w_ac, w_ba, w_bac)
        )
        return jcheck(r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)

    return call


def sharded_combined_check(mesh: Mesh, r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac):
    """One-shot convenience wrapper over :func:`make_sharded_combined_check`."""
    return make_sharded_combined_check(mesh)(r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)


def make_sharded_msm_check(mesh: Mesh):
    """Reusable sharded Pippenger-MSM == identity checker for ``mesh``.

    An MSM is a sum over (point, scalar) terms, so lane-sharding is exact:
    each device runs the full windowed-Pippenger kernel on its shard of the
    terms ([20, m/D] coords + [K, m/D] digits), producing one partial point;
    the ``D`` partials combine with one tiny cross-device gather — the ICI
    traffic is 4 coords x 20 limbs per device per batch, nothing else.

    Returns ``call(points, digits, c) -> scalar bool`` (``c`` static per
    compiled variant, cached by window size).
    """
    rows = _row_spec()
    d = mesh.devices.size
    cache: dict[int, object] = {}

    def build(c: int):
        def partial(points, digits):
            return msm.msm_kernel(points, digits, c)  # [20, 1] per device

        fn = shard_map(
            partial,
            mesh=mesh,
            in_specs=(_point_specs(rows), rows),
            out_specs=_point_specs(P(None, AXIS)),
            check_rep=False,
        )
        return jax.jit(fn)  # (points, digits) -> [20, D] partial points

    def call(points, digits, c: int):
        from ..ops import backend as _backend  # lazy: no import cycle

        m = digits.shape[-1]
        # over-cap MSMs run as mesh slices whose [20, D] partials
        # concatenate into one final tree-sum + identity test
        step, m_to = _mesh_step(d, m)
        points = pad_to_multiple(points, m_to)
        digits = pad_windows(digits, m_to)
        if c not in cache:
            cache[c] = build(c)
        fn = cache[c]
        if m_to <= step:
            partials = fn(points, digits)
        else:
            parts = [
                fn(tuple(cd[..., lo:hi] for cd in points), digits[:, lo:hi])
                for lo, hi in (
                    (lo, min(lo + step, m_to)) for lo in range(0, m_to, step))
            ]
            partials = _backend._stack_partials(parts)
        return _backend._partials_are_identity(partials)

    return call


def sharded_msm_check(mesh: Mesh, points, digits, c: int):
    """One-shot convenience wrapper over :func:`make_sharded_msm_check`."""
    return make_sharded_msm_check(mesh)(points, digits, c)
