"""Mesh-sharded batch verification (shard_map + ICI collectives).

Design (SURVEY.md §2.3, §5 long-context entry): proofs are embarrassingly
parallel along the batch axis, so every row array ([20, n] limb-major point
coords and [64, n] scalar windows — batch rides the minor axis / vector
lanes) is sharded over a 1-D device mesh along that batch axis.  The
per-proof kernel needs no communication at all; the combined RLC check
reduces each device's shard to one partial point locally, then combines the
``D`` partial points with one tiny cross-device gather — the multi-chip
analog of the reference's accumulation loop at
``src/verifier/batch.rs:271-312``.

``pad_to_multiple`` handles ragged batches here (instead of at every call
site): identity points with zero windows are verified-true rows in the
per-proof kernel and contribute the identity to the combined sum.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ..ops import curve, verify

AXIS = "batch"


def batch_mesh(devices=None) -> Mesh:
    """1-D data-parallel mesh over all (or the given) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (AXIS,))


def pad_to_multiple(pt: curve.Point, n_to: int) -> curve.Point:
    """Pad a [20, n] point SoA with identity rows up to n_to lanes."""
    n = pt[0].shape[-1]
    if n == n_to:
        return pt
    pad = curve.identity((n_to - n,))
    return tuple(jnp.concatenate([c, pc], axis=-1) for c, pc in zip(pt, pad))


def pad_windows(w: jnp.ndarray, n_to: int) -> jnp.ndarray:
    """Pad a [64, n] window array with zero-scalar lanes up to n_to."""
    n = w.shape[-1]
    if n == n_to:
        return w
    return jnp.concatenate(
        [w, jnp.zeros(w.shape[:-1] + (n_to - n,), dtype=w.dtype)], axis=-1
    )


def _point_specs(spec):
    return (spec, spec, spec, spec)


def _row_spec():
    # [20, n] coords / [64, n] windows: shard the minor (batch) axis
    return P(None, AXIS)


def sharded_verify_each(mesh: Mesh, g, h, y1, y2, r1, r2, ws, wc):
    """Per-proof checks over a batch-sharded mesh -> [n] bool.

    ``g``/``h`` [20, 1] (replicated); row arrays sharded on the batch axis.
    Ragged batches are padded here to a mesh-size multiple (identity rows
    with zero windows verify True and are sliced off the result).
    """
    n = ws.shape[-1]
    d = mesh.devices.size
    n_to = -(-n // d) * d
    y1, y2, r1, r2 = (pad_to_multiple(p, n_to) for p in (y1, y2, r1, r2))
    ws, wc = pad_windows(ws, n_to), pad_windows(wc, n_to)

    rows = _row_spec()
    rep = P()
    fn = shard_map(
        verify.verify_each_kernel,
        mesh=mesh,
        in_specs=(
            _point_specs(rep),
            _point_specs(rep),
            _point_specs(rows),
            _point_specs(rows),
            _point_specs(rows),
            _point_specs(rows),
            rows,
            rows,
        ),
        out_specs=P(AXIS),
        check_rep=False,
    )
    return jax.jit(fn)(g, h, y1, y2, r1, r2, ws, wc)[:n]


def _combined_partial(r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac):
    rows = verify._msm_rows(
        [
            verify.build_table(r1),
            verify.build_table(y1),
            verify.build_table(r2),
            verify.build_table(y2),
        ],
        [w_a, w_ac, w_ba, w_bac],
    )
    partial = curve.tree_sum(rows, axis=-1)
    return tuple(c[:, None] for c in partial)  # [20, 1] per device


def sharded_combined_check(mesh: Mesh, r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac):
    """Combined RLC check over a batch-sharded mesh -> scalar bool.

    Each device reduces its shard to one partial point (local tree-sum);
    the ``D`` partials are then combined and tested against the identity.
    The caller has already appended the ``(-sum a s) G + (-b sum a s) H``
    correction row (see :meth:`cpzk_tpu.ops.backend.TpuBackend.verify_combined`);
    ragged batches are padded here to a mesh-size multiple (identity rows
    with zero windows contribute the identity to the sum).
    """
    n = w_a.shape[-1]
    d = mesh.devices.size
    n_to = -(-n // d) * d
    r1, y1, r2, y2 = (pad_to_multiple(p, n_to) for p in (r1, y1, r2, y2))
    w_a, w_ac, w_ba, w_bac = (pad_windows(w, n_to) for w in (w_a, w_ac, w_ba, w_bac))

    rows = _row_spec()
    partial_fn = shard_map(
        _combined_partial,
        mesh=mesh,
        in_specs=(
            _point_specs(rows),
            _point_specs(rows),
            _point_specs(rows),
            _point_specs(rows),
            rows,
            rows,
            rows,
            rows,
        ),
        out_specs=_point_specs(P(None, AXIS)),
        check_rep=False,
    )

    def check(*args):
        partials = partial_fn(*args)  # [20, D] coords, one lane per device
        total = curve.tree_sum(partials, axis=-1)
        return curve.is_identity(total)

    return jax.jit(check)(r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)
