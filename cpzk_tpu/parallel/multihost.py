"""Multi-host scale-out: ``jax.distributed`` + a global batch mesh.

The reference is a single-process program; its only scaling axis is batch
size (SURVEY.md §2.3).  This module is the TPU-native multi-host analog of
an NCCL/MPI world: every host runs the same program, ``initialize`` wires
the jax.distributed coordinator (DCN), and ``global_batch_mesh`` returns a
1-D mesh over ALL devices in the job — per-chip partial reductions ride ICI
within a host/pod slice, and only the tiny per-device partial points cross
DCN during the final combine (see :mod:`cpzk_tpu.parallel.mesh`).

Typical deployment (one process per host):

    from cpzk_tpu.parallel import multihost
    multihost.initialize(coordinator="10.0.0.1:8476",
                         num_processes=4, process_id=HOST_INDEX)
    mesh = multihost.global_batch_mesh()
    backend = TpuBackend()            # sees the global device set
    ...

Single-process jobs may call these unconditionally: ``initialize`` is a
no-op when num_processes == 1, so the same binary runs laptop -> pod.
"""

from __future__ import annotations

import logging
import os

import jax

log = logging.getLogger("cpzk_tpu.parallel.multihost")

_initialized = False


def initialize(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the distributed job; no-op for single-process development.

    Arguments default from the env vars ``CPZK_COORDINATOR`` /
    ``CPZK_NUM_PROCESSES`` / ``CPZK_PROCESS_ID``.  Multi-host mode engages
    when ANY of those (or ``CPZK_MULTIHOST=1``) is present — values left
    ``None`` are passed through to ``jax.distributed.initialize`` so its
    own auto-detection fills them in on managed TPU pods.  With no
    configuration at all this is a no-op (dev/single-host default).
    Repeat calls after a real join are rejected loudly.
    """
    global _initialized
    coordinator = coordinator or os.environ.get("CPZK_COORDINATOR")
    if num_processes is None and (v := os.environ.get("CPZK_NUM_PROCESSES")):
        num_processes = int(v)
    if process_id is None and (v := os.environ.get("CPZK_PROCESS_ID")):
        process_id = int(v)
    explicit = (
        coordinator is not None
        or num_processes is not None
        or process_id is not None
        or os.environ.get("CPZK_MULTIHOST", "") in ("1", "true", "on")
    )
    if _initialized:
        if explicit:
            raise RuntimeError(
                "multihost.initialize called again after the job was formed; "
                "configure the coordinator once, before any device use"
            )
        return
    if not explicit:
        return  # single-process development: nothing to form, not latched
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    log.info(
        "joined distributed job: process %d/%d, %d global devices",
        jax.process_index(), jax.process_count(), jax.device_count(),
    )


def global_batch_mesh():
    """1-D batch mesh over every device in the (possibly multi-host) job.

    The mesh module import is deferred: it materializes device constants,
    which would initialize the backend — and :func:`initialize` must be
    able to run first.
    """
    from .mesh import batch_mesh

    return batch_mesh(jax.devices())


def process_info() -> tuple[int, int]:
    """(process_index, process_count) of this host in the job."""
    return jax.process_index(), jax.process_count()
