"""Multi-host scale-out: ``jax.distributed`` + a global batch mesh.

The reference is a single-process program; its only scaling axis is batch
size (SURVEY.md §2.3).  This module is the TPU-native multi-host analog of
an NCCL/MPI world: every host runs the same program, ``initialize`` wires
the jax.distributed coordinator (DCN), and ``global_batch_mesh`` returns a
1-D mesh over ALL devices in the job — per-chip partial reductions ride ICI
within a host/pod slice, and only the tiny per-device partial points cross
DCN during the final combine (see :mod:`cpzk_tpu.parallel.mesh`).

Typical deployment (one process per host):

    from cpzk_tpu.parallel import multihost
    multihost.initialize(coordinator="10.0.0.1:8476",
                         num_processes=4, process_id=HOST_INDEX)
    mesh = multihost.global_batch_mesh()
    backend = TpuBackend()            # sees the global device set
    ...

Single-process jobs may call these unconditionally: ``initialize`` is a
no-op when num_processes == 1, so the same binary runs laptop -> pod.
"""

from __future__ import annotations

import logging
import os

import jax

from .mesh import batch_mesh

log = logging.getLogger("cpzk_tpu.parallel.multihost")

_initialized = False


def initialize(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join (or trivially form) the distributed job.

    Arguments default from the standard env vars
    (``CPZK_COORDINATOR`` / ``CPZK_NUM_PROCESSES`` / ``CPZK_PROCESS_ID``,
    falling back to jax's own auto-detection on managed TPU pods).
    No-op for single-process jobs and on repeat calls.
    """
    global _initialized
    if _initialized:
        return
    coordinator = coordinator or os.environ.get("CPZK_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("CPZK_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("CPZK_PROCESS_ID", "0"))
    if num_processes <= 1 and coordinator is None:
        _initialized = True
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    log.info(
        "joined distributed job: process %d/%d, %d global devices",
        process_id, num_processes, jax.device_count(),
    )


def global_batch_mesh():
    """1-D batch mesh over every device in the (possibly multi-host) job."""
    return batch_mesh(jax.devices())


def process_info() -> tuple[int, int]:
    """(process_index, process_count) of this host in the job."""
    return jax.process_index(), jax.process_count()
