"""Replicated server state (ISSUE 8): WAL segment shipping to a warm
standby and lease-based promotion, built on the PR-3 durability subsystem
and the sharded :class:`~cpzk_tpu.server.state.ServerState`.

- :mod:`.segments` — sealed, CRC-checked WAL slices (the shipping unit);
- :mod:`.shipper` — primary side: tail-follow the WAL, ship segments,
  renew the lease, sync-mode acknowledgement barrier, fencing detection;
- :mod:`.standby` — standby side: validate + replay through the
  ``replay_journal_record`` trust boundary, lease watch, promotion,
  epoch fencing;
- :mod:`.wire` — hand-wired gRPC plumbing for ``proto/replication.proto``.

See ``docs/operations.md`` §"Replication & failover" for the topology,
the promotion runbook, and the loss-window table.
"""

from .segments import Segment, seal_segment, split_records, validate_segment
from .shipper import HandoverError, ReplicationTimeout, SegmentShipper
from .standby import SegmentApplier, StandbyReplica, load_epoch, store_epoch

__all__ = [
    "Segment",
    "seal_segment",
    "split_records",
    "validate_segment",
    "SegmentShipper",
    "ReplicationTimeout",
    "HandoverError",
    "SegmentApplier",
    "StandbyReplica",
    "load_epoch",
    "store_epoch",
]
