"""WAL segments: sealed, CRC-checked slices of the write-ahead log.

The replication unit (ISSUE 8): the primary chops the PR-3 write-ahead
log's framed-record stream into segments — a contiguous run of records
``[first_seq, last_seq]`` in the WAL's own wire format, sealed with a
CRC32 over the whole blob — and ships them to the warm standby.  A
segment is *sealed* once it reaches the configured size; the short run
at the head of the active log ships unsealed as a tail-follow delta
(same validation, it just signals "more of this is coming").

Validation on receipt is strict and total (the fuzz harness holds it as
an invariant): a segment either parses into exactly the records its
header claims, or it is rejected whole — a torn or bit-flipped segment
can never partially apply.  Duplicate and overlapping deliveries are
idempotent (records at or below the standby's applied sequence number
are skipped); a gap is refused so prefix-stability is preserved.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass

from ..durability.wal import encode_record, iter_frames

#: Sanity cap on one segment's frames blob (a garbage length field must
#: not make the standby buffer gigabytes): the largest sealed segment a
#: sane config produces is segment_bytes + one frame.
MAX_SEGMENT_BYTES = 8 * (1 << 20)


@dataclass
class Segment:
    """One shipped slice of the WAL (see module docstring)."""

    epoch: int
    index: int
    first_seq: int
    last_seq: int
    frames: bytes
    crc: int
    sealed: bool = True


def seal_segment(
    epoch: int, index: int, records: list[dict], sealed: bool = True
) -> Segment:
    """Frame ``records`` (already carrying ``seq``/``type``) into one
    sealed segment.  Re-encoding is canonical (compact, key-sorted JSON),
    so frames built here are byte-identical to the primary's log."""
    if not records:
        raise ValueError("a segment must carry at least one record")
    frames = b"".join(encode_record(r) for r in records)
    return Segment(
        epoch=epoch,
        index=index,
        first_seq=int(records[0]["seq"]),
        last_seq=int(records[-1]["seq"]),
        frames=frames,
        crc=zlib.crc32(frames) & 0xFFFFFFFF,
        sealed=sealed,
    )


def split_records(
    records: list[dict], epoch: int, first_index: int, segment_bytes: int
) -> list[Segment]:
    """Chop a record run into sealed segments of about ``segment_bytes``
    each; the remainder ships as one final *unsealed* tail-follow segment.
    Indexes are ``first_index, first_index+1, ...``."""
    out: list[Segment] = []
    chunk: list[dict] = []
    size = 0
    for rec in records:
        frame_len = len(encode_record(rec))
        chunk.append(rec)
        size += frame_len
        if size >= segment_bytes:
            out.append(seal_segment(epoch, first_index + len(out), chunk))
            chunk, size = [], 0
    if chunk:
        out.append(
            seal_segment(epoch, first_index + len(out), chunk, sealed=False)
        )
    return out


def validate_segment(seg: Segment) -> tuple[list[dict], str | None]:
    """``(records, None)`` when the segment is internally consistent, else
    ``([], reason)``.  Never raises — arbitrary hostile input comes back
    as a rejection reason (the standby refuses and the shipper retries or
    is fenced)."""
    try:
        frames = bytes(seg.frames)
        if not frames:
            return [], "empty segment"
        if len(frames) > MAX_SEGMENT_BYTES:
            return [], "segment exceeds the size cap"
        if zlib.crc32(frames) & 0xFFFFFFFF != int(seg.crc) & 0xFFFFFFFF:
            return [], "segment CRC mismatch"
        records, valid = iter_frames(frames)
        if valid != len(frames) or not records:
            return [], "segment frames do not parse cleanly"
        if records[0]["seq"] != int(seg.first_seq):
            return [], "first_seq does not match the frames"
        if records[-1]["seq"] != int(seg.last_seq):
            return [], "last_seq does not match the frames"
        return records, None
    except Exception as e:  # hostile input is a rejection, not a crash
        return [], f"malformed segment: {e!r}"
