"""Warm standby: segment receipt, replay, lease watch, promotion.

Two layers:

- :class:`SegmentApplier` — the pure trust boundary: validates one
  :class:`~cpzk_tpu.replication.segments.Segment` (epoch fencing, CRC,
  clean parse, contiguity with the applied prefix) and replays its new
  records through ``ServerState.replay_journal_record`` — the same
  validators a boot-time recovery uses, so a hostile primary cannot
  smuggle in what the live RPC would reject.  No gRPC, no disk (the disk
  write goes through an injectable sink); the fuzz harness drives this
  class directly with duplicated/reordered/truncated/cross-epoch
  deliveries and holds "never raises, prefix-stable" as invariants.

- :class:`StandbyReplica` — the serving wrapper: the ReplicationService
  gRPC handlers, durable frame persistence into the standby's own WAL
  (primary sequence numbers preserved via ``append_frames``), the lease
  clock (armed at first contact, renewed by every accepted ShipSegment /
  ReplicationStatus from an equal-or-higher epoch), and lease-based
  promotion — truncate the torn tail, finish replay, bump + persist the
  epoch, flip the readiness gate, and fence the deposed primary.
"""

from __future__ import annotations

import asyncio
import logging
import os
import tempfile
import time

from ..durability.wal import encode_record, iter_frames
from ..observability import get_tracer
from ..server import metrics
from .segments import Segment, validate_segment
from .wire import load_replication_pb2, make_replication_handler

log = logging.getLogger("cpzk_tpu.replication")


def load_epoch(path: str) -> int:
    """The persisted fencing epoch at ``path`` (1 when absent/garbage —
    epoch 1 is the first primary's epoch, so a fresh pair agrees)."""
    try:
        with open(path, encoding="utf-8") as f:
            return max(1, int(f.read().strip()))
    except (OSError, ValueError):
        return 1


def store_epoch(path: str, epoch: int) -> None:
    """Durably persist the fencing epoch (tmp + fsync + atomic rename,
    0600): a rebooted deposed primary must come back fenced, not amnesiac."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix="." + os.path.basename(path) + ".tmp.", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(str(int(epoch)))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class SegmentApplier:
    """Validate-and-replay for shipped WAL segments (see module docstring).

    ``sink`` (optional) is called as ``sink(frames, last_seq)`` with the
    canonical re-encoded frames of exactly the NEW records before they are
    applied — the durable-before-apply ordering the standby's WAL needs.
    """

    def __init__(self, state, epoch: int = 1, applied_seq: int = 0, sink=None):
        self.state = state
        self.epoch = epoch
        self.applied_seq = applied_seq
        self.sink = sink
        self.segments_received = 0
        self.segments_rejected = 0
        self.records_applied = 0
        self.records_skipped = 0
        self.fenced = 0
        self.lag_records = 0

    # -- the two-phase apply (prepare is pure; commit mutates state) -------

    def prepare(self, seg: Segment) -> tuple[bool, str, list[dict]]:
        """``(accepted, message, new_records)`` for one delivery.  Never
        raises.  ``accepted`` with an empty record list is an idempotent
        duplicate; a rejection names its reason and changes nothing."""
        self.segments_received += 1
        try:
            epoch = int(seg.epoch)
        except (TypeError, ValueError):
            epoch = -1
        if epoch < self.epoch:
            self.fenced += 1
            metrics.counter("state.repl.fenced").inc()
            return (
                False,
                f"fenced: stale epoch {epoch} < {self.epoch}",
                [],
            )
        records, err = validate_segment(seg)
        if err is not None:
            self.segments_rejected += 1
            return False, f"rejected: {err}", []
        if epoch > self.epoch:
            # a newer primary exists (our own epoch file lags a promotion
            # elsewhere): adopt its epoch so older senders fence correctly
            self.epoch = epoch
        if int(seg.last_seq) <= self.applied_seq:
            return True, "duplicate (already applied)", []
        if int(seg.first_seq) > self.applied_seq + 1:
            self.segments_rejected += 1
            return (
                False,
                f"gap: first_seq {seg.first_seq} > applied {self.applied_seq} + 1",
                [],
            )
        new = [r for r in records if r["seq"] > self.applied_seq]
        return True, "", new

    def commit(self, new_records: list[dict]) -> None:
        """Apply prepared records through the replay trust boundary and
        advance the applied watermark.  Invalid records are skipped and
        counted, never applied and never fatal — identical to boot-time
        recovery."""
        for rec in new_records:
            msg = self.state.replay_journal_record(rec)
            if msg is None:
                self.records_applied += 1
            else:
                self.records_skipped += 1
                log.warning(
                    "segment replay skipped seq %d (%s): %s",
                    rec["seq"], rec.get("type"), msg,
                )
            self.applied_seq = int(rec["seq"])
        metrics.gauge("state.repl.applied_seq").set(float(self.applied_seq))

    def apply(self, seg: Segment) -> tuple[bool, str]:
        """One-shot prepare + sink + commit (the synchronous path the fuzz
        harness and in-process tests drive)."""
        accepted, message, new = self.prepare(seg)
        if accepted and new:
            if self.sink is not None:
                frames = b"".join(encode_record(r) for r in new)
                self.sink(frames, int(new[-1]["seq"]))
            self.commit(new)
            message = f"applied {len(new)} records"
        return accepted, message

    def note_primary_seq(self, primary_seq: int) -> None:
        """Update lag accounting from the sender's advertised WAL head."""
        if primary_seq > 0:
            self.lag_records = max(0, int(primary_seq) - self.applied_seq)
            metrics.gauge("state.repl.lag_records").set(float(self.lag_records))


class StandbyReplica:
    """The standby node's replication plane (see module docstring).

    ``manager`` is the standby's own started
    :class:`~cpzk_tpu.durability.DurabilityManager` (``recover()`` already
    run): shipped frames append to its WAL with primary sequence numbers,
    so a standby reboot recovers through the ordinary durability path and
    a promotion continues the same journal for its own writes.
    """

    def __init__(self, state, manager, settings, faults=None, health=None,
                 audit_path: str | None = None):
        if manager is None or manager.wal is None:
            raise ValueError(
                "StandbyReplica requires a recovered DurabilityManager "
                "(replication is built on the durability subsystem)"
            )
        self.state = state
        self.manager = manager
        self.settings = settings
        self.health = health
        self._faults = faults
        #: where shipped ``kind="audit"`` proof-log segments land (the
        #: standby's own ``[audit] log_path``; segments are stored as
        #: ``<audit_path>.<first>-<last>.seg`` exactly as the primary
        #: sealed them, so a promotion continues the same directory)
        self.audit_path = audit_path
        self.audit_segments_received = 0
        self.pb2 = load_replication_pb2()
        self.role = "standby"
        self.epoch_path = settings.epoch_file or manager.state_file + ".epoch"
        epoch = load_epoch(self.epoch_path)
        self.applier = SegmentApplier(
            state, epoch=epoch, applied_seq=manager.wal.seq, sink=None
        )
        # serializes whole segment applications: prepare/persist/commit
        # must not interleave between two concurrent ShipSegment handlers
        self._apply_lock = asyncio.Lock()
        self._last_contact: float | None = None  # lease armed at 1st contact
        self._last_segment_at: float | None = None  # last ACCEPTED segment
        self._watch_task: asyncio.Task | None = None
        self._promotions = 0
        metrics.gauge("state.repl.role").set(0.0)
        metrics.gauge("state.repl.epoch").set(float(epoch))

    # -- introspection -----------------------------------------------------

    @property
    def epoch(self) -> int:
        return self.applier.epoch

    @property
    def applied_seq(self) -> int:
        return self.applier.applied_seq

    @property
    def lease_remaining_s(self) -> float | None:
        """Seconds until the primary's lease expires; ``None`` before the
        first contact (an unpaired standby never self-promotes)."""
        if self._last_contact is None:
            return None
        return (
            self.settings.lease_ms / 1000.0
            - (time.monotonic() - self._last_contact)
        )

    def status(self) -> dict:
        """The admin REPL ``/replication`` payload (standby side)."""
        lease = self.lease_remaining_s
        return {
            "role": self.role,
            "epoch": self.epoch,
            "applied_seq": self.applied_seq,
            "lag_records": self.applier.lag_records,
            "segments_received": self.applier.segments_received,
            "segments_rejected": self.applier.segments_rejected,
            "records_applied": self.applier.records_applied,
            "records_skipped": self.applier.records_skipped,
            "fenced": self.applier.fenced,
            "lease_remaining_s": lease,
            "last_ship_age_s": (
                None if self._last_segment_at is None
                else round(time.monotonic() - self._last_segment_at, 3)
            ),
            "promotions": self._promotions,
            "audit_segments_received": self.audit_segments_received,
        }

    # -- lease -------------------------------------------------------------

    def _renew_lease(self) -> None:
        self._last_contact = time.monotonic()

    def start(self) -> None:
        """Start the lease watch task (idempotent)."""
        if self._watch_task is None or self._watch_task.done():
            self._watch_task = asyncio.get_running_loop().create_task(
                self._watch()
            )

    async def stop(self) -> None:
        task = self._watch_task
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            except Exception:
                log.exception("replication lease watch task died")
            self._watch_task = None

    async def _watch(self) -> None:
        """Promote when the armed lease expires (``auto_promote``)."""
        interval = self.settings.renew_interval_ms / 1000.0
        while self.role == "standby":
            await asyncio.sleep(interval)
            lease = self.lease_remaining_s
            if (
                self.settings.auto_promote
                and lease is not None
                and lease <= 0
            ):
                log.warning(
                    "primary lease expired (%.0f ms without contact); "
                    "promoting standby at applied_seq=%d epoch=%d",
                    self.settings.lease_ms, self.applied_seq, self.epoch,
                )
                await self.promote(reason="lease-expired")
                return

    # -- gRPC handlers -----------------------------------------------------

    def handler(self):
        return make_replication_handler(self)

    async def ship_segment(self, request, context):
        del context
        if getattr(request, "kind", "") == "audit":
            return await self._ship_audit_segment(request)
        seg = Segment(
            epoch=request.epoch,
            index=request.segment_index,
            first_seq=request.first_seq,
            last_seq=request.last_seq,
            frames=bytes(request.frames),
            crc=request.crc32,
            sealed=request.sealed,
        )
        async with self._apply_lock:
            if self.role != "standby":
                # a promoted node refuses shipments outright — its epoch is
                # higher than any legitimate sender's, but be explicit
                accepted, message = False, (
                    f"fenced: this node is primary at epoch {self.epoch}"
                )
                self.applier.fenced += 1
                metrics.counter("state.repl.fenced").inc()
            else:
                accepted, message, new = self.applier.prepare(seg)
                if accepted:
                    if new:
                        frames = b"".join(encode_record(r) for r in new)
                        last = int(new[-1]["seq"])
                        # durable BEFORE applied: a standby crash between
                        # the two replays the frames from its own WAL
                        await asyncio.to_thread(
                            self._persist_frames, frames, last
                        )
                        self.applier.commit(new)
                        message = f"applied {len(new)} records"
                    self._last_segment_at = time.monotonic()
                    # apply lag against the shipper's send stamp: wall
                    # clock from "primary wrote it" to "standby applied
                    # it" (clock skew shows as a level shift, not noise)
                    sent_ms = int(getattr(request, "sent_unix_ms", 0))
                    if sent_ms > 0:
                        metrics.histogram(
                            "state.repl.apply_lag_seconds"
                        ).observe(max(0.0, time.time() - sent_ms / 1000.0))
                    self.applier.note_primary_seq(int(request.primary_seq))
                    self._renew_lease()
            if not accepted:
                get_tracer().record_event(
                    "segment_rejected",
                    epoch=int(request.epoch),
                    index=int(request.segment_index),
                    reason=message,
                )
        return self.pb2.ShipSegmentResponse(
            accepted=accepted,
            applied_seq=self.applied_seq,
            epoch=self.epoch,
            message=message,
        )

    def _persist_frames(self, frames: bytes, last_seq: int) -> None:
        wal = self.manager.wal
        assert wal is not None  # ctor refuses an unrecovered manager
        wal.append_frames(frames, last_seq)
        if wal.needs_sync():
            wal.sync()

    async def _ship_audit_segment(self, request):
        """A sealed proof-log segment (``kind="audit"``): validate CRC +
        clean parse, persist it atomically as a rotated-segment file next
        to this node's proof log.  Never replayed as state — proof
        records are audit evidence, not mutations.  Same epoch fencing as
        WAL segments; an identical re-delivery is an idempotent
        overwrite."""
        import zlib

        from ..durability.wal import iter_frames as _iter

        try:
            epoch = int(request.epoch)
        except (TypeError, ValueError):
            epoch = -1
        if self.role != "standby" or epoch < self.epoch:
            self.applier.fenced += 1
            metrics.counter("state.repl.fenced").inc()
            return self.pb2.ShipSegmentResponse(
                accepted=False, applied_seq=self.applied_seq,
                epoch=self.epoch,
                message=f"fenced: stale epoch {epoch} < {self.epoch}",
            )
        if self.audit_path is None:
            return self.pb2.ShipSegmentResponse(
                accepted=False, applied_seq=self.applied_seq,
                epoch=self.epoch,
                message="rejected: standby has no audit plane "
                        "([audit] log_path unset)",
            )
        raw = bytes(request.frames)
        if zlib.crc32(raw) & 0xFFFFFFFF != int(request.crc32) & 0xFFFFFFFF:
            return self.pb2.ShipSegmentResponse(
                accepted=False, applied_seq=self.applied_seq,
                epoch=self.epoch, message="rejected: segment CRC mismatch",
            )
        records, valid = _iter(raw)
        if valid != len(raw) or not records:
            return self.pb2.ShipSegmentResponse(
                accepted=False, applied_seq=self.applied_seq,
                epoch=self.epoch,
                message="rejected: segment frames do not parse cleanly",
            )
        if (
            int(records[0]["seq"]) != int(request.first_seq)
            or int(records[-1]["seq"]) != int(request.last_seq)
        ):
            return self.pb2.ShipSegmentResponse(
                accepted=False, applied_seq=self.applied_seq,
                epoch=self.epoch,
                message="rejected: seq bounds do not match the frames",
            )
        from ..audit.log import segment_name

        dst = segment_name(
            self.audit_path, int(request.first_seq), int(request.last_seq)
        )
        await asyncio.to_thread(self._persist_audit_file, dst, raw)
        self.audit_segments_received += 1
        self._renew_lease()
        return self.pb2.ShipSegmentResponse(
            accepted=True, applied_seq=self.applied_seq, epoch=self.epoch,
            message=f"audit segment stored ({len(records)} records)",
        )

    @staticmethod
    def _persist_audit_file(dst: str, raw: bytes) -> None:
        d = os.path.dirname(os.path.abspath(dst)) or "."
        fd, tmp = tempfile.mkstemp(
            prefix="." + os.path.basename(dst) + ".tmp.", dir=d
        )
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(raw)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, dst)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        os.chmod(dst, 0o600)

    async def replication_status(self, request, context):
        del context
        if (
            self.role == "standby"
            and request.renew_lease
            and int(request.epoch) >= self.epoch
        ):
            self._renew_lease()
        if self.role == "standby":
            self.applier.note_primary_seq(int(request.primary_seq))
        lease = self.lease_remaining_s
        return self.pb2.ReplicationStatusResponse(
            role=self.role,
            epoch=self.epoch,
            applied_seq=self.applied_seq,
            lag_records=self.applier.lag_records,
            lease_remaining_s=-1.0 if lease is None else lease,
            segments_received=self.applier.segments_received,
        )

    async def handover(self, request, context):
        """Coordinated-handover wire handler, STANDBY side (phase
        "promote"): promote at epoch+1 once the local applied sequence
        number has reached the primary's fence watermark.  The primary
        has already fenced writes and shipped the WAL tail, so under a
        healthy pair the watermark is already applied and this is one
        promotion away; a standby that somehow lags past the watermark
        refuses rather than promoting with acked writes missing — the
        primary then aborts, unfences, and the pair degrades to the
        ordinary path."""
        del context
        if request.phase not in ("", "promote"):
            return self.pb2.HandoverResponse(
                ok=False, role=self.role, epoch=self.epoch,
                applied_seq=self.applied_seq,
                message=(
                    "this node is a standby; it answers phase 'promote' "
                    f"only (got {request.phase!r})"
                ),
            )
        if self._faults is not None and self._faults.take_crash(
            "pre_handover_ack"
        ):
            from ..resilience.faults import CrashPoint

            raise CrashPoint("pre_handover_ack during handover promotion")
        if self.role == "primary":
            # idempotent retry of a handover whose response was lost
            return self.pb2.HandoverResponse(
                ok=True, role="primary", epoch=self.epoch,
                applied_seq=self.applied_seq, message="already primary",
                fence_seq=int(request.fence_seq),
            )
        if int(request.epoch) < self.epoch:
            return self.pb2.HandoverResponse(
                ok=False, role=self.role, epoch=self.epoch,
                applied_seq=self.applied_seq,
                message=(
                    f"fenced: stale handover epoch {int(request.epoch)} < "
                    f"{self.epoch}"
                ),
            )
        fence_seq = int(request.fence_seq)
        if self.applied_seq < fence_seq:
            return self.pb2.HandoverResponse(
                ok=False, role=self.role, epoch=self.epoch,
                applied_seq=self.applied_seq,
                message=(
                    f"not caught up: applied_seq {self.applied_seq} < "
                    f"fence watermark {fence_seq}"
                ),
            )
        report = await self.promote(
            reason=f"handover ({request.reason or 'rpc'})"
        )
        return self.pb2.HandoverResponse(
            ok=True, role=self.role, epoch=self.epoch,
            applied_seq=self.applied_seq,
            message=report["message"], fence_seq=fence_seq,
        )

    # -- promotion ---------------------------------------------------------

    async def promote(self, reason: str = "operator") -> dict:
        """Take over as primary: truncate the local WAL's torn tail,
        finish replaying anything persisted-but-unapplied, bump + persist
        the fencing epoch, flip the readiness gate to SERVING, and attach
        nothing new — the journal the frames landed in simply continues
        for this node's own writes.  Idempotent: promoting a primary is a
        no-op report, and a :class:`CrashPoint` at ``pre_promote`` leaves
        a retryable standby."""
        if self.role == "primary":
            return {"promoted": False, "message": "already primary",
                    "epoch": self.epoch}
        if self._faults is not None and self._faults.take_crash("pre_promote"):
            from ..resilience.faults import CrashPoint

            raise CrashPoint("pre_promote during standby promotion")
        async with self._apply_lock:
            wal = self.manager.wal
            assert wal is not None  # ctor refuses an unrecovered manager
            await asyncio.to_thread(wal.sync, True)
            # finish replay: anything durable in the local log beyond the
            # applied watermark (a crash between persist and commit), and
            # truncate a torn tail a hard standby death left behind —
            # read_from(0) spans sealed segments + the active file, so a
            # rotated standby WAL promotes exactly like a single file
            raw = await asyncio.to_thread(wal.read_from, 0)
            records, valid = iter_frames(raw)
            truncated = 0
            if valid < len(raw):
                truncated = len(raw) - valid
                await asyncio.to_thread(wal.truncate_to, valid)
            replayed = 0
            for rec in records:
                if rec["seq"] > self.applier.applied_seq:
                    self.applier.commit([rec])
                    replayed += 1
            self.applier.epoch += 1
            await asyncio.to_thread(
                store_epoch, self.epoch_path, self.applier.epoch
            )
            self.role = "primary"
            self._promotions += 1
            if self.health is not None:
                self.health.standby = False
        metrics.gauge("state.repl.role").set(1.0)
        metrics.gauge("state.repl.epoch").set(float(self.epoch))
        get_tracer().record_event(
            "promotion",
            reason=reason,
            epoch=self.epoch,
            applied_seq=self.applied_seq,
            replayed_tail=replayed,
            truncated_bytes=truncated,
        )
        log.warning(
            "PROMOTED to primary (reason=%s): epoch=%d applied_seq=%d "
            "tail_replayed=%d torn_bytes_truncated=%d",
            reason, self.epoch, self.applied_seq, replayed, truncated,
        )
        return {
            "promoted": True,
            "message": f"promoted ({reason})",
            "epoch": self.epoch,
            "applied_seq": self.applied_seq,
            "replayed_tail": replayed,
            "truncated_bytes": truncated,
        }
