"""Primary-side WAL segment shipping + lease renewal.

A :class:`SegmentShipper` follows the primary's write-ahead log, chops
new records into sealed segments (plus an unsealed tail-follow of the
active run), and ships them to the warm standby over the
ReplicationService.  Every successful exchange renews the standby's view
of the primary lease; when the primary dies, renewals stop, the lease
expires, and the standby promotes (``standby.py``).

Modes (``[replication] mode``):

- ``async`` — appends are acknowledged after the local fsync; shipping
  runs on the renewal cadence (loss window on failover: up to one
  ``renew_interval_ms`` of acknowledged writes).
- ``sync``  — :meth:`wait_replicated` is attached to ``ServerState`` as
  the replication barrier: an acknowledged mutation additionally waits
  until the standby has applied its sequence number (loss window: none —
  the SIGKILL chaos test pins it).  If the standby cannot acknowledge
  within ``sync_timeout_ms`` the mutation FAILS rather than silently
  degrading to async — zero-loss means refusing to lie about durability.

Fencing: a shipper that sees a higher epoch in a response (or an
explicit ``fenced`` rejection) has been deposed — it stops shipping for
good and every sync-mode barrier fails.  Compaction on the primary is
clamped to the shipped-and-acknowledged byte offset so a covering
snapshot can never drop records the standby has not yet received.
"""

from __future__ import annotations

import asyncio
import logging
import time

import grpc

from ..durability.wal import encode_record, iter_frames
from ..observability import get_tracer
from ..resilience.faults import CrashPoint
from ..server import metrics
from .segments import split_records
from .standby import load_epoch
from .wire import ReplicationStub, load_replication_pb2, make_replication_handler

log = logging.getLogger("cpzk_tpu.replication")


class ReplicationTimeout(RuntimeError):
    """A sync-mode barrier could not confirm standby durability in time
    (standby down, lagging past ``sync_timeout_ms``, or this primary has
    been fenced).  The mutation is durable locally but NOT replicated —
    the caller must surface the failure, not acknowledge the write."""


class HandoverError(RuntimeError):
    """A coordinated handover could not run or complete (no standby, a
    stale standby that never reached the fence watermark, a refused
    promotion, a concurrent handover).  Raised by
    :meth:`SegmentShipper.run_handover` — the caller falls back to the
    ordinary path (plain drain + lease failover), loudly."""


class SegmentShipper:
    """Ship sealed WAL segments + tail-follow deltas to the standby.

    With ``audit_log`` attached (a rotating
    :class:`~cpzk_tpu.audit.ProofLogWriter`), sealed proof-log segments
    ride the same loop as ``kind="audit"`` shipments: CRC-validated by
    the standby and persisted as rotated-segment files next to *its*
    proof log, so a machine death loses at most the unsealed audit tail
    — the PR 9 trail survives hardware the way the WAL does.
    """

    def __init__(self, state, manager, settings, faults=None,
                 audit_log=None):
        if manager is None or manager.wal is None:
            raise ValueError(
                "SegmentShipper requires a recovered DurabilityManager"
            )
        self.state = state
        self.manager = manager
        self.settings = settings
        self._faults = faults
        self.audit_log = audit_log  # ProofLogWriter | None
        self.audit_segments_shipped = 0
        #: sealed-segment basenames already accepted by the standby this
        #: boot; a restart re-ships (the standby's atomic overwrite makes
        #: duplicates idempotent)
        self._audit_shipped: set[str] = set()
        self.pb2 = load_replication_pb2()
        self.epoch_path = settings.epoch_file or manager.state_file + ".epoch"
        self.epoch = load_epoch(self.epoch_path)
        self.peer = settings.peer
        #: byte offset into the WAL file that has been shipped AND
        #: acknowledged — also the compaction floor (``DurabilityManager``
        #: never compacts past it)
        self.acked_offset = 0
        self.acked_seq = 0
        self.segments_shipped = 0
        #: ``time.monotonic()`` of the last ACCEPTED segment ship (None
        #: until the first) — the ``last_ship_age_s`` row of /statusz
        self.last_ship_at: float | None = None
        self.fenced = False
        self.gap_stalled = False
        self.crashed: BaseException | None = None
        #: "primary" always — lets ``serve(replica=shipper)`` expose the
        #: ReplicationService (the Handover entry point) on a primary
        #: daemon through the same seam as a standby, while ``_admit``'s
        #: role check keeps admitting auth traffic
        self.role = "primary"
        self.health = None  # serve() wires the HealthService here
        #: set while (and after) a handover fences writes: the address
        #: the service's redirect trailers point at (the standby)
        self.redirect_address: str | None = None
        #: coordinated-handover bookkeeping behind /statusz + /handover
        self._handover = {
            "stage": "idle", "fence_seq": 0, "standby_applied_seq": 0,
        }
        self.handovers_attempted = 0
        self.handovers_completed = 0
        self.handovers_aborted = 0
        self.last_handover_s: float | None = None
        self._index = 0
        self._task: asyncio.Task | None = None
        self._stop = False
        self._wake: asyncio.Event | None = None
        self._ack_cond: asyncio.Condition | None = None
        self._channel = None
        self._stub: ReplicationStub | None = None
        metrics.gauge("state.repl.role").set(1.0)
        metrics.gauge("state.repl.epoch").set(float(self.epoch))

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Start the shipping loop (idempotent); call on a running loop."""
        if self._task is None or self._task.done():
            self._wake = asyncio.Event()
            self._ack_cond = asyncio.Condition()
            self._stop = False
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        """Graceful stop: one final flush tick, then close the channel."""
        self._stop = True
        if self._task is not None:
            assert self._wake is not None
            self._wake.set()
            try:
                await self._task
            except Exception:
                log.exception("segment shipper loop died during stop")
            self._task = None
        await self._close_channel()

    async def kill(self) -> None:
        """Abrupt stop with NO final flush — the in-process stand-in for
        SIGKILLing the primary (chaos tests)."""
        self._stop = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None
        await self._close_channel()

    async def _close_channel(self) -> None:
        if self._channel is not None:
            await self._channel.close()
            self._channel = None
            self._stub = None

    def _ensure_stub(self) -> ReplicationStub:
        if self._stub is None:
            self._channel = grpc.aio.insecure_channel(self.peer)
            self._stub = ReplicationStub(self._channel)
        return self._stub

    # -- the loop ----------------------------------------------------------

    async def _run(self) -> None:
        interval = self.settings.renew_interval_ms / 1000.0
        wake, cond = self._wake, self._ack_cond
        assert wake is not None and cond is not None
        final = False
        while True:
            if self.fenced or self.crashed is not None:
                return
            try:
                await self._tick()
            except CrashPoint as e:
                # a scheduled deterministic death: the primary is "gone"
                self.crashed = e
                log.error("segment shipper crash point: %s", e)
                async with cond:
                    cond.notify_all()
                return
            except grpc.aio.AioRpcError as e:
                # standby unreachable: keep trying on the cadence — the
                # lease math on the other side decides what it means
                log.debug("standby unreachable: %s", e.code())
            except Exception:
                log.exception("segment shipper tick failed; retrying")
            if final:
                return
            if self._stop:
                final = True  # one last flush tick, then exit
                continue
            try:
                await asyncio.wait_for(wake.wait(), timeout=interval)
            except asyncio.TimeoutError:
                pass
            wake.clear()

    async def _tick(self) -> None:
        """Ship everything new past the acked offset, else renew the lease."""
        wal = self.manager.wal
        if wal is None:
            return

        offset = self.acked_offset
        # logical-offset read across sealed segments + the active file:
        # the shipper keeps tailing straight through a rotation, and
        # compaction rebases acked_offset via note_compacted as before
        raw = await asyncio.to_thread(wal.read_from, offset)
        records, valid = iter_frames(raw)
        new = [r for r in records if r["seq"] > self.acked_seq]
        # bytes of already-acknowledged records in the chunk (a restarted
        # primary re-reading history a caught-up standby already has):
        # skip them so the compaction floor advances past them too
        if records and len(new) < len(records):
            new_bytes = sum(len(encode_record(r)) for r in new)
            self.acked_offset = offset + valid - new_bytes
        if not new:
            await self._ship_audit_segments()
            if not self.fenced:
                await self._renew_lease()
            return
        for seg in split_records(
            new, self.epoch, self._index, self.settings.segment_bytes
        ):
            await self._ship(seg)
            if self.fenced:
                return
        await self._ship_audit_segments()

    async def _ship_audit_segments(self) -> None:
        """Ship sealed proof-log segments the standby has not accepted
        yet (``kind="audit"``).  Sealed files are immutable, so the work
        list is a directory scan and duplicates are idempotent on the
        standby (atomic overwrite of an identical file)."""
        log_writer = self.audit_log
        if log_writer is None or self.fenced:
            return
        import os
        import zlib

        for path in log_writer.sealed_segments():
            name = os.path.basename(path)
            if name in self._audit_shipped:
                continue

            def _read_seg(p=path) -> bytes:
                with open(p, "rb") as f:
                    return f.read()

            raw = await asyncio.to_thread(_read_seg)
            records, valid = iter_frames(raw)
            if valid != len(raw) or not records:
                # a sealed segment is fsynced before the rename — this is
                # disk corruption, not a race; skip it loudly rather than
                # spinning on it every tick
                log.error(
                    "sealed proof-log segment %s does not parse cleanly; "
                    "NOT shipped (inspect/restore from the primary copy)",
                    path,
                )
                self._audit_shipped.add(name)
                continue
            stub = self._ensure_stub()
            req = self.pb2.ShipSegmentRequest(
                epoch=self.epoch,
                segment_index=int(records[0]["seq"]),
                first_seq=int(records[0]["seq"]),
                last_seq=int(records[-1]["seq"]),
                frames=raw,
                crc32=zlib.crc32(raw) & 0xFFFFFFFF,
                sealed=True,
                primary_seq=self._wal_seq(),
                sent_unix_ms=int(time.time() * 1000.0),
                kind="audit",
            )
            resp = await stub.ship_segment(
                req, timeout=self.settings.sync_timeout_ms / 1000.0
            )
            if resp.accepted:
                self._audit_shipped.add(name)
                self.audit_segments_shipped += 1
                metrics.counter("audit.log.segments_shipped").inc()
            elif resp.epoch > self.epoch or "fenced" in resp.message:
                self._fence(resp.epoch, resp.message)
                return
            else:
                log.warning(
                    "audit segment %s rejected: %s", name, resp.message
                )
                return  # retry next tick

    def _wal_seq(self) -> int:
        wal = self.manager.wal
        return wal.seq if wal is not None else 0

    async def _renew_lease(self) -> None:
        stub = self._ensure_stub()
        req = self.pb2.ReplicationStatusRequest(
            epoch=self.epoch, renew_lease=True,
            primary_seq=self._wal_seq(),
        )
        resp = await stub.replication_status(
            req, timeout=self.settings.sync_timeout_ms / 1000.0
        )
        if resp.epoch > self.epoch or resp.role == "primary":
            self._fence(resp.epoch, "status exchange")
        else:
            self.acked_seq = max(self.acked_seq, int(resp.applied_seq))
        metrics.gauge("state.repl.lag_records").set(
            float(max(0, self._wal_seq() - self.acked_seq))
        )

    async def _ship(self, seg) -> None:
        if self._faults is not None and self._faults.take_crash("pre_ship"):
            raise CrashPoint(f"pre_ship of segment {seg.index}")
        stub = self._ensure_stub()
        frames = seg.frames
        if self._faults is not None and self._faults.take_crash("mid_segment"):
            # the death-mid-transfer stand-in: half the frame bytes leave
            # the machine (CRC intact, so the standby rejects the torn
            # blob whole), then the "process" dies
            torn = self.pb2.ShipSegmentRequest(
                epoch=self.epoch, segment_index=seg.index,
                first_seq=seg.first_seq, last_seq=seg.last_seq,
                frames=frames[: max(1, len(frames) // 2)],
                crc32=seg.crc, sealed=seg.sealed,
                primary_seq=self._wal_seq(),
            )
            try:
                await stub.ship_segment(
                    torn, timeout=self.settings.sync_timeout_ms / 1000.0
                )
            finally:
                raise CrashPoint(f"mid_segment of segment {seg.index}")
        req = self.pb2.ShipSegmentRequest(
            epoch=self.epoch, segment_index=seg.index,
            first_seq=seg.first_seq, last_seq=seg.last_seq,
            frames=frames, crc32=seg.crc, sealed=seg.sealed,
            primary_seq=self._wal_seq(),
            # wall-clock send stamp: the applier reports its apply-time
            # lag against this into state.repl.apply_lag_seconds
            sent_unix_ms=int(time.time() * 1000.0),
        )
        t0 = time.monotonic()
        resp = await stub.ship_segment(
            req, timeout=self.settings.sync_timeout_ms / 1000.0
        )
        # ship RTT: request out -> response in, the wire half of the
        # replication lag an operator sees on /statusz and /metrics
        metrics.histogram("state.repl.ship_rtt").observe(
            time.monotonic() - t0
        )
        if resp.accepted:
            self._index = seg.index + 1
            self.segments_shipped += 1
            self.last_ship_at = time.monotonic()
            self.acked_seq = max(self.acked_seq, int(resp.applied_seq))
            self.acked_offset += len(frames)
            self.gap_stalled = False
            metrics.counter("state.repl.segments_shipped").inc()
            metrics.gauge("state.repl.lag_records").set(
                float(max(0, self._wal_seq() - self.acked_seq))
            )
            await self._notify_ack()
        elif resp.epoch > self.epoch or "fenced" in resp.message:
            self._fence(resp.epoch, resp.message)
            await self._notify_ack()
        elif "gap" in resp.message:
            # the standby is missing history this WAL no longer holds
            # (compacted before the pair was connected): unrecoverable
            # over the wire — seed the standby from a snapshot copy
            # (docs/operations.md runbook) — but keep renewing the lease
            # so a live primary is not failed over from
            if not self.gap_stalled:
                log.error(
                    "standby reports a history gap (%s): seed it from a "
                    "snapshot copy and restart replication", resp.message,
                )
            self.gap_stalled = True
            await self._renew_lease()
        else:
            log.warning("segment %d rejected: %s", seg.index, resp.message)

    async def _notify_ack(self) -> None:
        cond = self._ack_cond
        if cond is not None:
            async with cond:
                cond.notify_all()

    def _fence(self, their_epoch: int, where: str) -> None:
        if not self.fenced:
            log.error(
                "DEPOSED: standby is at epoch %d > ours %d (%s); this "
                "primary stops shipping and must not take writes",
                their_epoch, self.epoch, where,
            )
            get_tracer().record_event(
                "primary_fenced", our_epoch=self.epoch,
                their_epoch=int(their_epoch),
            )
        self.fenced = True

    # -- sync-mode barrier -------------------------------------------------

    async def wait_replicated(self, seq: int) -> None:
        """Block until the standby has applied ``seq`` (the sync-mode
        acknowledgement barrier ``ServerState`` awaits before an RPC
        returns).  Raises :class:`ReplicationTimeout` when the standby
        cannot confirm within ``sync_timeout_ms`` or this primary has
        been fenced/crashed."""
        if seq <= self.acked_seq:
            return
        wake, cond = self._wake, self._ack_cond
        if wake is None or cond is None:
            raise ReplicationTimeout("segment shipper is not running")
        wake.set()
        timeout = self.settings.sync_timeout_ms / 1000.0

        def _done() -> bool:
            return (
                self.acked_seq >= seq
                or self.fenced
                or self.crashed is not None
            )

        try:
            async with cond:
                await asyncio.wait_for(
                    cond.wait_for(_done), timeout=timeout
                )
        except asyncio.TimeoutError:
            raise ReplicationTimeout(
                f"standby did not acknowledge seq {seq} within "
                f"{self.settings.sync_timeout_ms:g} ms (acked "
                f"{self.acked_seq})"
            ) from None
        if self.fenced:
            raise ReplicationTimeout(
                "this primary has been fenced by a promoted standby"
            )
        if self.crashed is not None:
            raise ReplicationTimeout("segment shipper crashed")

    # -- coordinated handover (ISSUE 18) -----------------------------------

    def handler(self):
        """ReplicationService handler for the PRIMARY side: ship/status
        answer with structural refusals, ``Handover`` (phase "initiate")
        runs the coordinated handover — what lets ``serve(replica=self)``
        expose the planned-operations entry point over the same port as
        auth traffic."""
        return make_replication_handler(self)

    def _crashpt(self, point: str) -> None:
        if self._faults is not None and self._faults.take_crash(point):
            raise CrashPoint(f"{point} during handover")

    def _set_stage(self, stage: str) -> None:
        self._handover["stage"] = stage

    async def run_handover(self, reason: str = "operator",
                           timeout_ms: float | None = None) -> dict:
        """The coordinated primary→standby handover, end to end:

        1. arm the write fence (``ServerState.owner_fence`` — reads and
           challenge consumes stay open; fenced writes get the standard
           FAILED_PRECONDITION redirect, pointed at the standby);
        2. flush and ship the WAL tail, wait for the standby's
           applied-seq ack at the fence watermark;
        3. instruct the standby to promote at epoch+1;
        4. enter deposed-redirecting mode (stay fenced, stop shipping).

        Zero acked-write loss is structural: the fence precedes the
        journal append, so every acknowledged write has ``seq <=
        fence_seq`` and the standby applied it before promoting.  Any
        failure before step 3 completes restores the previous fence and
        re-raises — the pair keeps serving exactly as before, and a real
        process death at any stage degrades to ordinary lease failover
        (``HANDOVER_CRASH_POINTS`` pins every stage).
        """
        if self.fenced:
            raise HandoverError("this primary is already fenced/deposed")
        if self.crashed is not None:
            raise HandoverError("segment shipper crashed")
        if not self.peer:
            raise HandoverError("no standby attached ([replication] peer)")
        if self._handover["stage"] in ("fence", "ship_tail", "promote"):
            raise HandoverError("a handover is already in progress")
        timeout_s = (
            timeout_ms if timeout_ms is not None
            else self.settings.handover_timeout_ms
        ) / 1000.0
        self.handovers_attempted += 1
        metrics.counter("fleet.handover.attempts").inc()
        t0 = time.monotonic()
        prev_fence = getattr(self.state, "owner_fence", None)
        target = self.peer
        promoted = False
        try:
            self._crashpt("pre_handover_fence")
            # 1. arm the write fence, composed over any fleet fence: a
            # user another partition owns keeps its fleet redirect, every
            # user this partition owns redirects to the standby
            def _handover_fence(uid: str, _prev=prev_fence):
                if _prev is not None:
                    msg = _prev(uid)
                    if msg is not None:
                        return msg
                return (
                    "wrong partition: handover in progress; writes go to "
                    f"the standby at {target}"
                )

            if hasattr(self.state, "attach_owner_fence"):
                self.state.attach_owner_fence(_handover_fence)
            self.redirect_address = target
            self._set_stage("fence")
            self._crashpt("post_handover_fence")
            # 2. flush + ship the tail; the fence preceded every later
            # append, so this watermark covers every acknowledged write
            wal = self.manager.wal
            if wal is not None:
                await asyncio.to_thread(wal.sync, True)
            fence_seq = self._wal_seq()
            self._handover["fence_seq"] = fence_seq
            self._set_stage("ship_tail")
            await self._await_acked(fence_seq, timeout_s)
            self._handover["standby_applied_seq"] = self.acked_seq
            self._crashpt("pre_handover_promote")
            # 3. instruct the standby to promote at epoch+1
            self._set_stage("promote")
            stub = self._ensure_stub()
            resp = await stub.handover(
                self.pb2.HandoverRequest(
                    phase="promote", epoch=self.epoch,
                    fence_seq=fence_seq, reason=reason,
                ),
                timeout=timeout_s,
            )
            if not resp.ok:
                raise HandoverError(
                    f"standby refused promotion: {resp.message}"
                )
            promoted = True
            self._handover["standby_applied_seq"] = int(resp.applied_seq)
            self._crashpt("post_handover_promote")
            # 4. deposed-redirecting mode: stop shipping/renewing for
            # good, keep the fence redirecting writes at the new primary
            self._fence(int(resp.epoch), "coordinated handover")
            await self._notify_ack()
            self._set_stage("deposed")
            duration = time.monotonic() - t0
            self.last_handover_s = duration
            self.handovers_completed += 1
            metrics.counter("fleet.handover.completed").inc()
            metrics.histogram("fleet.handover.duration").observe(duration)
            get_tracer().record_event(
                "handover", reason=reason, fence_seq=fence_seq,
                new_epoch=int(resp.epoch), duration_s=duration,
            )
            log.warning(
                "handover complete (%s): standby %s promoted at epoch %d, "
                "fence watermark seq %d, %.3fs; this node is "
                "deposed-redirecting and should drain",
                reason, target, int(resp.epoch), fence_seq, duration,
            )
            return {
                "ok": True, "epoch": int(resp.epoch),
                "fence_seq": fence_seq,
                "applied_seq": int(resp.applied_seq),
                "duration_s": duration, "peer": target,
            }
        except BaseException:
            self.handovers_aborted += 1
            metrics.counter("fleet.handover.aborted").inc()
            if promoted:
                # the standby IS primary now — stay deposed-redirecting;
                # anything less re-forks history
                self._fence(self.epoch + 1, "handover abort after promotion")
                self._set_stage("deposed")
            else:
                # nothing irreversible happened: restore the previous
                # fence and keep serving as the primary (lease renewal
                # continues; a real death here becomes lease failover)
                if hasattr(self.state, "attach_owner_fence"):
                    self.state.attach_owner_fence(prev_fence)
                self.redirect_address = None
                self._set_stage("aborted")
            raise

    async def _await_acked(self, seq: int, timeout_s: float) -> None:
        """Wait until the standby has applied ``seq`` (the handover's
        fence-watermark wait — ``wait_replicated`` with the handover
        deadline instead of the sync-mode one)."""
        if seq <= self.acked_seq:
            return
        wake, cond = self._wake, self._ack_cond
        if wake is None or cond is None:
            raise HandoverError("segment shipper is not running")
        wake.set()

        def _done() -> bool:
            return (
                self.acked_seq >= seq
                or self.fenced
                or self.crashed is not None
            )

        try:
            async with cond:
                await asyncio.wait_for(cond.wait_for(_done), timeout=timeout_s)
        except asyncio.TimeoutError:
            raise HandoverError(
                f"stale standby: did not reach the fence watermark seq "
                f"{seq} within {timeout_s * 1000.0:g} ms (applied "
                f"{self.acked_seq})"
            ) from None
        if self.fenced:
            raise HandoverError("fenced during handover")
        if self.crashed is not None:
            raise HandoverError("segment shipper crashed during handover")

    # ReplicationService wire methods (serve(replica=shipper) installs
    # these next to the auth handlers on a primary daemon)

    async def handover(self, request, context):
        """Wire entry point: phase "initiate" runs :meth:`run_handover`
        (the fleet rolling-restart CLI's path); anything else is refused
        — a primary does not promote."""
        if request.phase not in ("", "initiate"):
            return self.pb2.HandoverResponse(
                ok=False, role="primary", epoch=self.epoch,
                applied_seq=self._wal_seq(),
                message=(
                    "this node is a primary; it answers phase 'initiate' "
                    f"only (got {request.phase!r})"
                ),
            )
        try:
            report = await self.run_handover(reason=request.reason or "rpc")
        except CrashPoint:
            raise  # the process-death stand-in must stay fatal
        except Exception as e:
            return self.pb2.HandoverResponse(
                ok=False, role="primary", epoch=self.epoch,
                applied_seq=self._wal_seq(), message=str(e),
            )
        return self.pb2.HandoverResponse(
            ok=True, role="primary", epoch=report["epoch"],
            applied_seq=report["applied_seq"],
            message="standby promoted; this node is deposed-redirecting",
            fence_seq=report["fence_seq"],
            duration_s=report["duration_s"],
        )

    async def ship_segment(self, request, context):
        """A primary never applies shipped segments; the 'fenced' refusal
        makes a deposed twin shipping at us fence itself."""
        return self.pb2.ShipSegmentResponse(
            accepted=False, applied_seq=self._wal_seq(), epoch=self.epoch,
            message="fenced: this node is a primary, not a standby",
        )

    async def replication_status(self, request, context):
        return self.pb2.ReplicationStatusResponse(
            role="primary", epoch=self.epoch,
            applied_seq=self._wal_seq(),
            lag_records=max(0, self._wal_seq() - self.acked_seq),
            lease_remaining_s=0.0, segments_received=0,
        )

    def handover_status(self) -> dict:
        """The ``handover`` block of ``/statusz`` and the REPL's
        ``/handover`` status line."""
        return {
            "stage": self._handover["stage"],
            "fence_seq": self._handover["fence_seq"],
            "standby_applied_seq": self._handover["standby_applied_seq"],
            "last_duration_s": (
                None if self.last_handover_s is None
                else round(self.last_handover_s, 4)
            ),
            "attempts": self.handovers_attempted,
            "completed": self.handovers_completed,
            "aborted": self.handovers_aborted,
            "redirecting_to": self.redirect_address,
        }

    # -- compaction coupling (DurabilityManager) ---------------------------

    def safe_compact_offset(self) -> int:
        """Compaction floor: bytes at or past this offset have not been
        acknowledged by the standby and must survive compaction."""
        return self.acked_offset

    def note_compacted(self, freed: int) -> None:
        """Compaction dropped ``freed`` bytes of the already-acked prefix;
        rebase the shipped-offset bookkeeping."""
        self.acked_offset = max(0, self.acked_offset - freed)

    # -- introspection -----------------------------------------------------

    def status(self) -> dict:
        """The admin REPL ``/replication`` payload (primary side) — also
        the ``replication`` block of the ops plane's ``/statusz``."""
        wal_seq = self._wal_seq()
        return {
            "role": "primary",
            "epoch": self.epoch,
            "mode": self.settings.mode,
            "peer": self.peer,
            "wal_seq": wal_seq,
            "acked_seq": self.acked_seq,
            "lag_records": max(0, wal_seq - self.acked_seq),
            "segments_shipped": self.segments_shipped,
            "last_ship_age_s": (
                None if self.last_ship_at is None
                else round(time.monotonic() - self.last_ship_at, 3)
            ),
            "fenced": self.fenced,
            "gap_stalled": self.gap_stalled,
            "audit_segments_shipped": self.audit_segments_shipped,
        }
