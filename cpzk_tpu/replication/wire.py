"""gRPC plumbing for the replication plane (hand-wired like auth).

``grpc_tools`` is unavailable in this environment, so the message module
comes from ``protoc`` via :mod:`cpzk_tpu.server.proto` and the service is
wired through grpcio's generic handler API on the server side and raw
``channel.unary_unary`` multicallables on the client side.
"""

from __future__ import annotations

import grpc

from ..server.proto import load_replication_pb2

SERVICE_NAME = "replication.ReplicationService"

_METHODS = {
    "ShipSegment": ("ShipSegmentRequest", "ShipSegmentResponse"),
    "ReplicationStatus": (
        "ReplicationStatusRequest", "ReplicationStatusResponse",
    ),
    "Handover": ("HandoverRequest", "HandoverResponse"),
}


def method_types(pb2):
    """{rpc name: (request class, response class)} for the three RPCs."""
    return {
        name: (getattr(pb2, req), getattr(pb2, resp))
        for name, (req, resp) in _METHODS.items()
    }


def make_replication_handler(impl) -> grpc.GenericRpcHandler:
    """Generic handler for an object with ``ship_segment``,
    ``replication_status``, and ``handover`` async methods — the
    :class:`StandbyReplica`, or (since ISSUE 18) the
    :class:`SegmentShipper`, which answers ship/status with refusals but
    serves ``Handover`` (phase "initiate") for the planned-operations
    plane."""
    pb2 = load_replication_pb2()
    types = method_types(pb2)
    methods = {
        "ShipSegment": impl.ship_segment,
        "ReplicationStatus": impl.replication_status,
        "Handover": impl.handover,
    }
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            methods[name],
            request_deserializer=types[name][0].FromString,
            response_serializer=types[name][1].SerializeToString,
        )
        for name in methods
    }
    return grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)


class ReplicationStub:
    """Client-side multicallables over an ``grpc.aio`` channel (the
    shipper's view of the standby)."""

    def __init__(self, channel: grpc.aio.Channel):
        pb2 = load_replication_pb2()
        self.pb2 = pb2
        types = method_types(pb2)
        self.ship_segment = channel.unary_unary(
            f"/{SERVICE_NAME}/ShipSegment",
            request_serializer=types["ShipSegment"][0].SerializeToString,
            response_deserializer=types["ShipSegment"][1].FromString,
        )
        self.replication_status = channel.unary_unary(
            f"/{SERVICE_NAME}/ReplicationStatus",
            request_serializer=types["ReplicationStatus"][0].SerializeToString,
            response_deserializer=types["ReplicationStatus"][1].FromString,
        )
        self.handover = channel.unary_unary(
            f"/{SERVICE_NAME}/Handover",
            request_serializer=types["Handover"][0].SerializeToString,
            response_deserializer=types["Handover"][1].FromString,
        )
