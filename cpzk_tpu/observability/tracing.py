"""Software spans + an in-memory completed-trace ring buffer.

This is deliberately not an OpenTelemetry dependency: the serving stack
needs (a) per-request stage breakdowns it can assert on in tests and show
an operator in the admin REPL, and (b) span names that line up with xprof
device timelines — both are a few hundred lines of stdlib, and the
container bakes no OTel SDK.  The shapes mirror OTel loosely (trace id,
named spans with start offsets and durations, attributes) so a real
exporter can be bolted onto :meth:`Tracer.completed` later.

Thread-safety: spans are recorded from batcher worker threads while the
owning RPC task awaits its future, so every mutation is lock-guarded.
The ring only holds *completed* traces; in-flight ones live in a dict
keyed by trace id (one active attempt per trace id at a time — a PR-1
retry reuses the id with a bumped attempt, producing one ring entry per
attempt).

``TraceAnnotation`` alignment: :class:`BatchStages` wraps each software
stage in ``jax.profiler.TraceAnnotation("cpzk.<stage>")`` when jax is
already imported, so an xprof capture (CPZK_XPROF_DIR) shows the exact
same stage names the ring buffer reports — software queue math and device
HLO sit on one timeline.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

from ..server import metrics
from . import flightrec
from .context import RequestContext, new_trace_id
from .flightrec import (
    STAGE_COMPILE,
    STAGE_DEVICE_WAIT,
    STAGE_EXECUTE,
    STAGE_MARSHAL,
    STAGE_THREAD_HOP,
    FlightRecord,
)

#: Canonical pipeline stage names (doc + test vocabulary).  ``queue_wait``
#: and ``device_dispatch`` bracket the device; ``pad_and_pack`` /
#: ``unpack`` are the host stages around it.  The flight recorder widens
#: ``device_dispatch`` into ``thread_hop``/``marshal``/``compile``/
#: ``execute`` sub-spans (see :mod:`.flightrec`).
STAGE_QUEUE_WAIT = "queue_wait"
STAGE_PAD_AND_PACK = "pad_and_pack"
STAGE_DEVICE_DISPATCH = "device_dispatch"
STAGE_UNPACK = "unpack"

#: Which stage feeds which latency histogram.
_STAGE_HISTOGRAM = {
    STAGE_PAD_AND_PACK: "tpu.batch.host_time",
    STAGE_UNPACK: "tpu.batch.host_time",
    STAGE_DEVICE_DISPATCH: "tpu.batch.device_time",
}


#: JSON payload schema tag of the ``/tracez`` dump (REPL + HTTP).
TRACEZ_SCHEMA = "cpzk-tracez/1"


@dataclass
class SpanRecord:
    """One completed stage within a trace."""

    name: str
    #: ``time.monotonic()`` at stage entry.
    start: float
    duration_s: float
    attrs: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "start": self.start,
            "duration_s": self.duration_s,
            "attrs": {k: v for k, v in sorted(self.attrs.items())},
        }


@dataclass
class TraceRecord:
    """One completed (or in-flight) request attempt."""

    trace_id: str
    name: str  # RPC / operation name
    attempt: int = 1
    start_wall: float = 0.0  # time.time() at trace start
    start: float = 0.0       # time.monotonic() at trace start
    duration_s: float = 0.0
    status: str = "in-flight"
    spans: list[SpanRecord] = field(default_factory=list)

    def span_names(self) -> list[str]:
        return [s.name for s in self.spans]

    def stage_seconds(self, name: str) -> float:
        """Total recorded duration of all spans named ``name``."""
        return sum(s.duration_s for s in self.spans if s.name == name)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "attempt": self.attempt,
            "start_wall": self.start_wall,
            "duration_s": self.duration_s,
            "status": self.status,
            "spans": [s.to_dict() for s in self.spans],
        }


class Tracer:
    """Active-trace registry + completed-trace ring buffer."""

    def __init__(self, capacity: int = 256, slow_request_s: float = 1.0):
        self._lock = threading.Lock()
        self._active: dict[str, TraceRecord] = {}
        self._ring: deque[TraceRecord] = deque(maxlen=max(1, capacity))
        #: Requests slower than this log a WARNING with their stage
        #: breakdown; 0 logs every request, None/negative disables.
        self.slow_request_s: float | None = slow_request_s

    # -- configuration ------------------------------------------------------

    def configure(
        self,
        capacity: int | None = None,
        slow_request_s: float | None = None,
    ) -> None:
        with self._lock:
            if capacity is not None and capacity != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=max(1, capacity))
            if slow_request_s is not None:
                self.slow_request_s = (
                    None if slow_request_s < 0 else slow_request_s
                )

    def clear(self) -> None:
        with self._lock:
            self._active.clear()
            self._ring.clear()

    # -- lifecycle ----------------------------------------------------------

    def start(self, ctx: RequestContext, name: str) -> TraceRecord:
        """Open a trace for ``ctx``.  A second ``start`` with the same
        trace id (a retry's next attempt) replaces the in-flight record —
        each attempt completes into its own ring entry."""
        rec = TraceRecord(
            trace_id=ctx.trace_id,
            name=name,
            attempt=ctx.attempt,
            start_wall=time.time(),
            start=time.monotonic(),
        )
        with self._lock:
            self._active[ctx.trace_id] = rec
        return rec

    def add_span(
        self,
        trace_id: str | None,
        name: str,
        start: float,
        duration_s: float,
        **attrs,
    ) -> None:
        """Attach a completed span to an in-flight trace; silently dropped
        when the trace is unknown (entry submitted outside an instrumented
        RPC, or the trace already finished)."""
        if not trace_id:
            return
        with self._lock:
            rec = self._active.get(trace_id)
            if rec is not None:
                rec.spans.append(
                    SpanRecord(name, start, max(0.0, duration_s), dict(attrs))
                )

    def add_span_many(
        self,
        trace_ids: list[str],
        name: str,
        start: float,
        duration_s: float,
        **attrs,
    ) -> None:
        """One batch-stage span fanned out to every member trace under a
        SINGLE lock acquisition, with one shared (never mutated) attrs
        dict.  A device batch coalesces hundreds of RPCs and emits ~6
        stages each — per-trace locking made the fan-out itself a
        milliseconds-scale slice of the dispatch wall that no stage span
        covered."""
        if not trace_ids:
            return
        dur = max(0.0, duration_s)
        with self._lock:
            for tid in trace_ids:
                rec = self._active.get(tid)
                if rec is not None:
                    rec.spans.append(SpanRecord(name, start, dur, attrs))

    def finish(
        self, trace_id: str, status: str, duration_s: float | None = None
    ) -> TraceRecord | None:
        """Complete the in-flight trace and move it into the ring."""
        with self._lock:
            rec = self._active.pop(trace_id, None)
            if rec is None:
                return None
            rec.status = status
            rec.duration_s = (
                duration_s
                if duration_s is not None
                else max(0.0, time.monotonic() - rec.start)
            )
            self._ring.append(rec)
        return rec

    def record_event(self, name: str, **attrs) -> TraceRecord:
        """A standalone zero-duration event (breaker flip, failover) as a
        single-span completed trace, so state transitions share the
        ``/tracez`` timeline with the requests they affected."""
        now = time.monotonic()
        rec = TraceRecord(
            trace_id=new_trace_id(),
            name=name,
            start_wall=time.time(),
            start=now,
            status="event",
        )
        rec.spans.append(SpanRecord(name, now, 0.0, dict(attrs)))
        with self._lock:
            self._ring.append(rec)
        return rec

    # -- inspection ---------------------------------------------------------

    def completed(self, n: int | None = None) -> list[TraceRecord]:
        """Most-recent-last snapshot of completed traces (last ``n``)."""
        with self._lock:
            out = list(self._ring)
        return out if n is None else out[-n:]

    def find(self, trace_id: str) -> list[TraceRecord]:
        """All completed attempts of one trace id, oldest first."""
        return [t for t in self.completed() if t.trace_id == trace_id]

    def payload(self, n: int | None = None) -> dict:
        """THE ``cpzk-tracez/1`` payload — the single serializer behind
        the REPL ``/tracez`` rendering and the ops plane's HTTP
        ``/tracez`` (one schema, one code path: the surfaces cannot
        drift)."""
        return {
            "schema": TRACEZ_SCHEMA,
            "dumped_at": time.time(),
            "traces": [t.to_dict() for t in self.completed(n)],
        }


_TRACER = Tracer()


def get_tracer() -> Tracer:
    """The process-wide tracer (configure via ``observability.configure``)."""
    return _TRACER


# -- xprof alignment ---------------------------------------------------------


def _trace_annotation(name: str):
    """``jax.profiler.TraceAnnotation`` when jax is already loaded (the
    serving process on the TPU path), else a null context — the software
    span must never pay a cold jax import on the inline CPU path."""
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return jax.profiler.TraceAnnotation(f"cpzk.{name}")
        except Exception:  # pragma: no cover - stub jax without profiler
            pass

    @contextmanager
    def _null():
        yield

    return _null()


class BatchStages:
    """Stage recorder handed to ``BatchVerifier.verify``: each stage is
    timed once per device batch and fanned out as a span to every member
    trace, observed into the stage latency histograms, and wrapped in a
    matching ``TraceAnnotation`` so xprof shows the same stage names.

    Flight-recorder integration: the batcher calls :meth:`mark_submit`
    just before handing the batch to a worker thread and
    :meth:`mark_worker_start` as the worker picks it up (the
    ``thread_hop`` span); the ``device_dispatch`` stage installs a
    :class:`~cpzk_tpu.observability.flightrec.DeviceSink` the backend
    reports marshal time and jit cache outcomes into, which this class
    turns into ``marshal``/``compile``/``execute`` sub-spans; and
    :meth:`finalize` folds everything into one
    :class:`~cpzk_tpu.observability.flightrec.FlightRecord`."""

    def __init__(
        self,
        tracer: Tracer | None,
        trace_ids: list[str],
        batch_size: int = 0,
        backend_label: str = "cpu",
        queue_wait_s: float = 0.0,
    ):
        self.tracer = tracer
        # deduped (order kept): a batch whose entries share one trace —
        # a VerifyProofBatch's items, or a whole VerifyProofStream chunk —
        # must get ONE span per stage on that trace, not one per entry
        # (64k-entry streams would append 64k identical spans per stage)
        self.trace_ids = list(dict.fromkeys(t for t in trace_ids if t))
        self.batch_size = batch_size
        self.backend_label = backend_label
        self.queue_wait_s = queue_wait_s
        #: dispatch-lane index, stamped by the LaneRouter at placement
        #: time ("mesh" for the big-batch mesh path; None = single-lane)
        self.lane: int | str | None = None
        #: accumulated seconds per stage name (incl. the widened vocab)
        self.durations: dict[str, float] = {}
        self._submitted_at: float | None = None
        self._staged_at: float | None = None
        self._worker_ended_at: float | None = None
        self._sink: flightrec.DeviceSink | None = None
        self._gap_s = 0.0

    # -- flight-recorder marks ---------------------------------------------

    def mark_submit(self) -> None:
        """Stamp the dispatch commit (event-loop side, just before the
        batch crosses to the dispatch lane or a worker thread)."""
        self._submitted_at = time.monotonic()

    def mark_worker_start(self) -> None:
        """Stamp worker-thread pickup; the elapsed time since
        :meth:`mark_submit` is the ``thread_hop`` span — the per-batch
        cost of crossing the batcher->worker seam (a condition-variable
        wakeup on the persistent dispatch lane; a thread-pool handoff on
        the legacy ``asyncio.to_thread`` path)."""
        if self._submitted_at is None:
            return
        now = time.monotonic()
        dur = max(0.0, now - self._submitted_at)
        self._emit(STAGE_THREAD_HOP, now - dur, dur)
        metrics.histogram("tpu.batch.thread_hop").observe(dur)

    def mark_staged(self) -> None:
        """Stamp host-prep completion (the batch entering a dispatch-lane
        staging slot, prepared but not yet on the device thread)."""
        self._staged_at = time.monotonic()

    def mark_device_start(self) -> None:
        """Stamp device-thread pickup; the elapsed time since
        :meth:`mark_staged` is the ``device_wait`` span — staging-slot
        dwell while the device thread finishes the previous batch (the
        double-buffering overlap made visible).  No-op when the batch
        never entered a staging slot (single-thread inline verify)."""
        if self._staged_at is None:
            return
        now = time.monotonic()
        dur = max(0.0, now - self._staged_at)
        self._emit(STAGE_DEVICE_WAIT, now - dur, dur)
        metrics.histogram("tpu.batch.device_wait").observe(dur)

    def mark_worker_end(self) -> None:
        """Stamp verify completion on the worker thread; the record's
        ``wall_s`` is submit -> here, the interval the widened stages
        tile (the hop back to the event loop is scheduling latency the
        RPC trace already covers, not device-plane work)."""
        self._worker_ended_at = time.monotonic()

    def _emit(self, name: str, start: float, dur: float, **attrs) -> None:
        self.durations[name] = self.durations.get(name, 0.0) + dur
        if self.tracer is not None:
            self.tracer.add_span_many(
                self.trace_ids, name, start, dur,
                batch=self.batch_size, backend=self.backend_label,
                **attrs,
            )

    @contextmanager
    def stage(self, name: str):
        device = name == STAGE_DEVICE_DISPATCH
        token = None
        if device:
            self._sink, token = flightrec.install_sink()
        t0 = time.monotonic()
        try:
            with _trace_annotation(name):
                yield
        finally:
            dur = time.monotonic() - t0
            if device:
                flightrec.uninstall_sink(token)
        hist = _STAGE_HISTOGRAM.get(name)
        if hist == "tpu.batch.device_time":
            metrics.histogram(hist, labelnames=("backend",)).labels(
                backend=self.backend_label
            ).observe(dur)
        elif hist is not None:
            metrics.histogram(hist).observe(dur)
        self._emit(name, t0, dur)
        if device:
            self._split_device(t0, dur)

    def _split_device(self, t0: float, dur: float) -> None:
        """Widen the ``device_dispatch`` interval into ``marshal`` /
        ``compile`` / ``execute`` from the sink the backend reported
        into.  Attribution rule: marshal is measured directly; when any
        program in the batch was a first-sight compile, the non-marshal
        remainder is ``compile`` (a first call at a new padded shape is
        trace+compile dominated), otherwise it is ``execute``.  A
        backend that reports nothing (the CPU oracle) is pure
        ``execute``."""
        sink = self._sink or flightrec.DeviceSink()
        marshal = min(max(0.0, sink.marshal_s), dur)
        rest = max(0.0, dur - marshal)
        compile_s, execute_s = (
            (rest, 0.0) if sink.jit_misses > 0 else (0.0, rest)
        )
        if marshal > 0.0:
            self._emit(STAGE_MARSHAL, t0, marshal)
        if compile_s > 0.0:
            self._emit(
                STAGE_COMPILE, t0 + marshal, compile_s,
                shapes=",".join(sink.compiled),
            )
            metrics.histogram("tpu.jit.compile_time").observe(compile_s)
        self._emit(STAGE_EXECUTE, t0 + marshal + compile_s, execute_s)
        self._gap_s = flightrec.get_flight_recorder().note_device_interval(
            t0, t0 + dur
        )

    def finalize(self, wall_s: float) -> "flightrec.FlightRecord":
        """Fold the recorded stages into one flight record (called by the
        batcher once the dispatch's results are in).  ``wall_s`` is the
        event-loop submit->resolved wall time, used as a fallback; when
        the worker marks ran, the record's wall is submit->verify-end —
        the interval the widened stages tile, which is what the stage-sum
        invariant is pinned against."""
        if self._submitted_at is not None and self._worker_ended_at is not None:
            wall_s = max(0.0, self._worker_ended_at - self._submitted_at)
        sink = self._sink or flightrec.DeviceSink()
        lanes = sink.lanes
        rows = sink.rows or self.batch_size
        occupancy = (rows / lanes) if lanes > 0 else 1.0
        rec = FlightRecord(
            batch=self.batch_size,
            lane=self.lane,
            lanes=lanes,
            occupancy=occupancy,
            pad_waste=max(0.0, 1.0 - occupancy),
            backend=self.backend_label,
            queue_wait_s=self.queue_wait_s,
            stages_s=dict(self.durations),
            wall_s=wall_s,
            dispatch_gap_s=self._gap_s,
            jit_hits=sink.jit_hits,
            jit_misses=sink.jit_misses,
            compiled=list(sink.compiled),
        )
        return flightrec.get_flight_recorder().record(rec)


# -- operator rendering -------------------------------------------------------


def format_trace(rec: dict) -> str:
    """One ``/tracez`` line: id, name, outcome, total, stage breakdown.
    Consumes a serialized trace dict (``TraceRecord.to_dict``) — the
    REPL renders the same payload the HTTP endpoint serves."""
    stages = " ".join(
        f"{s['name']}={s['duration_s'] * 1000:.2f}ms" for s in rec["spans"]
    )
    head = (
        f"{rec['trace_id'][:16]} {rec['name']} {rec['status']} "
        f"total={rec['duration_s'] * 1000:.2f}ms attempt={rec['attempt']}"
    )
    return f"{head} {stages}".rstrip()


def format_tracez(payload: dict, limit: int = 20) -> str:
    """The admin REPL ``/tracez`` body: last ``limit`` traces, newest
    first, one line each.  Takes the :meth:`Tracer.payload` dict — the
    REPL is a text rendering of EXACTLY the JSON the HTTP endpoint
    serves."""
    recent = payload.get("traces", [])[-limit:][::-1]
    if not recent:
        return "no completed traces yet"
    lines = [f"last {len(recent)} completed traces (newest first):"]
    lines += ["  " + format_trace(t) for t in recent]
    return "\n".join(lines)
