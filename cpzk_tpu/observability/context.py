"""Request trace context: minted at the client, carried over gRPC metadata.

A :class:`RequestContext` identifies one logical request across its whole
path — client stub, wire, service handler, batching queue, device dispatch
— and across PR-1 retries: the trace id is minted once per logical call
and stays stable while ``attempt`` increments, so a retried RPC shows up
as one trace with several completions rather than unrelated ids.

The wire encoding is two ASCII metadata keys (``cpzk-trace-id``,
``cpzk-attempt``); unknown or absent metadata mints a fresh server-side
context, so uninstrumented clients still get traced from the service
boundary on.  ``current_context`` is a contextvar set for the duration of
each instrumented RPC handler — the JSON log formatter and any code
downstream of the handler can read the active trace id without threading
it through every signature.
"""

from __future__ import annotations

import contextvars
import os
from dataclasses import dataclass, field

#: gRPC metadata keys (lowercase per the metadata spec).
TRACE_ID_KEY = "cpzk-trace-id"
ATTEMPT_KEY = "cpzk-attempt"
PARENT_SPAN_KEY = "cpzk-parent-span"

#: The trace context of the RPC currently being served on this task, or
#: None outside an instrumented handler.
current_context: contextvars.ContextVar["RequestContext | None"] = (
    contextvars.ContextVar("cpzk_request_context", default=None)
)


def new_trace_id() -> str:
    """128-bit random hex trace id (W3C traceparent sized)."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """64-bit random hex span id."""
    return os.urandom(8).hex()


@dataclass
class RequestContext:
    """Identity + position of one request in the serving pipeline."""

    trace_id: str = field(default_factory=new_trace_id)
    #: 1-based attempt number; bumped by the client retry loop, stable
    #: trace_id across attempts.
    attempt: int = 1
    #: Span id of the caller's enclosing span ("" = root).
    parent_span: str = ""
    #: Absolute ``time.monotonic()`` RPC deadline, when known.
    deadline: float | None = None

    def child(self) -> "RequestContext":
        """Context for the next attempt of the same logical request."""
        return RequestContext(
            trace_id=self.trace_id,
            attempt=self.attempt + 1,
            parent_span=self.parent_span,
            deadline=self.deadline,
        )

    # -- gRPC metadata ------------------------------------------------------

    def to_metadata(self) -> tuple[tuple[str, str], ...]:
        md = [(TRACE_ID_KEY, self.trace_id), (ATTEMPT_KEY, str(self.attempt))]
        if self.parent_span:
            md.append((PARENT_SPAN_KEY, self.parent_span))
        return tuple(md)

    @classmethod
    def from_metadata(cls, metadata, deadline: float | None = None) -> "RequestContext":
        """Extract from an iterable of (key, value) metadata pairs; any
        missing or malformed field falls back to a freshly minted value
        (a garbage attempt header must not kill the RPC)."""
        trace_id = ""
        attempt = 1
        parent = ""
        for key, value in metadata or ():
            k = key.lower()
            if k == TRACE_ID_KEY:
                trace_id = str(value)
            elif k == ATTEMPT_KEY:
                try:
                    attempt = max(1, int(value))
                except (TypeError, ValueError):
                    attempt = 1
            elif k == PARENT_SPAN_KEY:
                parent = str(value)
        return cls(
            trace_id=trace_id or new_trace_id(),
            attempt=attempt,
            parent_span=parent,
            deadline=deadline,
        )

    @classmethod
    def from_grpc(cls, context, deadline: float | None = None) -> "RequestContext":
        """Extract from a gRPC servicer context; tolerates hand-rolled
        test contexts without ``invocation_metadata``."""
        try:
            md = context.invocation_metadata()
        except Exception:
            md = ()
        return cls.from_metadata(md, deadline=deadline)
