"""End-to-end request tracing + latency-breakdown telemetry.

The diagnostic substrate for the TPU serving plane (ROADMAP north star:
know *where* a request spent its time before optimizing it):

- :mod:`.context` — ``RequestContext`` minted at the client, carried in
  gRPC metadata, stable across retries;
- :mod:`.tracing` — per-stage software spans (``queue_wait``,
  ``pad_and_pack``, ``device_dispatch``, ``unpack``), a completed-trace
  ring buffer behind the admin REPL's ``/tracez``, and
  ``jax.profiler.TraceAnnotation`` alignment so xprof shows the same
  stage names;
- :mod:`.instrument` — the ``traced_rpc`` decorator owning the
  requests/outcome/duration metric lifecycle for every RPC handler;
- :mod:`.logs` — the opt-in JSON log formatter with automatic trace-id
  correlation.

``configure(settings)`` applies an ``[observability]`` config section to
the process-wide tracer, metric buckets, and log format in one call.
"""

from __future__ import annotations

from .context import RequestContext, current_context, new_trace_id
from .flightrec import (
    FlightRecord,
    FlightRecorder,
    format_flightrec,
    get_flight_recorder,
)
from .instrument import rpc_deadline, traced_rpc, traced_stream_rpc
from .logs import JsonLogFormatter, enable_json_logs
from .opsplane import OpsPlane, OpsSources
from .slo import SloEngine
from .tracing import (
    BatchStages,
    SpanRecord,
    TraceRecord,
    Tracer,
    format_trace,
    format_tracez,
    get_tracer,
)

__all__ = [
    "BatchStages",
    "FlightRecord",
    "FlightRecorder",
    "JsonLogFormatter",
    "OpsPlane",
    "OpsSources",
    "RequestContext",
    "SloEngine",
    "SpanRecord",
    "TraceRecord",
    "Tracer",
    "configure",
    "current_context",
    "enable_json_logs",
    "format_flightrec",
    "format_trace",
    "format_tracez",
    "get_flight_recorder",
    "get_tracer",
    "new_trace_id",
    "rpc_deadline",
    "traced_rpc",
    "traced_stream_rpc",
]


def configure(settings) -> None:
    """Apply an ``ObservabilitySettings`` (see ``server/config.py``):
    trace ring capacity, slow-request threshold, histogram buckets, the
    flight-recorder ring + compile-storm window, and the JSON log
    formatter opt-in."""
    from ..server import metrics

    get_tracer().configure(
        capacity=settings.trace_ring,
        slow_request_s=(
            -1.0 if settings.slow_request_ms < 0
            else settings.slow_request_ms / 1000.0
        ),
    )
    get_flight_recorder().configure(
        capacity=settings.flight_ring,
        storm_threshold=settings.compile_storm_threshold,
    )
    buckets = settings.parsed_buckets()
    if buckets:
        metrics.set_default_buckets(buckets)
    if settings.json_logs:
        enable_json_logs()
