"""Device-plane flight recorder: per-batch dispatch accounting.

PR-2's four spans (``queue_wait``/``pad_and_pack``/``device_dispatch``/
``unpack``) tell an operator *that* the serving path starves the device,
not *why* — the 46x device-serving collapse (PROFILE.md §7c) hides
inside ``device_dispatch``, which conflates the ``asyncio.to_thread``
hop, host limb marshalling, first-sight XLA compiles, and actual device
execution.  This module is the always-on instrument that splits them:

- a :class:`DeviceSink` contextvar the backend reports into from the
  worker thread (``marshal`` seconds, jit cache hits/misses per padded
  shape, lane counts) without the backend ever importing the tracer;
- a :class:`FlightRecorder` ring of per-batch :class:`FlightRecord` rows
  — batch size, padded lanes, occupancy, pad waste, jit hit/miss, the
  widened stage breakdown, and **dispatch gap**: device idle time
  between consecutive dispatches, the direct measure of "serving
  starves the silicon";
- gauges/histograms on top (``tpu.device.busy_fraction``,
  ``tpu.batch.occupancy``, ``tpu.dispatch.gap``, ``tpu.jit.*``, a
  rolling proofs/s EWMA) plus a compile-storm WARNING when first-sight
  compiles exceed a threshold per window — the signature of a
  misconfigured padding schedule recompiling per batch size;
- an on-demand deep capture (``/profile``) wrapping
  ``jax.profiler.start_trace``/``stop_trace``, guarded against
  concurrent captures, whose timeline carries the same ``cpzk.<stage>``
  annotation names as the software spans.

Everything here is batch-shape metadata — no statement bytes, proofs,
or secrets ever enter a record, so dumps are safe to attach to bugs.

Thread-safety: records are built by batcher worker threads while the
REPL/SIGUSR2 read the ring from the event-loop thread; every ring and
window mutation is lock-guarded.
"""

from __future__ import annotations

import contextvars
import json
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ..server import metrics

log = logging.getLogger("cpzk_tpu.observability.flightrec")

#: JSON dump schema tag (bump on incompatible record changes).
SCHEMA = "cpzk-flightrec/1"

#: Stage vocabulary widening (the split of PR-2's ``device_dispatch``).
STAGE_THREAD_HOP = "thread_hop"
STAGE_DEVICE_WAIT = "device_wait"
STAGE_MARSHAL = "marshal"
STAGE_COMPILE = "compile"
STAGE_EXECUTE = "execute"

#: Stage keys of one flight record, dispatch order.  ``queue_wait`` is
#: carried separately (per-entry mean) — these tile the submit->resolve
#: wall time, which is the sum invariant the tests pin.  ``device_wait``
#: is the dispatch lane's staging-slot dwell: a host-prepared batch
#: waiting for the device thread to finish the previous batch (near the
#: previous batch's device time under double-buffered overlap, ~0 when
#: the device is the idle side).
RECORD_STAGES = (
    STAGE_THREAD_HOP,
    "pad_and_pack",
    STAGE_DEVICE_WAIT,
    STAGE_MARSHAL,
    STAGE_COMPILE,
    STAGE_EXECUTE,
    "unpack",
)


# -- device sink (backend -> recorder seam) -----------------------------------


@dataclass
class DeviceSink:
    """Per-batch accumulator the backend reports device-plane facts into.

    Installed (contextvar) by the stage recorder around the
    ``device_dispatch`` stage in the worker thread; the backend calls the
    module-level ``note_*`` helpers, which no-op when no sink is active
    (benches and direct ``BatchVerifier`` use stay zero-overhead)."""

    marshal_s: float = 0.0
    jit_hits: int = 0
    jit_misses: int = 0
    compiled: list[str] = field(default_factory=list)
    rows: int = 0
    lanes: int = 0


_SINK: contextvars.ContextVar[DeviceSink | None] = contextvars.ContextVar(
    "cpzk_device_sink", default=None
)


def install_sink() -> tuple[DeviceSink, contextvars.Token]:
    sink = DeviceSink()
    return sink, _SINK.set(sink)


def uninstall_sink(token: contextvars.Token) -> None:
    _SINK.reset(token)


def note_marshal(duration_s: float) -> None:
    """Host SoA limb-marshal seconds within the current device dispatch."""
    sink = _SINK.get()
    if sink is not None:
        sink.marshal_s += max(0.0, duration_s)


def note_jit(shape: str, first_sight: bool) -> None:
    """One jitted-program cache check: ``first_sight`` means this padded
    shape has never been dispatched by this process, so the call pays an
    XLA trace+compile (its cost is attributed to the ``compile`` stage)."""
    metrics.counter("tpu.jit.cache", labelnames=("outcome",)).labels(
        outcome="miss" if first_sight else "hit"
    ).inc()
    if first_sight:
        metrics.counter("tpu.jit.compiles", labelnames=("shape",)).labels(
            shape=shape
        ).inc()
        get_flight_recorder().note_compile_event(shape)
    sink = _SINK.get()
    if sink is not None:
        if first_sight:
            sink.jit_misses += 1
            sink.compiled.append(shape)
        else:
            sink.jit_hits += 1


def note_lanes(rows: int, lanes: int) -> None:
    """Padded device-lane accounting for the current dispatch: occupancy
    = true rows / padded lanes (the complement of ``tpu.batch.pad_waste``)."""
    if lanes > 0:
        metrics.gauge("tpu.batch.occupancy").set(rows / lanes)
    sink = _SINK.get()
    if sink is not None:
        sink.rows = rows
        sink.lanes = lanes


# -- flight records -----------------------------------------------------------


@dataclass
class FlightRecord:
    """One device batch through the batcher->backend seam."""

    seq: int = 0
    ts: float = 0.0            # wall clock at record time
    batch: int = 0             # true rows in the batch
    lane: int | str | None = None  # dispatch lane index ("mesh" for the
                               # big-batch mesh path; None = single-lane)
    lanes: int = 0             # padded device lanes (0 = no device padding)
    occupancy: float = 1.0     # batch / lanes (1.0 without device padding)
    pad_waste: float = 0.0     # 1 - occupancy
    backend: str = "cpu"
    queue_wait_s: float = 0.0  # mean over member entries
    stages_s: dict[str, float] = field(default_factory=dict)
    wall_s: float = 0.0        # dispatch commit -> results returned
    dispatch_gap_s: float = 0.0  # device idle before this dispatch
    jit_hits: int = 0
    jit_misses: int = 0
    compiled: list[str] = field(default_factory=list)

    def stage_sum_s(self) -> float:
        """Sum of the widened stage spans — the tests pin this against
        ``wall_s`` (within 10%): the decomposition must tile the wall."""
        return sum(self.stages_s.get(name, 0.0) for name in RECORD_STAGES)

    def to_dict(self) -> dict:
        return {
            "seq": self.seq,
            "ts": self.ts,
            "batch": self.batch,
            "lane": self.lane,
            "lanes": self.lanes,
            "occupancy": round(self.occupancy, 6),
            "pad_waste": round(self.pad_waste, 6),
            "backend": self.backend,
            "queue_wait_s": self.queue_wait_s,
            "stages_s": {k: v for k, v in sorted(self.stages_s.items())},
            "wall_s": self.wall_s,
            "dispatch_gap_s": self.dispatch_gap_s,
            "jit_hits": self.jit_hits,
            "jit_misses": self.jit_misses,
            "compiled": list(self.compiled),
        }


class FlightRecorder:
    """Fixed-size ring of :class:`FlightRecord` rows + the derived
    device-plane gauges.  Always on; the per-batch cost is a lock, a
    deque append, and a handful of float ops (<2% of even the CPU
    serving path — pinned by the bench overhead test)."""

    def __init__(
        self,
        capacity: int = 512,
        storm_threshold: int = 8,
        storm_window_s: float = 60.0,
        clock=time.monotonic,
    ):
        self._lock = threading.Lock()
        self._ring: deque[FlightRecord] = deque(maxlen=max(1, capacity))
        self._clock = clock
        self._seq = 0
        # device-idle accounting between consecutive dispatches
        self._last_device_end: float | None = None
        self._busy_ewma = 0.0
        # rolling serving throughput
        self._last_record_at: float | None = None
        self._pps_ewma = 0.0
        # compile-storm window
        self.storm_threshold = max(1, storm_threshold)
        self.storm_window_s = storm_window_s
        self._compile_times: deque[float] = deque()
        self._storm_warned_at: float | None = None

    # -- configuration ------------------------------------------------------

    def configure(
        self,
        capacity: int | None = None,
        storm_threshold: int | None = None,
        storm_window_s: float | None = None,
    ) -> None:
        with self._lock:
            if capacity is not None and capacity != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=max(1, capacity))
            if storm_threshold is not None:
                self.storm_threshold = max(1, storm_threshold)
            if storm_window_s is not None:
                self.storm_window_s = storm_window_s

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._last_device_end = None
            self._busy_ewma = 0.0
            self._last_record_at = None
            self._pps_ewma = 0.0
            self._compile_times.clear()
            self._storm_warned_at = None

    # -- device-idle / compile-storm signals --------------------------------

    def note_device_interval(self, start: float, end: float) -> float:
        """Account one device-busy interval [start, end] (monotonic
        seconds); returns the **dispatch gap** — device idle time since
        the previous dispatch ended (0 for the first dispatch, and 0
        under pipelined overlap, where the device never went idle)."""
        with self._lock:
            if self._last_device_end is None:
                gap = 0.0
            else:
                gap = max(0.0, start - self._last_device_end)
            self._last_device_end = max(self._last_device_end or end, end)
            busy = max(0.0, end - start)
            frac = busy / (busy + gap) if busy + gap > 0 else 0.0
            self._busy_ewma = (
                frac if self._busy_ewma == 0.0
                else 0.8 * self._busy_ewma + 0.2 * frac
            )
            busy_ewma = self._busy_ewma
        metrics.histogram("tpu.dispatch.gap").observe(gap)
        metrics.gauge("tpu.device.busy_fraction").set(busy_ewma)
        return gap

    def note_compile_event(self, shape: str) -> None:
        """One first-sight compile; WARNING when the rolling window
        exceeds the storm threshold (at most once per window)."""
        now = self._clock()
        with self._lock:
            self._compile_times.append(now)
            horizon = now - self.storm_window_s
            while self._compile_times and self._compile_times[0] < horizon:
                self._compile_times.popleft()
            storm = len(self._compile_times) > self.storm_threshold
            warned_recently = (
                self._storm_warned_at is not None
                and now - self._storm_warned_at < self.storm_window_s
            )
            count = len(self._compile_times)
            if storm and not warned_recently:
                self._storm_warned_at = now
            else:
                storm = False
        if storm:
            log.warning(
                "compile storm: %d first-sight jit compiles in the last "
                "%.0fs (threshold %d, latest shape %s) — the padding "
                "schedule is minting fresh device programs per batch; "
                "check CPZK_LANE_QUANTUM / batch sizing",
                count, self.storm_window_s, self.storm_threshold, shape,
            )

    # -- recording ----------------------------------------------------------

    def record(self, rec: FlightRecord) -> FlightRecord:
        now = self._clock()
        with self._lock:
            self._seq += 1
            rec.seq = self._seq
            if rec.ts == 0.0:
                rec.ts = time.time()
            if self._last_record_at is not None and rec.batch > 0:
                dt = now - self._last_record_at
                if dt > 0:
                    inst = rec.batch / dt
                    self._pps_ewma = (
                        inst if self._pps_ewma == 0.0
                        else 0.8 * self._pps_ewma + 0.2 * inst
                    )
            self._last_record_at = now
            pps = self._pps_ewma
            self._ring.append(rec)
        metrics.gauge("tpu.throughput.proofs_per_s").set(pps)
        metrics.gauge("tpu.batch.occupancy").set(rec.occupancy)
        return rec

    # -- inspection / dump --------------------------------------------------

    def snapshot(self, n: int | None = None) -> list[FlightRecord]:
        """Most-recent-last copy of the ring (last ``n``)."""
        with self._lock:
            out = list(self._ring)
        return out if n is None else out[-n:]

    def proofs_per_s(self) -> float:
        with self._lock:
            return self._pps_ewma

    def payload(self, n: int | None = None) -> dict:
        """THE ``cpzk-flightrec/1`` payload — the single serializer behind
        the REPL ``/flightrec`` rendering, the SIGUSR2 dump, and the ops
        plane's HTTP ``/flightrec`` (one schema, one code path: the three
        surfaces cannot drift)."""
        return {
            "schema": SCHEMA,
            "dumped_at": time.time(),
            "proofs_per_s_ewma": self.proofs_per_s(),
            "records": [r.to_dict() for r in self.snapshot(n)],
        }

    def to_json(self, n: int | None = None) -> str:
        return json.dumps(self.payload(n), indent=2, sort_keys=True)

    def dump(self, path: str, n: int | None = None) -> str:
        """Write the ring as JSON to ``path`` (the SIGUSR2 hook target).
        Serialization happens outside the lock via :meth:`snapshot`."""
        text = self.to_json(n)
        with open(path, "w", encoding="utf-8") as f:
            f.write(text)
        return path


_RECORDER = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    """The process-wide flight recorder (configure via
    ``observability.configure``)."""
    return _RECORDER


# -- operator rendering -------------------------------------------------------


def format_record(rec: dict) -> str:
    """One ``/flightrec`` line: shape, occupancy, gap, stage breakdown.
    Consumes a serialized record dict (``FlightRecord.to_dict``) — the
    REPL renders the same payload the HTTP endpoint serves."""
    stages_s = rec.get("stages_s", {})
    stages = " ".join(
        f"{name}={stages_s.get(name, 0.0) * 1000:.2f}ms"
        for name in RECORD_STAGES
    )
    lane = rec.get("lane")
    lane_tag = "" if lane is None else f"lane={lane} "
    return (
        f"#{rec['seq']} {lane_tag}n={rec['batch']} lanes={rec['lanes']} "
        f"occ={rec['occupancy']:.2f} gap={rec['dispatch_gap_s'] * 1000:.2f}ms "
        f"wait={rec['queue_wait_s'] * 1000:.2f}ms {stages} "
        f"wall={rec['wall_s'] * 1000:.2f}ms "
        f"jit={rec['jit_hits']}h/{rec['jit_misses']}m {rec['backend']}"
    )


def format_flightrec(payload: dict, limit: int = 20) -> str:
    """The admin REPL ``/flightrec`` body: last ``limit`` batches, newest
    first, one line each, plus the rolling throughput header.  Takes the
    :meth:`FlightRecorder.payload` dict — the REPL is a text rendering
    of EXACTLY the JSON the HTTP endpoint and SIGUSR2 dump emit."""
    recent = payload.get("records", [])[-limit:][::-1]
    if not recent:
        return "no recorded batches yet"
    lines = [
        f"last {len(recent)} device batches (newest first), "
        f"~{payload.get('proofs_per_s_ewma', 0.0):.0f} proofs/s EWMA:"
    ]
    lines += ["  " + format_record(r) for r in recent]
    return "\n".join(lines)


# -- on-demand deep capture (xprof) -------------------------------------------

_PROFILE_LOCK = threading.Lock()
_PROFILE_DIR: str | None = None


def profile_active() -> str | None:
    """The capture directory of an in-flight profile, or None."""
    with _PROFILE_LOCK:
        return _PROFILE_DIR


def start_profile(logdir: str) -> bool:
    """Begin a ``jax.profiler`` trace into ``logdir``; False when a
    capture is already running (concurrent captures corrupt the trace)."""
    global _PROFILE_DIR
    import jax

    with _PROFILE_LOCK:
        if _PROFILE_DIR is not None:
            return False
        jax.profiler.start_trace(logdir)
        _PROFILE_DIR = logdir
        return True


def stop_profile() -> str | None:
    """End the in-flight capture; returns its directory (None when no
    capture was running)."""
    global _PROFILE_DIR
    import jax

    with _PROFILE_LOCK:
        if _PROFILE_DIR is None:
            return None
        logdir, _PROFILE_DIR = _PROFILE_DIR, None
        jax.profiler.stop_trace()
        return logdir
