"""SLO engine: multi-window burn rates over the existing RPC families.

Raw counters tell an operator what happened; an SLO tells them whether to
page.  This module turns the labeled families the ``traced_rpc`` /
``traced_stream_rpc`` decorators already maintain — ``rpc.requests{rpc,
outcome}`` and ``rpc.duration{rpc}`` — into per-RPC objectives and
**burn rates** over the standard multi-window pairs (5m/1h fast,
30m/6h slow, the Google SRE workbook alerting scheme):

- **availability**: the fraction of requests that must succeed
  (``[slo] availability_target``, default 99.9%).  Burn over a window =
  observed error ratio / allowed error ratio — burn 1.0 spends the error
  budget exactly at the rate that exhausts it at the window's end of the
  SLO period, burn 14.4 exhausts a 30-day budget in 2 days.
- **latency**: a per-RPC-class target mean (``[slo] latency_ms``, with
  built-in defaults per RPC).  Latency burn = windowed mean duration /
  target — above 1.0 the class is out of its latency objective.

``slo.burn_rate{rpc,window}`` exports the worse of the two per window;
``slo.error_budget_remaining{rpc}`` exports the unspent fraction of the
availability budget over the slow (6h) window.  A page-worthy burn —
BOTH windows of a pair above the pair's threshold (defaults 14.4 fast /
6.0 slow) — logs one WARNING per window period per RPC and lands a
``slo_burn`` event in the trace ring, so pages and request traces share
a timeline.

The engine is pull-based and pure over the metrics facade: ``tick()``
samples the cumulative counters (both backings — the no-prometheus
fallback tracks identical numbers) into a bounded per-RPC ring and
derives every window from deltas, so it never instruments the serving
path.  The daemon ticks it on ``[slo] tick_interval_ms``; the ops
plane's ``/slo`` endpoint ticks once more on demand so the payload is
always current.  ``clock`` is injectable, which is how the synthetic
error-storm test drives hours of budget math in milliseconds.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass

from ..server import metrics

log = logging.getLogger("cpzk_tpu.observability.slo")

#: Schema tag of the ``/slo`` JSON payload.
SCHEMA = "cpzk-slo/1"

#: The RPC classes with objectives (the ``rpc`` label values the
#: ``traced_rpc`` decorators emit).
RPC_CLASSES = (
    "Register",
    "RegisterBatch",
    "CreateChallenge",
    "VerifyProof",
    "VerifyProofBatch",
    "VerifyProofStream",
)

#: (label, seconds) of every burn window, dashboard order.
WINDOWS: tuple[tuple[str, float], ...] = (
    ("5m", 300.0),
    ("30m", 1800.0),
    ("1h", 3600.0),
    ("6h", 21600.0),
)
_WINDOW_S = dict(WINDOWS)

#: The multi-window page pairs: a page fires only when BOTH windows of a
#: pair burn above the pair's threshold (short window = it is happening
#: now; long window = it is not a blip).
FAST_PAIR = ("5m", "1h")
SLOW_PAIR = ("30m", "6h")

#: Built-in latency targets (ms, windowed mean) per RPC class —
#: overridable per class via ``[slo] latency_ms``.  Batch and stream
#: RPCs carry device-quantum batches, so their targets are wider.
DEFAULT_LATENCY_MS: dict[str, float] = {
    "Register": 250.0,
    "RegisterBatch": 1000.0,
    "CreateChallenge": 100.0,
    "VerifyProof": 500.0,
    "VerifyProofBatch": 2000.0,
    "VerifyProofStream": 30000.0,
}


@dataclass
class _Sample:
    """One cumulative-counter observation for one RPC class."""

    t: float          # engine clock at sample time
    ok: float         # rpc.requests{outcome="success"} cumulative
    fail: float       # rpc.requests{outcome="failure"} cumulative
    dur_count: float  # rpc.duration observation count cumulative
    dur_sum: float    # rpc.duration seconds sum cumulative


class SloEngine:
    """Windowed burn-rate computation over the RPC metric families."""

    def __init__(self, settings, clock=time.monotonic):
        self.settings = settings
        self._clock = clock
        self._lock = threading.Lock()
        #: per-RPC ring of cumulative samples, pruned past the slow window
        self._samples: dict[str, deque[_Sample]] = {
            rpc: deque() for rpc in RPC_CLASSES
        }
        #: (rpc, pair) -> engine-clock time of the last WARNING, so a
        #: sustained burn warns once per short-window period, not per tick
        self._warned_at: dict[tuple[str, str], float] = {}
        self.latency_ms = dict(DEFAULT_LATENCY_MS)
        self.latency_ms.update(settings.parsed_latency_ms())
        #: allowed error ratio (the denominator of availability burn)
        self.allowed_error = max(1e-9, 1.0 - settings.availability_target)
        #: last computed per-RPC view (the ``/slo`` payload body)
        self._last: dict[str, dict] = {}
        self._pages = 0
        #: fleet partition label ("" outside a fleet): stamped into the
        #: ``/slo`` payload so per-partition dashboards can join burn
        #: rates across the fleet without scraping instance labels
        self.partition = ""

    # -- sampling ------------------------------------------------------------

    def _read_rpc(self, rpc: str) -> tuple[float, float, float, float]:
        ok = metrics.read(
            "rpc.requests", labels={"rpc": rpc, "outcome": "success"}
        )
        fail = metrics.read(
            "rpc.requests", labels={"rpc": rpc, "outcome": "failure"}
        )
        dur_count, dur_sum = metrics.read_histogram(
            "rpc.duration", labels={"rpc": rpc}
        )
        return ok, fail, dur_count, dur_sum

    def _window_delta(
        self, ring: deque[_Sample], now_s: _Sample, window_s: float
    ) -> tuple[float, float, float, float, float]:
        """(covered_s, d_requests, d_failures, d_dur_count, d_dur_sum)
        between ``now_s`` and the newest sample at least ``window_s`` old
        (or the oldest available — a young process reports over the
        history it actually has)."""
        base = ring[0]
        horizon = now_s.t - window_s
        for s in ring:
            if s.t > horizon:
                break
            base = s
        return (
            max(0.0, now_s.t - base.t),
            max(0.0, (now_s.ok + now_s.fail) - (base.ok + base.fail)),
            max(0.0, now_s.fail - base.fail),
            max(0.0, now_s.dur_count - base.dur_count),
            max(0.0, now_s.dur_sum - base.dur_sum),
        )

    # -- the tick ------------------------------------------------------------

    def tick(self) -> dict[str, dict]:
        """Sample the counters, recompute every (rpc, window) burn rate,
        export the gauges, and fire page WARNINGs.  Returns the per-RPC
        view (also kept for :meth:`snapshot`).  Thread-safe: the daemon's
        tick task and an on-demand ``/slo`` render may overlap."""
        now = self._clock()
        horizon = now - _WINDOW_S[SLOW_PAIR[1]] - 1.0
        burn_gauge = metrics.gauge(
            "slo.burn_rate", labelnames=("rpc", "window")
        )
        budget_gauge = metrics.gauge(
            "slo.error_budget_remaining", labelnames=("rpc",)
        )
        with self._lock:
            view: dict[str, dict] = {}
            for rpc in RPC_CLASSES:
                ok, fail, dc, ds = self._read_rpc(rpc)
                sample = _Sample(now, ok, fail, dc, ds)
                ring = self._samples[rpc]
                ring.append(sample)
                while len(ring) > 1 and ring[1].t <= horizon:
                    ring.popleft()
                target_ms = self.latency_ms.get(
                    rpc, DEFAULT_LATENCY_MS["VerifyProof"]
                )
                windows: dict[str, dict] = {}
                for label, seconds in WINDOWS:
                    covered, d_req, d_fail, d_dc, d_ds = self._window_delta(
                        ring, sample, seconds
                    )
                    err_ratio = d_fail / d_req if d_req > 0 else 0.0
                    avail_burn = err_ratio / self.allowed_error
                    mean_ms = (d_ds / d_dc) * 1000.0 if d_dc > 0 else 0.0
                    latency_burn = mean_ms / target_ms if target_ms > 0 else 0.0
                    burn = max(avail_burn, latency_burn)
                    windows[label] = {
                        "burn_rate": round(burn, 4),
                        "availability_burn": round(avail_burn, 4),
                        "latency_burn": round(latency_burn, 4),
                        "requests": d_req,
                        "failures": d_fail,
                        "mean_latency_ms": round(mean_ms, 3),
                        "covered_s": round(covered, 1),
                    }
                    burn_gauge.labels(rpc=rpc, window=label).set(burn)
                # budget remaining over the slow window: the unspent
                # fraction of the availability error budget
                slow = windows[SLOW_PAIR[1]]
                if slow["requests"] > 0:
                    spent = slow["failures"] / (
                        self.allowed_error * slow["requests"]
                    )
                else:
                    spent = 0.0
                remaining = max(0.0, 1.0 - spent)
                budget_gauge.labels(rpc=rpc).set(remaining)
                paging = self._check_pages(rpc, windows, now)
                view[rpc] = {
                    "availability_target": self.settings.availability_target,
                    "latency_target_ms": target_ms,
                    "windows": windows,
                    "error_budget_remaining": round(remaining, 4),
                    "paging": paging,
                    "total_requests": ok + fail,
                    "total_failures": fail,
                }
            self._last = view
            return view

    def _check_pages(
        self, rpc: str, windows: dict[str, dict], now: float
    ) -> list[str]:
        """Page-worthy pairs this tick (["fast"] / ["slow"] / both).
        Each fires its WARNING + trace-ring event at most once per its
        short window's period."""
        paging: list[str] = []
        for name, pair, threshold in (
            ("fast", FAST_PAIR, self.settings.fast_burn_threshold),
            ("slow", SLOW_PAIR, self.settings.slow_burn_threshold),
        ):
            short, long_ = pair
            if not (
                windows[short]["burn_rate"] >= threshold
                and windows[long_]["burn_rate"] >= threshold
            ):
                continue
            paging.append(name)
            warned = self._warned_at.get((rpc, name))
            if warned is not None and now - warned < _WINDOW_S[short]:
                continue
            self._warned_at[(rpc, name)] = now
            self._pages += 1
            log.warning(
                "SLO burn (%s): %s burning error budget at %.1fx over %s "
                "and %.1fx over %s (threshold %.1fx) — budget spends to "
                "zero well before the period ends; see /slo",
                name, rpc,
                windows[short]["burn_rate"], short,
                windows[long_]["burn_rate"], long_,
                threshold,
            )
            from . import get_tracer

            get_tracer().record_event(
                "slo_burn",
                rpc=rpc,
                pair=name,
                burn_short=windows[short]["burn_rate"],
                burn_long=windows[long_]["burn_rate"],
                threshold=threshold,
            )
        return paging

    # -- inspection ----------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``/slo`` JSON payload (last computed view + objectives)."""
        with self._lock:
            return {
                "schema": SCHEMA,
                "partition": self.partition,
                "availability_target": self.settings.availability_target,
                "fast_burn_threshold": self.settings.fast_burn_threshold,
                "slow_burn_threshold": self.settings.slow_burn_threshold,
                "windows": [label for label, _ in WINDOWS],
                "pages_fired": self._pages,
                "rpcs": self._last,
            }
