"""Structured JSON logging with trace-id correlation.

One formatter for the whole process: every record becomes a single-line
JSON object with a stable schema (documented in docs/operations.md
§Telemetry), and the active request's trace id is attached automatically
from :data:`~cpzk_tpu.observability.context.current_context` — log lines
emitted anywhere below an instrumented RPC handler correlate with the
trace ring buffer and the Prometheus exporter without any call-site
changes.  Opt-in via the ``[observability] json_logs`` config key /
``SERVER_OBSERVABILITY_JSON_LOGS`` env (human-readable logging stays the
default for interactive runs).
"""

from __future__ import annotations

import json
import logging
import time

from .context import current_context

#: logging.LogRecord attributes that are plumbing, not payload — anything
#: else found on a record (``extra=...``) is emitted as a JSON field.
_RESERVED = frozenset(
    (
        "name", "msg", "args", "levelname", "levelno", "pathname", "filename",
        "module", "exc_info", "exc_text", "stack_info", "lineno", "funcName",
        "created", "msecs", "relativeCreated", "thread", "threadName",
        "processName", "process", "taskName", "message", "asctime",
    )
)


class JsonLogFormatter(logging.Formatter):
    """``{"ts", "level", "logger", "message", "trace_id"?, ...extras}``."""

    def format(self, record: logging.LogRecord) -> str:
        data: dict = {
            "ts": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)
            )
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        trace_id = getattr(record, "trace_id", None)
        if trace_id is None:
            ctx = current_context.get()
            trace_id = ctx.trace_id if ctx is not None else None
        if trace_id:
            data["trace_id"] = trace_id
        for key, value in record.__dict__.items():
            if key in _RESERVED or key.startswith("_") or key == "trace_id":
                continue
            if key in data:
                continue
            try:
                json.dumps(value)
            except (TypeError, ValueError):
                value = repr(value)
            data[key] = value
        if record.exc_info:
            data["exc"] = self.formatException(record.exc_info)
        return json.dumps(data, separators=(",", ":"), sort_keys=False)


def enable_json_logs(logger: logging.Logger | None = None) -> logging.Handler:
    """Swap the (root by default) logger's stream handlers to the JSON
    formatter; installs one if none exist.  Returns the handler so tests
    and the daemon can detach it."""
    target = logger or logging.getLogger()
    formatter = JsonLogFormatter()
    for handler in target.handlers:
        if isinstance(handler, logging.StreamHandler):
            handler.setFormatter(formatter)
            return handler
    handler = logging.StreamHandler()
    handler.setFormatter(formatter)
    target.addHandler(handler)
    return handler
