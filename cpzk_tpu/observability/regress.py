"""Perf-regression gate CLI: compare two PerfSnapshot files.

Usage::

    python -m cpzk_tpu.observability.regress OLD NEW [--threshold 0.35]
                                             [--json]

Exit codes: 0 = no regression, 1 = at least one entry regressed past its
noise-adjusted gate, 2 = usage / unreadable snapshot.  CI runs this
against the committed ``BENCH_BASELINE_CPU.json`` (the seed of the BENCH
trajectory); see docs/operations.md §Telemetry for the workflow.
"""

from __future__ import annotations

import argparse
import json
import sys

from .perf import compare_files, format_report


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cpzk_tpu.observability.regress",
        description="compare two cpzk-perf-snapshot files",
    )
    ap.add_argument("old", help="baseline snapshot (e.g. BENCH_BASELINE_CPU.json)")
    ap.add_argument("new", help="candidate snapshot")
    ap.add_argument(
        "--threshold", type=float, default=0.35,
        help="base relative-regression gate before the per-entry noise "
             "allowance (default 0.35 = 35%%)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit the machine-readable report instead of text",
    )
    args = ap.parse_args(argv)
    if not 0 < args.threshold < 10:
        print(f"--threshold out of range: {args.threshold}", file=sys.stderr)
        return 2

    try:
        report = compare_files(args.old, args.new, threshold=args.threshold)
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as exc:
        print(f"cannot compare snapshots: {exc}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(
            {
                "passed": report["passed"],
                "compared": report["compared"],
                "threshold": args.threshold,
                "regressions": [
                    {
                        "name": d.key[0], "backend": d.key[1],
                        "n": d.key[2], "unit": d.key[3],
                        "lanes": d.key[4], "wire": d.key[5],
                        "old": d.old, "new": d.new,
                        "change": d.change, "limit": d.limit,
                    }
                    for d in report["regressions"]
                ],
                "only_old": [list(k) for k in report["only_old"]],
                "only_new": [list(k) for k in report["only_new"]],
            },
            indent=2, sort_keys=True,
        ))
    else:
        print(format_report(report, args.threshold))
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
