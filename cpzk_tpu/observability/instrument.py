"""RPC instrumentation: one decorator for the whole requests/outcome/
duration/trace lifecycle.

Before this existed, every handler in ``server/service.py`` repeated the
same four metric calls by hand — and several early-return failure paths
forgot ``.observe()``, so failure latencies were invisible.  The
decorator owns the lifecycle instead:

- extracts the client's :class:`RequestContext` from gRPC metadata (or
  mints one), publishes it via ``current_context`` for the handler body,
  the batcher, and the JSON log formatter;
- counts ``<prefix>.requests`` on entry and exactly one of
  ``<prefix>.success`` / ``<prefix>.failure`` on exit (aborts and
  cancellations are failures), and ALWAYS observes
  ``<prefix>.duration`` — both outcomes, every path;
- mirrors everything into the labeled facade (``rpc.requests{rpc,
  outcome}``, ``rpc.duration{rpc}``) so one dashboard query covers all
  RPCs;
- completes the trace in the ring buffer and emits the slow-request
  WARNING (threshold ``observability.slow_request_ms``; 0 logs every
  request, -1 disables) with the per-stage breakdown inline.
"""

from __future__ import annotations

import functools
import logging
import time

from ..server import metrics
from .context import RequestContext, current_context
from .tracing import get_tracer

rpc_log = logging.getLogger("cpzk_tpu.observability.rpc")


def rpc_deadline(context) -> float | None:
    """Absolute ``time.monotonic()`` deadline of this RPC, or None when the
    client set none (tolerates hand-rolled test contexts)."""
    try:
        remaining = context.time_remaining()
    except Exception:
        return None
    if remaining is None:
        return None
    return time.monotonic() + max(0.0, remaining)


def traced_stream_rpc(rpc: str, metric_prefix: str):
    """The :func:`traced_rpc` lifecycle for an async-generator
    ``(self, request_iterator, context)`` bidi-streaming handler: one
    trace per STREAM (entries inherit its trace id through the batcher,
    so their stage spans land on the stream's trace exactly like a unary
    request's do), requests/outcome counters and a duration histogram
    over the stream's whole life, and the slow-request WARNING keyed on
    stream duration."""

    def decorator(fn):
        @functools.wraps(fn)
        async def wrapper(self, request_iterator, context):
            rctx = RequestContext.from_grpc(
                context, deadline=rpc_deadline(context)
            )
            token = current_context.set(rctx)
            tracer = get_tracer()
            tracer.start(rctx, rpc)
            metrics.counter(f"{metric_prefix}.requests").inc()
            start = time.perf_counter()
            outcome = "failure"
            try:
                async for response in fn(self, request_iterator, context):
                    yield response
                outcome = "success"
            finally:
                duration = time.perf_counter() - start
                metrics.counter(f"{metric_prefix}.{outcome}").inc()
                metrics.histogram(f"{metric_prefix}.duration").observe(duration)
                metrics.counter(
                    "rpc.requests", labelnames=("rpc", "outcome")
                ).labels(rpc=rpc, outcome=outcome).inc()
                metrics.histogram(
                    "rpc.duration", labelnames=("rpc",)
                ).labels(rpc=rpc).observe(duration)
                record = tracer.finish(
                    rctx.trace_id, outcome, duration_s=duration
                )
                threshold = tracer.slow_request_s
                if threshold is not None and duration >= threshold:
                    stages = {
                        s.name: round(s.duration_s * 1000, 3)
                        for s in (record.spans if record else ())
                    }
                    rpc_log.warning(
                        "%s %s in %.2fms (attempt %d)",
                        rpc, outcome, duration * 1000, rctx.attempt,
                        extra={
                            "trace_id": rctx.trace_id,
                            "rpc": rpc,
                            "outcome": outcome,
                            "duration_ms": round(duration * 1000, 3),
                            "attempt": rctx.attempt,
                            "stages_ms": stages,
                        },
                    )
                current_context.reset(token)

        return wrapper

    return decorator


def traced_rpc(rpc: str, metric_prefix: str):
    """Wrap an async ``(self, request, context)`` RPC handler with the
    full metrics + tracing lifecycle described in the module docstring."""

    def decorator(fn):
        @functools.wraps(fn)
        async def wrapper(self, request, context):
            rctx = RequestContext.from_grpc(
                context, deadline=rpc_deadline(context)
            )
            token = current_context.set(rctx)
            tracer = get_tracer()
            tracer.start(rctx, rpc)
            metrics.counter(f"{metric_prefix}.requests").inc()
            start = time.perf_counter()
            outcome = "failure"
            try:
                response = await fn(self, request, context)
                outcome = "success"
                return response
            finally:
                duration = time.perf_counter() - start
                metrics.counter(f"{metric_prefix}.{outcome}").inc()
                metrics.histogram(f"{metric_prefix}.duration").observe(duration)
                metrics.counter(
                    "rpc.requests", labelnames=("rpc", "outcome")
                ).labels(rpc=rpc, outcome=outcome).inc()
                metrics.histogram(
                    "rpc.duration", labelnames=("rpc",)
                ).labels(rpc=rpc).observe(duration)
                record = tracer.finish(
                    rctx.trace_id, outcome, duration_s=duration
                )
                threshold = tracer.slow_request_s
                if threshold is not None and duration >= threshold:
                    stages = {
                        s.name: round(s.duration_s * 1000, 3)
                        for s in (record.spans if record else ())
                    }
                    rpc_log.warning(
                        "%s %s in %.2fms (attempt %d)",
                        rpc, outcome, duration * 1000, rctx.attempt,
                        extra={
                            "trace_id": rctx.trace_id,
                            "rpc": rpc,
                            "outcome": outcome,
                            "duration_ms": round(duration * 1000, 3),
                            "attempt": rctx.attempt,
                            "stages_ms": stages,
                        },
                    )
                current_context.reset(token)

        return wrapper

    return decorator
