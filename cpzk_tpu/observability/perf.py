"""PerfSnapshot: a stable JSON schema for benchmark results + the
noise-aware comparator behind the CI perf-regression gate.

The BENCH trajectory was empty before this existed: nothing would have
noticed a 2x serving regression until an operator did.  The pipeline is

1. a bench driver (``benches/bench_batch.py --snapshot``,
   ``benches/bench_e2e_curve.py --snapshot``) emits a **PerfSnapshot**:
   throughput / per-batch latency entries per (bench, backend, n), each
   carrying a measured ``spread`` (max-min across repeat runs — the
   run's own noise bound), plus per-stage latency percentiles from the
   flight recorder when the serving path was exercised;
2. ``python -m cpzk_tpu.observability.regress OLD NEW`` compares two
   snapshots entry-by-entry with a **noise-adjusted threshold**: an
   entry regresses only when it moved in the bad direction by more than
   ``threshold + relative spread of both runs`` — so a noisy bench
   widens its own gate instead of flapping CI;
3. CI runs the small CPU bench on every push and gates against the
   committed ``BENCH_BASELINE_CPU.json``.

Schema (``cpzk-perf-snapshot/1``)::

    {"schema": "cpzk-perf-snapshot/1", "created_at": <unix>,
     "meta": {"platform": ..., ...},
     "entries": [{"name": "batch_e2e", "backend": "cpu", "n": 50,
                  "value": 1.94, "unit": "ms/batch", "spread": 0.11,
                  "stages_ms": {"execute": {"p50": ..., "p90": ...}}}]}

``unit`` decides the regression direction: ``proofs/s`` regresses when
it drops, ``ms/batch`` (and any other latency unit) when it rises.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field

SCHEMA = "cpzk-perf-snapshot/1"

#: Units where larger is better; every other unit gates lower-is-better.
#: The soak harness (``benches/bench_soak.py``) leans on the
#: lower-is-better default for its non-throughput metric kinds — ``ms``
#: (per-RPC p50/p99, snapshot pause, sweep duration, failover time) and
#: ``bytes`` (steady-state RSS) — so a BENCH_SOAK.json gates through the
#: same noise-aware comparator as the throughput benches.
HIGHER_IS_BETTER = frozenset({"proofs/s", "users/s"})

#: Stage-latency percentiles carried per entry when available.
PERCENTILES = (50, 90, 99)


@dataclass
class PerfEntry:
    """One measured configuration of one benchmark.

    ``lanes`` and ``wire`` are config-key components (the multi-chip
    serving plane's dispatch-lane count; the transport wire path,
    ``"python"`` = protobuf runtime, ``"native"`` = the C++ wire
    parser): entries measured at different values gate independently,
    and because absent keys never gate, the first snapshot carrying a
    new lane count or wire mode seeds its trajectory instead of failing
    CI.  Baselines written before a key existed load with its historical
    value (``lanes=1``, ``wire="python"``) — exactly the configuration
    they measured."""

    name: str
    backend: str
    n: int
    value: float
    unit: str
    spread: float = 0.0  # max-min over repeat runs, same unit as value
    lanes: int = 1
    wire: str = "python"
    stages_ms: dict[str, dict[str, float]] = field(default_factory=dict)

    def key(self) -> tuple[str, str, int, str, int, str]:
        return (self.name, self.backend, self.n, self.unit, self.lanes,
                self.wire)

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "backend": self.backend,
            "n": self.n,
            "value": self.value,
            "unit": self.unit,
            "spread": self.spread,
        }
        if self.lanes != 1:
            out["lanes"] = self.lanes
        if self.wire != "python":
            out["wire"] = self.wire
        if self.stages_ms:
            out["stages_ms"] = self.stages_ms
        return out

    @classmethod
    def from_dict(cls, data: dict) -> "PerfEntry":
        return cls(
            name=str(data["name"]),
            backend=str(data.get("backend", "cpu")),
            n=int(data.get("n", 0)),
            value=float(data["value"]),
            unit=str(data.get("unit", "ms/batch")),
            spread=max(0.0, float(data.get("spread", 0.0))),
            lanes=int(data.get("lanes", 1)),
            wire=str(data.get("wire", "python")),
            stages_ms=dict(data.get("stages_ms", {})),
        )


def build_snapshot(entries: list[PerfEntry], meta: dict | None = None) -> dict:
    return {
        "schema": SCHEMA,
        "created_at": time.time(),
        "meta": dict(meta or {}),
        "entries": [e.to_dict() for e in entries],
    }


def write_snapshot(
    path: str, entries: list[PerfEntry], meta: dict | None = None
) -> str:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(build_snapshot(entries, meta), f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def load_snapshot(path: str) -> list[PerfEntry]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("schema") != SCHEMA:
        raise ValueError(
            f"{path}: not a {SCHEMA} snapshot "
            f"(schema={data.get('schema')!r})"
        )
    return [PerfEntry.from_dict(e) for e in data.get("entries", [])]


def stage_percentiles(
    records, percentiles: tuple[int, ...] = PERCENTILES
) -> dict[str, dict[str, float]]:
    """Per-stage latency percentiles (ms) over flight-recorder records —
    the ``stages_ms`` block of a snapshot entry.  Nearest-rank on the
    sorted per-batch stage durations; empty dict when no records."""
    by_stage: dict[str, list[float]] = {}
    for rec in records:
        for name, secs in rec.stages_s.items():
            by_stage.setdefault(name, []).append(secs * 1000.0)
    out: dict[str, dict[str, float]] = {}
    for name, values in sorted(by_stage.items()):
        values.sort()
        out[name] = {
            f"p{q}": values[
                min(len(values) - 1, max(0, -(-q * len(values) // 100) - 1))
            ]
            for q in percentiles
        }
    return out


# -- comparison ---------------------------------------------------------------


@dataclass
class Delta:
    """One compared entry: relative change, adjusted gate, verdict."""

    key: tuple[str, str, int, str, int, str]
    old: float
    new: float
    change: float      # relative move in the BAD direction (>0 = worse)
    limit: float       # threshold + noise allowance actually applied
    regressed: bool

    def describe(self) -> str:
        name, backend, n, unit, lanes, wire = self.key
        lane_tag = f"/lanes={lanes}" if lanes != 1 else ""
        wire_tag = f"/wire={wire}" if wire != "python" else ""
        arrow = "WORSE" if self.change > 0 else "better"
        return (
            f"{name}/{backend}/n={n}{lane_tag}{wire_tag}: "
            f"{self.old:g} -> {self.new:g} {unit} "
            f"({abs(self.change) * 100:.1f}% {arrow}, "
            f"gate {self.limit * 100:.1f}%)"
        )


def compare_entries(
    old: list[PerfEntry],
    new: list[PerfEntry],
    threshold: float = 0.35,
) -> dict:
    """Noise-aware snapshot comparison.

    For each key present in BOTH snapshots, the relative move in the bad
    direction (throughput down / latency up) is gated at ``threshold``
    plus the combined relative spread of the two runs (capped at 1x the
    threshold, so a pathologically noisy bench cannot disable its own
    gate entirely).  Keys present in only one snapshot are reported but
    never fail the gate — adding or retiring a bench config must not
    break CI."""
    old_by = {e.key(): e for e in old}
    new_by = {e.key(): e for e in new}
    deltas: list[Delta] = []
    for key in sorted(old_by.keys() & new_by.keys()):
        o, n_ = old_by[key], new_by[key]
        if o.value <= 0:
            continue
        raw = (n_.value - o.value) / o.value
        change = -raw if key[3] in HIGHER_IS_BETTER else raw
        noise = 0.0
        if o.value > 0:
            noise += o.spread / o.value
        if n_.value > 0:
            noise += n_.spread / n_.value
        limit = threshold + min(noise, threshold)
        deltas.append(
            Delta(
                key=key, old=o.value, new=n_.value,
                change=change, limit=limit, regressed=change > limit,
            )
        )
    regressions = [d for d in deltas if d.regressed]
    return {
        "compared": len(deltas),
        "regressions": regressions,
        "only_old": sorted(old_by.keys() - new_by.keys()),
        "only_new": sorted(new_by.keys() - old_by.keys()),
        "passed": not regressions,
        "deltas": deltas,
    }


def compare_files(old_path: str, new_path: str, threshold: float = 0.35) -> dict:
    return compare_entries(
        load_snapshot(old_path), load_snapshot(new_path), threshold
    )


def format_report(report: dict, threshold: float) -> str:
    lines = [
        f"perf gate: {report['compared']} configs compared "
        f"(base threshold {threshold * 100:.0f}%, noise-adjusted per entry)"
    ]
    for d in report["deltas"]:
        mark = "FAIL" if d.regressed else " ok "
        lines.append(f"  [{mark}] {d.describe()}")
    for key in report["only_old"]:
        lines.append(f"  [gone] {key} only in the baseline (not gated)")
    for key in report["only_new"]:
        lines.append(f"  [new ] {key} only in the new snapshot (not gated)")
    lines.append("PASS" if report["passed"] else "REGRESSION")
    return "\n".join(lines)
