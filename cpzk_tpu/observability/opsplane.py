"""Fleet ops plane: dependency-free HTTP introspection endpoints.

Every operational surface built so far — ``/tracez``, ``/flightrec``,
``/replication``, ``/overload``, ``/persist``, ``/audit``, SIGUSR2 dumps
— was reachable only from an interactive REPL on the box itself, and
metric exposition existed only when ``prometheus_client`` happened to be
importable.  This module is the remote surface: a small asyncio HTTP/1.1
server (stdlib only — the container bakes no web framework) the daemon
starts **before** the gRPC listener, serving:

- ``GET /metrics``  — text exposition rendered directly from the metrics
  facade's own registry (:func:`cpzk_tpu.server.metrics.render_exposition`),
  identical family set on the prometheus and no-prometheus backings;
- ``GET /statusz``  — one JSON snapshot of the whole box: batcher depth/
  in-flight/drain rate, dispatch-lane stage percentiles from the flight
  ring, per-shard registry sizes + sampled lock wait, admission level,
  breaker state, replication role/epoch/lag/last ship, audit log
  seq/bytes, active streams, uptime, config fingerprint;
- ``GET /tracez``, ``GET /flightrec`` — the ring dumps as JSON, the
  EXACT payloads the REPL renders and SIGUSR2 writes (one serializer,
  one schema: ``Tracer.payload`` / ``FlightRecorder.payload``);
- ``GET /healthz``  — the readiness/liveness split as JSON (200 while
  live; ``?service=readiness`` keys the status code on readiness, for
  probes that can only read status codes);
- ``GET /slo``      — the :class:`~cpzk_tpu.observability.slo.SloEngine`
  burn-rate view (ticked on demand, so it is always current).

Anything else is a JSON 404 listing the catalog.  GET only — the ops
plane is strictly read-only (``/promote`` and friends stay on the REPL,
where an operator's hands are on the box).  Bind it to loopback (the
default) or an internal interface; there is no auth layer.

The handler loop never blocks the event loop (ASYNC-001 applies here):
every render is a synchronous walk over in-memory rings/registries, and
responses are bounded (ring sizes cap the payloads).

Hosts without an event loop (the bulk audit pipeline) attach via
:meth:`OpsPlane.start_in_thread`, which runs the same server on a
daemon-thread loop.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
import urllib.parse
from dataclasses import dataclass, field

from ..server import metrics

#: Endpoint catalog (the 404 body lists it; tests pin it).
ENDPOINTS = (
    "/metrics", "/statusz", "/tracez", "/flightrec", "/healthz", "/slo",
    "/partitionmap",
)

#: Schema tag of the ``/statusz`` payload.
STATUSZ_SCHEMA = "cpzk-statusz/1"

_MAX_REQUEST_BYTES = 16384
_READ_TIMEOUT_S = 10.0


@dataclass
class OpsSources:
    """Everything the ops plane can introspect — all optional, so the
    same server attaches to a full daemon, a standby, or the bulk audit
    pipeline (absent planes render as ``null`` rows, never errors)."""

    state: object | None = None        # ServerState
    batcher: object | None = None      # DynamicBatcher
    backend: object | None = None      # FailoverBackend
    admission: object | None = None    # AdmissionController
    replication: object | None = None  # SegmentShipper | StandbyReplica
    audit_log: object | None = None    # ProofLogWriter
    durability: object | None = None   # DurabilityManager
    health: object | None = None       # HealthService
    service: object | None = None      # AuthServiceImpl (stream stats)
    slo: object | None = None          # SloEngine
    fleet: object | None = None        # fleet.FleetRouter
    ingest: object | None = None       # server.ingest.IngestSupervisor
    controller: object | None = None   # fleet.controller.FleetController
    config_fingerprint: str = ""
    role: str = "server"               # "server" | "standby" | "audit"
    started_at: float = field(default_factory=time.monotonic)

    # -- gauge refresh -------------------------------------------------------

    def refresh_gauges(self) -> None:
        """Update the pull-style gauges (per-shard sizes, queue depth is
        push-maintained already) right before an exposition render, so a
        scrape never reads stale registry sizes."""
        state = self.state
        if state is not None and hasattr(state, "export_shard_gauges"):
            state.export_shard_gauges()

    # -- statusz -------------------------------------------------------------

    def statusz(self) -> dict:
        """The one-box JSON snapshot (see module docstring)."""
        from .flightrec import get_flight_recorder
        from .perf import stage_percentiles

        self.refresh_gauges()
        doc: dict = {
            "schema": STATUSZ_SCHEMA,
            "role": self.role,
            "uptime_s": round(time.monotonic() - self.started_at, 3),
            "config_fingerprint": self.config_fingerprint,
            "ts": time.time(),
        }

        batcher = self.batcher
        if batcher is not None:
            depth, capacity = batcher.load_snapshot()
            doc["batcher"] = {
                "queue_depth": depth,
                "queue_capacity": capacity,
                "max_batch": batcher.max_batch,
                "window_ms": batcher.window * 1000.0,
                "drain_rate_per_s": round(batcher.drain_rate(), 3),
            }
        else:
            doc["batcher"] = None

        recorder = get_flight_recorder()
        records = recorder.snapshot()
        doc["dispatch"] = {
            "recorded_batches": len(records),
            "proofs_per_s_ewma": round(recorder.proofs_per_s(), 1),
            "stage_percentiles_ms": stage_percentiles(records),
        }

        # multi-chip serving plane: one row per dispatch lane (breaker
        # state, depth, dispatches, drain rate) + the mesh lane when the
        # big-batch path is configured; null on single-lane hosts
        router = getattr(batcher, "router", None) if batcher is not None else None
        doc["lanes"] = router.status() if router is not None else None

        state = self.state
        if state is not None and hasattr(state, "shard_stats"):
            shards = state.shard_stats()
            wait_count, wait_sum = metrics.read_histogram(
                "state.shard.lock_wait"
            )
            doc["shards"] = {
                "count": len(shards),
                "users": sum(s["users"] for s in shards),
                "sessions": sum(s["sessions"] for s in shards),
                "challenges": sum(s["challenges"] for s in shards),
                "lock_wait_sampled": wait_count,
                "lock_wait_mean_ms": round(
                    (wait_sum / wait_count) * 1000.0, 4
                ) if wait_count else 0.0,
                "per_shard": shards,
            }
        else:
            doc["shards"] = None

        admission = self.admission
        if admission is not None:
            s = admission.snapshot()
            doc["admission"] = {
                "level": round(s["level"], 3),
                "admitted_tiers": s["admitted_tiers"],
                "clients": s["clients"],
                "max_clients": s["max_clients"],
                "utilization": round(s["utilization"], 4),
                "retry_after_ms": round(s["retry_after_ms"], 1),
            }
        else:
            doc["admission"] = None

        backend = self.backend
        if backend is not None and hasattr(backend, "breaker"):
            doc["breaker"] = {
                "state": backend.breaker.state.value,
                "degraded_seconds": round(
                    backend.breaker.degraded_seconds, 3
                ),
            }
        else:
            doc["breaker"] = None

        replication = self.replication
        doc["replication"] = (
            replication.status() if replication is not None else None
        )
        # coordinated-handover bookkeeping (primary side only): stage,
        # fence watermark, standby applied-seq, last duration + counters
        doc["handover"] = (
            replication.handover_status()
            if replication is not None
            and hasattr(replication, "handover_status")
            else None
        )

        audit_log = self.audit_log
        doc["audit"] = audit_log.status() if audit_log is not None else None

        # fleet partition rollup: this box's slot in the partition map,
        # its owned keyspace share, and the wrong-partition redirects it
        # has answered (map version/digest spot drift across the fleet)
        fleet = self.fleet
        doc["fleet"] = fleet.status() if fleet is not None else None

        # fleet controller: mode (dry-run vs live), cooldowns in flight,
        # administratively drained lanes, and the last-N decision ring —
        # the primary "what did the controller just do and why" surface
        controller = self.controller
        doc["controller"] = (
            controller.status() if controller is not None else None
        )

        # sharded ingest: one row per SO_REUSEPORT listener process
        # (pid, connected, rpcs/streams handled, native parses vs
        # protobuf fallbacks, respawns); null on in-process listeners
        ingest = self.ingest
        doc["ingest"] = ingest.status() if ingest is not None else None

        durability = self.durability
        if durability is not None and getattr(durability, "wal", None) is not None:
            doc["durability"] = durability.status()
        else:
            doc["durability"] = None

        service = self.service
        doc["streams"] = (
            service.stream_stats()
            if service is not None and hasattr(service, "stream_stats")
            else None
        )

        health = self.health
        if health is not None:
            doc["health"] = {
                "live": bool(health.serving),
                "ready": bool(health._ready()),
            }
        else:
            doc["health"] = None
        return doc

    def healthz(self) -> dict:
        """The readiness/liveness split as one JSON object."""
        health = self.health
        if health is None:
            # an attached-without-health host (audit pipeline): the
            # process answering IS the liveness signal
            return {"live": True, "ready": True, "detail": "no health gate"}
        return {
            "live": bool(health.serving),
            "ready": bool(health._ready()),
            "recovering": bool(getattr(health, "recovering", False)),
            "standby": bool(getattr(health, "standby", False)),
        }


class OpsPlane:
    """The HTTP introspection server (see module docstring)."""

    def __init__(self, sources: OpsSources, host: str = "127.0.0.1",
                 port: int = 9092):
        self.sources = sources
        self.host = host
        self.port = port
        self.bound_port: int | None = None
        self._server: asyncio.AbstractServer | None = None
        self._thread: threading.Thread | None = None
        self._thread_loop: asyncio.AbstractEventLoop | None = None

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> int:
        """Bind and serve; returns the bound port (the configured one, or
        the OS pick when ``port`` is 0 — tests bind ephemeral)."""
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.bound_port = self._server.sockets[0].getsockname()[1]
        return self.bound_port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def start_in_thread(self) -> int:
        """Run the same server on a daemon-thread event loop — the
        attachment point for synchronous hosts (the bulk audit pipeline).
        Returns the bound port; the thread dies with the process."""
        ready = threading.Event()
        box: dict = {}

        def run() -> None:
            loop = asyncio.new_event_loop()
            self._thread_loop = loop
            try:
                box["port"] = loop.run_until_complete(self.start())
            except OSError as e:  # bind failure surfaces to the caller
                box["error"] = e
                ready.set()
                return
            ready.set()
            loop.run_forever()

        self._thread = threading.Thread(
            target=run, name="cpzk-opsplane", daemon=True
        )
        self._thread.start()
        ready.wait(timeout=10.0)
        if "error" in box:
            raise box["error"]
        return box["port"]

    def stop_thread(self) -> None:
        """Stop a :meth:`start_in_thread` server (idempotent)."""
        loop = self._thread_loop
        if loop is None:
            return

        def shutdown() -> None:
            task = loop.create_task(self.stop())
            task.add_done_callback(lambda _t: loop.stop())

        loop.call_soon_threadsafe(shutdown)
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        self._thread = None
        self._thread_loop = None

    # -- request handling ----------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            try:
                request = await asyncio.wait_for(
                    reader.readuntil(b"\r\n\r\n"), timeout=_READ_TIMEOUT_S
                )
            except asyncio.LimitOverrunError:
                await self._respond(writer, 431, "text/plain",
                                    b"request too large\n")
                return
            except (asyncio.IncompleteReadError, asyncio.TimeoutError):
                return
            if len(request) > _MAX_REQUEST_BYTES:
                await self._respond(writer, 431, "text/plain",
                                    b"request too large\n")
                return
            line = request.split(b"\r\n", 1)[0].decode("latin-1")
            parts = line.split()
            if len(parts) != 3:
                await self._respond(writer, 400, "text/plain",
                                    b"malformed request line\n")
                return
            method, target, _version = parts
            if method != "GET":
                await self._respond(
                    writer, 405, "application/json",
                    _json({"error": "method not allowed", "allow": "GET"}),
                )
                return
            status, ctype, body = self._route(target)
            await self._respond(writer, status, ctype, body)
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass

    async def _respond(self, writer: asyncio.StreamWriter, status: int,
                       ctype: str, body: bytes) -> None:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  405: "Method Not Allowed", 431: "Request Too Large",
                  503: "Service Unavailable"}.get(status, "OK")
        head = (
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            "Connection: close\r\n"
            "\r\n"
        ).encode("latin-1")
        writer.write(head + body)
        await writer.drain()

    # -- routing (every render is synchronous, in-memory, bounded) -----------

    def _route(self, target: str) -> tuple[int, str, bytes]:
        parsed = urllib.parse.urlsplit(target)
        path = parsed.path.rstrip("/") or "/"
        query = urllib.parse.parse_qs(parsed.query)
        if path == "/metrics":
            self.sources.refresh_gauges()
            return (200, "text/plain; version=0.0.4; charset=utf-8",
                    metrics.render_exposition().encode())
        if path == "/statusz":
            return 200, "application/json", _json(self.sources.statusz())
        if path == "/tracez":
            from .tracing import get_tracer

            return (200, "application/json",
                    _json(get_tracer().payload(_limit(query))))
        if path == "/flightrec":
            from .flightrec import get_flight_recorder

            return (200, "application/json",
                    _json(get_flight_recorder().payload(_limit(query))))
        if path == "/healthz":
            doc = self.sources.healthz()
            want_ready = query.get("service", [""])[0] == "readiness"
            ok = doc.get("ready", False) if want_ready else doc.get("live", False)
            return (200 if ok else 503), "application/json", _json(doc)
        if path == "/slo":
            engine = self.sources.slo
            if engine is None:
                return (404, "application/json",
                        _json({"error": "no SLO engine attached"}))
            engine.tick()
            return 200, "application/json", _json(engine.snapshot())
        if path == "/partitionmap":
            fleet = self.sources.fleet
            if fleet is None:
                return (404, "application/json",
                        _json({"error": "no partition map attached "
                                        "([fleet] is disabled)"}))
            # the canonical serialized map, digest included — exactly
            # what PartitionMap.from_doc validates, so a client's
            # map_refresh can point straight at this endpoint
            return 200, "application/json", _json(fleet.map.to_doc())
        return (404, "application/json", _json({
            "error": f"unknown path {path!r}",
            "endpoints": list(ENDPOINTS),
        }))


def _limit(query: dict) -> int | None:
    """``?n=`` ring-dump limit (None = whole ring; garbage = None)."""
    raw = query.get("n", [None])[0]
    if raw is None:
        return None
    try:
        return max(1, int(raw))
    except ValueError:
        return None


def _json(obj: dict) -> bytes:
    return (json.dumps(obj, indent=2, sort_keys=True) + "\n").encode()
