"""TPU/JAX ``VerifierBackend`` — the device data plane behind
:class:`cpzk_tpu.protocol.batch.BatchVerifier`.

Host side: scalar arithmetic mod l (Python ints are exact and cheap relative
to group ops), window/digit decomposition, and SoA limb marshalling of the
row points.  Device side: the batched kernels in :mod:`cpzk_tpu.ops.verify`
and the windowed-Pippenger MSM in :mod:`cpzk_tpu.ops.msm`.  Batch shapes
follow the ``_pad_lanes`` schedule — powers of two up to ``LANE_QUANTUM``,
then quantum multiples — so ``jax.jit`` caches a bounded program set
without pow2's 2x padding waste at just-past-pow2 sizes.

The combined RLC check dispatches by topology: single-device batches use
the per-row shared-doubling kernel at EVERY size (calibrated winner on TPU
v5 lite — see ``PIPPENGER_MIN_ROWS``), tiled into ``LANE_CHUNK``-lane
programs past the device's proven program size; mesh-sharded batches route
through the Pippenger MSM over all 4n+2 terms, whose per-device partial
points combine over ICI (``parallel/mesh.py``).

Semantics parity (reference ``src/verifier/batch.rs``): the combined check
is only an accelerator — on failure ``BatchVerifier`` falls back to
``verify_each``, whose per-row results are ground truth, so accept/reject
matches the reference bit-for-bit (SURVEY.md §3.2).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import OrderedDict
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ..core import edwards
from ..core.ristretto import Ristretto255, Scalar
from ..core.scalars import L
from ..protocol.batch import BatchRow, VerifierBackend
from . import curve, msm, verify

#: Row count at or above which the combined check uses the Pippenger MSM
#: instead of per-row windowed chains.  Calibrated on TPU v5 lite
#: (.hw/ sweep, round 5): the per-row kernel wins EVERY measured A/B —
#: 11,991 vs 7,844 proofs/s at n=1024, 24,714 vs 19,028 at n=4096 — so
#: the single-device default is "never" (the Pippenger path remains the
#: multi-chip sharded-MSM story and stays selectable via
#: CPZK_PIPPENGER_MIN or the constructor for re-calibration on other
#: silicon).
PIPPENGER_MIN_ROWS = int(os.environ.get("CPZK_PIPPENGER_MIN", str(1 << 62)))

#: Maximum lane count for one monolithic device program.  Measured on TPU
#: v5 lite (benches/debug_pip16k.py, PROFILE.md §7a): the MSM kernel is
#: bit-correct through 32,770 lanes and deterministically WRONG at 40,962+
#: (internal XLA error at 49,154; all-zero output at 57,346), and the
#: per-row combined kernel fails its in-kernel check at 65,538 rows while
#: passing at 16,386 — an XLA codegen defect on large-lane programs, not
#: a math bug (the identical code passes every CPU differential at every
#: size).  Batches above this are tiled into full chunks of this many
#: lanes plus one quantum-aligned remainder chunk (one compile per chunk
#: shape, partial points added at the end), which also cuts the 64k
#: monolith's >18-minute compile.
LANE_CHUNK = int(os.environ.get("CPZK_LANE_CHUNK", "16384"))

#: Lane-pad granularity past the pow2 range.  Pure pow2 padding doubles
#: the device work for just-past-pow2 batches (the ubiquitous N+1
#: correction-row case: 16,385 -> 32,768); quantum padding caps the waste
#: at <= QUANTUM-1 lanes (~3% at 64k) while keeping the jit cache bounded
#: (one shared full-chunk program + at most LANE_CHUNK/QUANTUM remainder
#: shapes).
LANE_QUANTUM = int(os.environ.get("CPZK_LANE_QUANTUM", "2048"))
if LANE_CHUNK % min(LANE_QUANTUM, LANE_CHUNK):
    # a chunk that is not a quantum multiple makes every remainder shape
    # batch-size-dependent — one fresh minutes-long XLA compile each,
    # defeating the bounded-cache design; round down once, loudly
    import warnings

    _rounded = LANE_CHUNK - LANE_CHUNK % LANE_QUANTUM
    warnings.warn(
        f"CPZK_LANE_CHUNK={LANE_CHUNK} is not a multiple of "
        f"LANE_QUANTUM={LANE_QUANTUM}; rounding down to {_rounded} to keep "
        "remainder-chunk shapes bounded", stacklevel=1)
    LANE_CHUNK = _rounded


#: LRU bound on ``TpuBackend._gh_cache`` — device-resident generator-pair
#: points keyed by statement bytes.  Real deployments share one generator
#: pair, so 128 is generous; the bound exists because an adversarial (or
#: merely huge) registered-statement population must not leak device/host
#: memory one [20, 1] coordinate set at a time.
GH_CACHE_MAX = int(os.environ.get("CPZK_GH_CACHE_MAX", "128"))


def _note_gh_cache(size: int, evicted: int) -> None:
    """Generator-pair cache telemetry (``tpu.gh_cache.size`` gauge,
    ``tpu.gh_cache.evictions`` counter); optional like all server-layer
    metrics from this module."""
    try:
        from ..server import metrics

        metrics.gauge("tpu.gh_cache.size").set(size)
        if evicted:
            metrics.counter("tpu.gh_cache.evictions").inc(evicted)
    except Exception:  # pragma: no cover - server layer unavailable
        pass


def _note_pad_waste(n: int, pad: int) -> None:
    """Batch-shape telemetry: fraction of device lanes burned on padding
    for the most recent batch (``tpu.batch.pad_waste`` gauge) plus the
    flight recorder's occupancy accounting (``tpu.batch.occupancy``).
    Metrics live in the server layer; this module stays importable
    without it."""
    try:
        from ..server import metrics

        metrics.gauge("tpu.batch.pad_waste").set(
            (pad - n) / pad if pad > 0 else 0.0
        )
    except Exception:  # pragma: no cover - server layer unavailable
        pass
    try:
        from ..observability import flightrec

        flightrec.note_lanes(n, pad)
    except Exception:  # pragma: no cover - observability unavailable
        pass


def _note_marshal(t0: float) -> None:
    """Report elapsed host limb-marshal seconds since ``t0`` into the
    flight recorder's device sink (no-op outside an instrumented batch)."""
    try:
        from ..observability import flightrec

        flightrec.note_marshal(time.perf_counter() - t0)
    except Exception:  # pragma: no cover - observability unavailable
        pass


#: Per-thread device pin.  A per-device dispatch lane's backend enters
#: :func:`device_scope` around every verify call, which (a) makes
#: ``jax.default_device`` target that chip for the thread (staging
#: transfers AND jit executions land there) and (b) stamps the thread's
#: device key into every jit/AOT cache key below — XLA compiles one
#: executable PER device, so a cache that ignored the device would book
#: phantom hits on lanes 1..N-1 and the prewarm would warm only lane 0
#: (the ISSUE 12 prewarm bug).
_ACTIVE_DEVICE = threading.local()


def _device_key() -> str | None:
    """The jit/AOT cache-key suffix of the thread's pinned device (None
    outside :func:`device_scope` — the default-device fast path keeps its
    historical unsuffixed keys)."""
    return getattr(_ACTIVE_DEVICE, "key", None)


@contextlib.contextmanager
def device_scope(device):
    """Pin this thread's dispatches (staging, jit, AOT lookup) to one jax
    device.  ``None`` is a no-op, so single-device callers pay nothing."""
    if device is None:
        yield
        return
    prev = getattr(_ACTIVE_DEVICE, "key", None)
    _ACTIVE_DEVICE.key = f"dev{device.id}"
    try:
        with jax.default_device(device):
            yield
    finally:
        _ACTIVE_DEVICE.key = prev


def _scoped_key(key: tuple) -> tuple:
    dk = _device_key()
    return key if dk is None else key + (dk,)


#: First-sight registry of jitted device programs, keyed by (kernel name,
#: static args, padded shape[, device]) — the cache key the flight
#: recorder uses to attribute a dispatch's cost to ``compile`` (first
#: sight of a padded shape pays an XLA trace+compile) vs ``execute``.
#: The device component appears only under :func:`device_scope` (per-lane
#: dispatch): XLA compiles per device, so first-sights are per-device
#: facts.  Guarded: pipelined batches call the backend from multiple
#: worker threads.
_JIT_SEEN: set[tuple] = set()
_JIT_LOCK = threading.Lock()


def _jit_first_sight(*key) -> bool:
    """Register one jitted-program dispatch; True when this process has
    never dispatched this (kernel, shape) on this thread's device before."""
    key = _scoped_key(key)
    with _JIT_LOCK:
        first = key not in _JIT_SEEN
        if first:
            _JIT_SEEN.add(key)
    try:
        from ..observability import flightrec

        flightrec.note_jit("/".join(str(k) for k in key), first)
    except Exception:  # pragma: no cover - observability unavailable
        pass
    return first


#: Pre-lowered executables per (kernel, padded shape[, device]), keyed
#: like ``_JIT_SEEN``.  Populated by :func:`prewarm_executables` at server
#: startup (``[tpu] prewarm_quanta``) via ``jit(...).lower(...).compile()``;
#: the dispatch wrappers consult it FIRST, so a warmed shape never pays an
#: XLA trace at serving time and the flight recorder books its dispatches
#: as cache hits (zero steady-state ``compile`` spans).  Keys carry the
#: compiling thread's :func:`device_scope` pin, so a per-lane prewarm
#: yields one executable per chip and lane N's first dispatch finds ITS
#: executable, not lane 0's.
_AOT_CACHE: dict[tuple, object] = {}


def _aot_get(*key):
    key = _scoped_key(key)
    with _JIT_LOCK:
        return _AOT_CACHE.get(key)


def _aot_register(key: tuple, exe) -> None:
    key = _scoped_key(key)
    with _JIT_LOCK:
        _AOT_CACHE[key] = exe
        # pre-register the jit cache key: the first serving dispatch at
        # this shape (on this device) is a HIT (compiled before ready)
        _JIT_SEEN.add(key)


def _point_aval(pad: int):
    return tuple(
        jax.ShapeDtypeStruct((curve.NLIMBS, pad), jnp.int32)
        for _ in range(4)
    )


def _windows_aval(pad: int):
    return jax.ShapeDtypeStruct((curve.NWINDOWS, pad), jnp.int32)


def _prewarm_plan(batch_sizes) -> list[tuple]:
    """The (key, lower-thunk) list a prewarm covers: exactly the program
    shapes the shipping single-device dispatch of each batch size hits —
    the per-row combined kernel (with its +1 correction row), the
    chunk/partial programs past LANE_CHUNK, and the ``verify_each``
    ground-truth kernel the combined check falls back to."""
    plan: list[tuple] = []
    seen: set[tuple] = set()

    def add(key, thunk):
        if key not in seen:
            seen.add(key)
            plan.append((key, thunk))

    for n in batch_sizes:
        n = int(n)
        if n < 1:
            continue
        # combined RLC check: n rows + 1 correction row
        pad = _pad_lanes(n + 1)
        if pad <= LANE_CHUNK:
            add(
                ("combined", pad),
                lambda p=pad: _kernel("combined").lower(
                    p,
                    _point_aval(p), _point_aval(p),
                    _point_aval(p), _point_aval(p),
                    _windows_aval(p), _windows_aval(p),
                    _windows_aval(p), _windows_aval(p),
                ),
            )
        else:
            bounds = list(_chunk_bounds(pad))
            for lo, hi in bounds:
                w = hi - lo
                add(
                    ("combined_partial", w),
                    lambda p=w: _kernel("combined_partial").lower(
                        p,
                        _point_aval(p), _point_aval(p),
                        _point_aval(p), _point_aval(p),
                        _windows_aval(p), _windows_aval(p),
                        _windows_aval(p), _windows_aval(p),
                    ),
                )
            add(
                ("partials", len(bounds)),
                lambda k=len(bounds): _partials_jit.lower(_point_aval(k)),
            )
        # verify_each fallback (shared generator pair, [20, 1] g/h)
        pad_e = _pad_lanes(n)
        chunks = (
            [(0, pad_e)] if pad_e <= LANE_CHUNK else list(_chunk_bounds(pad_e))
        )
        for lo, hi in chunks:
            w = hi - lo
            add(
                ("each", w, True),
                lambda p=w: _kernel("each").lower(
                    p,
                    _point_aval(1), _point_aval(1),
                    _point_aval(p), _point_aval(p),
                    _point_aval(p), _point_aval(p),
                    _windows_aval(p), _windows_aval(p),
                ),
            )
    return plan


def prewarm_executables(batch_sizes, devices=None) -> list[str]:
    """AOT-compile (``jit(...).lower(...).compile()``) the single-device
    verify kernels for every padded shape the given batch sizes dispatch,
    and register them in the AOT executable cache + ``_JIT_SEEN``.  Call
    before the server reports ready (``[tpu] prewarm_quanta``): steady-
    state dispatch then never pays an XLA trace/compile.

    ``devices`` targets the prewarm: ``None`` warms the default device
    with the historical unsuffixed cache keys; a device list compiles one
    executable PER device under :func:`device_scope`, so every per-device
    dispatch lane's first serving dispatch books a jit HIT (before this,
    prewarm registered ``_JIT_SEEN`` globally but compiled on the default
    device only — lanes 1..N-1 ate a first-dispatch compile the recorder
    then misbooked as a cache hit).

    Returns the warmed shape keys (for the startup log).  Idempotent per
    (shape, device)."""
    warmed: list[str] = []
    for device in (devices if devices is not None else [None]):
        with device_scope(device):
            for key, lower in _prewarm_plan(batch_sizes):
                if _aot_get(*key) is not None:
                    continue
                t0 = time.perf_counter()
                exe = lower().compile()
                _aot_register(key, exe)
                name = "/".join(str(k) for k in _scoped_key(key))
                warmed.append(name)
                log_s = time.perf_counter() - t0
                if log_s > 1.0:  # long compiles are worth a line each
                    import logging

                    logging.getLogger("cpzk_tpu.ops.backend").info(
                        "prewarmed %s in %.1fs", name, log_s
                    )
    return warmed


def _pad_pow2(n: int) -> int:
    size = 1
    while size < n:
        size *= 2
    return size


def _pad_lanes(n: int) -> int:
    """Lane padding schedule: powers of two while small (compile-cache
    friendly), then multiples of LANE_QUANTUM.  Chunking slices the
    result into LANE_CHUNK-lane programs plus one quantum-aligned
    remainder program (see ``_chunk_bounds``)."""
    q = min(LANE_QUANTUM, LANE_CHUNK)
    if n <= q:
        return _pad_pow2(n)
    return -(-n // q) * q


def _chunk_bounds(pad: int):
    """(lo, hi) slices of a padded lane axis: full LANE_CHUNK chunks plus
    one remainder chunk (a LANE_QUANTUM multiple by construction)."""
    lo = 0
    while lo < pad:
        hi = min(lo + LANE_CHUNK, pad)
        yield lo, hi
        lo = hi


def _points_soa(points: list[edwards.Point], pad: int) -> curve.Point:
    return curve.points_soa(points, pad)


def _elems_soa(elems: list, pad: int, device=None) -> curve.Point:
    """SoA limb marshal of Elements.  Serving-path elements are
    wire-validated with lazy coordinates, so the native batch decode
    (threaded, ~9 us/point) beats materializing ``.point`` per element
    (~340 us of Python big-int decode each) by ~40x; falls back to the
    Python path when the native core is absent — checked FIRST, so the
    fallback never pays O(n) wire encodes just to learn that.  ``device``
    targets the staging transfer at a pinned chip (per-lane dispatch);
    the Python fallback relies on the caller's :func:`device_scope`."""
    from ..core import _native

    if _native.load() is not None:
        dev = curve.wires_to_device(
            b"".join(e.wire() for e in elems), pad, device=device
        )
        if dev is not None:
            return dev
    return _points_soa([e.point for e in elems], pad)


def _windows(values: list[int], pad: int) -> jnp.ndarray:
    return curve.scalar_windows(values, pad)


@jax.jit
def _rlc_products(n_arr, al, cl, sl, bl):
    """Device RLC scalar prep (CPZK_DEVICE_RLC=1): from alpha/challenge/
    response limbs (zero-padded past the true row count), derive the four
    window columns of the combined check — the per-row Python big-int
    products this replaces are the host bottleneck at 1M-row scale
    (PROFILE.md §1; ops/sclimbs.py module docstring).

    Inputs are [20, pad] limb arrays; ``n_arr`` is the TRACED row count,
    so the jit cache keys on the padded shape only.  The correction
    scalars land in column ``n`` via a lane mask (matching the host
    path's point layout: rows, then the G/H correction row, then
    identity padding — the pre-splice padding lanes hold zero scalars).
    Returns four [64, pad] window arrays for a, a*c, b*a, b*a*c.
    """
    from . import sclimbs as sc

    ac = sc.mul(al, cl)
    ba = sc.mul(bl, al)
    bac = sc.mul(bl, ac)
    sum_as = sc.sum_mod_l(sc.mul(al, sl))            # [20, 1]
    corr0 = sc.neg(sum_as)
    corr1 = sc.neg(sc.mul(bl, sum_as))

    lane = jnp.arange(al.shape[-1])[None, :]  # [1, pad]

    def col(body, corr):
        spliced = jnp.where(lane == n_arr, corr, body)
        return sc.to_windows(spliced)

    zero = jnp.zeros_like(corr0)
    return (
        col(al, corr0), col(ac, corr1), col(ba, zero), col(bac, zero)
    )


def _marshal_scalar_limbs(rows: list[BatchRow], beta: Scalar, pad: int):
    from . import sclimbs as sc

    n = len(rows)
    zeros = [0] * (pad - n)
    al = jnp.asarray(sc.ints_to_limbs([r.alpha.value for r in rows] + zeros))
    cl = jnp.asarray(sc.ints_to_limbs([r.c.value for r in rows] + zeros))
    sl = jnp.asarray(sc.ints_to_limbs([r.s.value for r in rows] + zeros))
    bl = jnp.asarray(sc.ints_to_limbs([beta.value]))
    return al, cl, sl, bl


def _rlc_windows_device(rows: list[BatchRow], beta: Scalar, pad: int):
    """Device window columns for the per-row combined kernel."""
    al, cl, sl, bl = _marshal_scalar_limbs(rows, beta, pad)
    return _rlc_products(jnp.int32(len(rows)), al, cl, sl, bl)


@jax.jit
def _rlc_scalar_groups(al, cl, sl, bl):
    """Products + corrections for the Pippenger term layout (no splice:
    the caller concatenates the groups eagerly)."""
    from . import sclimbs as sc

    ac = sc.mul(al, cl)
    ba = sc.mul(bl, al)
    bac = sc.mul(bl, ac)
    sum_as = sc.sum_mod_l(sc.mul(al, sl))
    return ac, ba, bac, sc.neg(sum_as), sc.neg(sc.mul(bl, sum_as))


@partial(jax.jit, static_argnums=(0,))
def _signed_digits_jit(c, limbs_arr):
    from . import sclimbs as sc

    return sc.to_signed_digits(limbs_arr, c)


def _pippenger_digits_device(
    rows: list[BatchRow], beta: Scalar, m: int, c: int
) -> jnp.ndarray:
    """[K, m] signed digits for the 4n+2-term MSM — scalar products and
    the digit recode both on device (CPZK_DEVICE_RLC=1 large-batch path).

    Term order matches ``_combined_pippenger``'s point layout:
    a(n) | ac(n) | ba(n) | bac(n) | corr_G | corr_H | zeros(pad).  The
    group concatenation happens eagerly (outside jit), so the two jitted
    stages key on the pow2-padded row count and the term count only.
    """
    n = len(rows)
    pad = _pad_pow2(n)
    al, cl, sl, bl = _marshal_scalar_limbs(rows, beta, pad)
    ac, ba, bac, corr0, corr1 = _rlc_scalar_groups(al, cl, sl, bl)
    from . import sclimbs as sc

    zeros = jnp.zeros((sc.NLIMBS, m - 4 * n - 2), dtype=jnp.int32)
    all_scalars = jnp.concatenate(
        [al[:, :n], ac[:, :n], ba[:, :n], bac[:, :n], corr0, corr1, zeros],
        axis=-1,
    )
    return _signed_digits_jit(c, all_scalars)


def _each_shared_impl(n_pad, g, h, y1, y2, r1, r2, ws, wc):
    del n_pad  # static cache key only
    return verify.verify_each_kernel(g, h, y1, y2, r1, r2, ws, wc)


def _combined_impl(n_pad, r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac):
    del n_pad
    return verify.combined_kernel(r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)


def _combined_partial_impl(n_pad, r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac):
    del n_pad
    return verify.combined_partial_kernel(
        r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)


#: Jitted single-device kernels, built lazily so buffer donation can be
#: decided once the JAX backend is known (importing this module must not
#: initialize a backend).  Donation marks the per-batch input arrays as
#: reusable by XLA — steady-state serving then recycles the same device
#: buffers batch after batch instead of allocating per dispatch.  Gated
#: off on CPU (XLA CPU ignores donation and warns per call); the cached
#: generator-pair arrays of ``_each_shared`` (g, h) are NEVER donated —
#: the gh-cache hands the same buffers to every batch.
_KERNELS: dict[str, object] = {}
_KERNEL_SPECS = {
    # name -> (impl, donate_argnums when donation is on)
    "each": (_each_shared_impl, tuple(range(3, 9))),
    "combined": (_combined_impl, tuple(range(1, 9))),
    "combined_partial": (_combined_partial_impl, tuple(range(1, 9))),
}


_DONATE_OVERRIDE: bool | None = None


def enable_donation(on: bool = True) -> None:
    """Serving-daemon switch: donate per-batch kernel inputs so XLA
    recycles their device buffers across batches.  Deliberately NOT the
    default — benches and direct callers may re-dispatch the same arrays
    (a donated array is dead after its call), so only the serving path,
    which rebuilds every input per batch, turns this on (build_backend,
    off-CPU).  Call before the first kernel dispatch; already-jitted
    kernels are rebuilt under the new policy, already-AOT-compiled
    executables are not."""
    global _DONATE_OVERRIDE
    _DONATE_OVERRIDE = on
    _KERNELS.clear()


def _donation_enabled() -> bool:
    """Donate device input buffers?  CPZK_DONATE_BUFFERS=1/0 forces;
    otherwise the :func:`enable_donation` switch decides (default off)."""
    forced = os.environ.get("CPZK_DONATE_BUFFERS")
    if forced in ("0", "1"):
        return forced == "1"
    return bool(_DONATE_OVERRIDE)


def _kernel(name: str):
    fn = _KERNELS.get(name)
    if fn is None:
        impl, donate = _KERNEL_SPECS[name]
        fn = _KERNELS[name] = jax.jit(
            impl,
            static_argnums=(0,),
            donate_argnums=donate if _donation_enabled() else (),
        )
    return fn


def _each_shared(n_pad, g, h, y1, y2, r1, r2, ws, wc):
    # the AOT executable is lowered for a SHARED [20, 1] generator pair;
    # mixed-generator batches (full-width g/h) must take the jit path
    if g[0].shape[-1] == 1:
        exe = _aot_get("each", n_pad, True)
        if exe is not None:
            return exe(g, h, y1, y2, r1, r2, ws, wc)
    return _kernel("each")(n_pad, g, h, y1, y2, r1, r2, ws, wc)


def _combined(n_pad, r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac):
    exe = _aot_get("combined", n_pad)
    if exe is not None:
        return exe(r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)
    return _kernel("combined")(
        n_pad, r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)


def _combined_partial(n_pad, r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac):
    exe = _aot_get("combined_partial", n_pad)
    if exe is not None:
        return exe(r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)
    return _kernel("combined_partial")(
        n_pad, r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)


@partial(jax.jit, static_argnums=(0,))
def _msm_identity(c, points, digits):
    return msm.msm_is_identity_kernel(points, digits, c)


@partial(jax.jit, static_argnums=(0,))
def _msm_partial(c, points, digits):
    return msm.msm_kernel(points, digits, c)


def _partials_impl(parts: curve.Point) -> jnp.ndarray:
    return curve.is_identity(curve.tree_sum(parts, axis=-1))


_partials_jit = jax.jit(_partials_impl)


def _partials_are_identity(parts: curve.Point) -> jnp.ndarray:
    """[20, k] partial points -> does their sum hit the identity coset."""
    exe = _aot_get("partials", parts[0].shape[-1])
    if exe is not None:
        return exe(parts)
    return _partials_jit(parts)


def _chunk_point(pt: curve.Point, lo: int, hi: int) -> curve.Point:
    """Lane-slice every coordinate array of a SoA point."""
    return tuple(c[..., lo:hi] for c in pt)


def _stack_partials(parts: list[curve.Point]) -> curve.Point:
    """[20, 1] chunk partials -> one [20, k] point batch for the final
    tree-sum + identity test."""
    return tuple(
        jnp.concatenate([p[k] for p in parts], axis=-1) for k in range(4)
    )


def chunked_combined_identity(pad, r1, y1, r2, y2,
                              w_a, w_ac, w_ba, w_bac) -> bool:
    """The full chunked per-row combined check: LANE_CHUNK-lane partial
    programs (identity-padded lanes contribute identity partials), then
    one tree-sum + identity test.  The SINGLE implementation of the
    chunk schedule — TpuBackend serves it and bench.py times it, so the
    bench cannot drift from the shipped dispatch."""
    if pad <= LANE_CHUNK:
        _jit_first_sight("combined", pad)
        return bool(_combined(pad, r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac))
    parts = []
    for lo, hi in _chunk_bounds(pad):
        _jit_first_sight("combined_partial", hi - lo)
        parts.append(_combined_partial(
            hi - lo,
            _chunk_point(r1, lo, hi), _chunk_point(y1, lo, hi),
            _chunk_point(r2, lo, hi), _chunk_point(y2, lo, hi),
            w_a[:, lo:hi], w_ac[:, lo:hi],
            w_ba[:, lo:hi], w_bac[:, lo:hi]))
    _jit_first_sight("partials", len(parts))
    return bool(_partials_are_identity(_stack_partials(parts)))


def chunked_msm_identity(c: int, pts: curve.Point,
                         digits: jnp.ndarray) -> bool:
    """The full chunked MSM == identity check (term axis tiled; zero-digit
    padded terms contribute identity).  Shared by TpuBackend and bench.py
    for the same no-drift reason as :func:`chunked_combined_identity`."""
    m_pad = digits.shape[-1]
    if m_pad <= LANE_CHUNK:
        _jit_first_sight("msm", c, m_pad)
        return bool(_msm_identity(c, pts, digits))
    parts = []
    for lo, hi in _chunk_bounds(m_pad):
        _jit_first_sight("msm_partial", c, hi - lo)
        parts.append(_msm_partial(
            c, _chunk_point(pts, lo, hi), digits[:, lo:hi]))
    _jit_first_sight("partials", len(parts))
    return bool(_partials_are_identity(_stack_partials(parts)))


class TpuBackend(VerifierBackend):
    """Vectorized device backend (TPU when available, any JAX backend).

    ``mesh_devices``: ``None`` pins single-device execution; ``0`` shards
    the batch axis over all visible devices (production default via the
    ``tpu.mesh_devices`` config knob); ``k > 1`` uses the first k.  The
    sharded paths ride ICI collectives via ``shard_map``
    (:mod:`cpzk_tpu.parallel.mesh`).

    ``device`` pins every dispatch of THIS instance to one jax device
    (staging transfers via ``jax.device_put``-targeted
    ``wires_to_device``, jit/AOT execution via :func:`device_scope`) —
    the per-device serving lanes each hold one pinned instance, so eight
    chips serve eight independent batch streams.  Mutually exclusive
    with a mesh.
    """

    prefers_combined = True

    def __init__(self, mesh_devices: int | None = None,
                 pippenger_min: int | None = None,
                 gh_cache_max: int | None = None,
                 device=None):
        """``pippenger_min`` overrides the rowcombined->Pippenger crossover
        for this instance (None = the module default / CPZK_PIPPENGER_MIN);
        a constructor parameter so callers (drivers, calibration sweeps)
        never need the env-plus-module-reload dance.  ``gh_cache_max``
        bounds the per-generator-pair device-point cache (None = the
        GH_CACHE_MAX module default / CPZK_GH_CACHE_MAX).  ``device``
        pins the instance to one jax device (see class docstring)."""
        if device is not None and mesh_devices is not None:
            raise ValueError(
                "TpuBackend(device=...) pins one chip; it cannot also "
                "shard over a mesh (mesh_devices must be None)"
            )
        self._device = device
        self._pippenger_min = (
            PIPPENGER_MIN_ROWS if pippenger_min is None else pippenger_min
        )

        # LRU-bounded generator-pair cache: keyed by statement generator
        # bytes, so millions of distinct registered statements must not
        # grow it without bound (the KeyedTokenBuckets containment story
        # applied to device memory) — least-recently-verified pair evicts
        self._gh_cache: OrderedDict[
            tuple[bytes, bytes], tuple[curve.Point, curve.Point]
        ] = OrderedDict()
        self._gh_cache_max = max(
            1, GH_CACHE_MAX if gh_cache_max is None else gh_cache_max
        )
        # the pipelined batcher calls verify_* from multiple worker
        # threads; guard the check-then-insert so a cold generator pair
        # is marshalled once, not once per concurrent batch
        self._gh_lock = threading.Lock()
        self._mesh = None
        self._sharded_each = None
        self._sharded_msm = None
        if mesh_devices is not None:
            from ..parallel import (
                batch_mesh,
                make_sharded_msm_check,
                make_sharded_verify_each,
                resolve_mesh_devices,
            )

            devices = resolve_mesh_devices(mesh_devices)
            if devices is not None:
                self._mesh = batch_mesh(devices)
                self._sharded_each = make_sharded_verify_each(self._mesh)
                self._sharded_msm = make_sharded_msm_check(self._mesh)

    def _gh(self, row: BatchRow) -> tuple[curve.Point, curve.Point]:
        key = (
            Ristretto255.element_to_bytes(row.g),
            Ristretto255.element_to_bytes(row.h),
        )
        evicted = 0
        with self._gh_lock:
            pair = self._gh_cache.pop(key, None)
            if pair is None:
                # single shared points keep a size-1 batch axis ([20, 1]
                # coords) and broadcast against the [20, n] row arrays
                pair = (
                    curve.points_to_device([row.g.point]),
                    curve.points_to_device([row.h.point]),
                )
            self._gh_cache[key] = pair  # (re)insert most-recently-used
            while len(self._gh_cache) > self._gh_cache_max:
                self._gh_cache.popitem(last=False)
                evicted += 1
            size = len(self._gh_cache)
        _note_gh_cache(size, evicted)
        return pair

    # -- VerifierBackend interface ------------------------------------------

    def verify_combined(self, rows: list[BatchRow], beta: Scalar) -> bool:
        with device_scope(self._device):
            return self._verify_combined(rows, beta)

    def _verify_combined(self, rows: list[BatchRow], beta: Scalar) -> bool:
        n = len(rows)
        device_rlc = os.environ.get("CPZK_DEVICE_RLC") == "1"

        if self._sharded_msm is not None or n >= self._pippenger_min:
            # a mesh always routes through the Pippenger MSM: the sharded
            # combined check is the partial-bucket-psum path (SURVEY §2.3)
            return self._combined_pippenger(rows, beta, device_rlc)

        # correction row: G in slot r1 with -sum(a s), H in slot y1 with
        # -b sum(a s); identity in the other two slots.
        debug = os.environ.get("CPZK_BATCH_DEBUG") == "1"
        t0 = time.perf_counter()
        pad = _pad_lanes(n + 1)
        _note_pad_waste(n + 1, pad)
        dev = self._device
        r1 = _elems_soa([r.r1 for r in rows] + [rows[0].g], pad, device=dev)
        y1 = _elems_soa([r.y1 for r in rows] + [rows[0].h], pad, device=dev)
        r2 = _elems_soa([r.r2 for r in rows], pad, device=dev)
        y2 = _elems_soa([r.y2 for r in rows], pad, device=dev)
        if device_rlc:
            _jit_first_sight("rlc", pad)
            w_a, w_ac, w_ba, w_bac = _rlc_windows_device(rows, beta, pad)
        else:
            b = beta.value
            a = [r.alpha.value for r in rows]
            c = [r.c.value for r in rows]
            s = [r.s.value for r in rows]
            ac = [x * y % L for x, y in zip(a, c)]
            ba = [b * x % L for x in a]
            bac = [b * x % L for x in ac]
            sum_as = sum(x * y for x, y in zip(a, s)) % L
            w_a = _windows(a + [(L - sum_as) % L], pad)
            w_ac = _windows(ac + [(L - b * sum_as % L) % L], pad)
            w_ba = _windows(ba, pad)
            w_bac = _windows(bac, pad)
        _note_marshal(t0)

        if not debug:
            return chunked_combined_identity(
                pad, r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)
        t1 = time.perf_counter()
        ok = chunked_combined_identity(
            pad, r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)
        import sys

        print(f"[backend-debug] n={n} pad={pad} marshal={t1 - t0:.3f}s "
              f"device={time.perf_counter() - t1:.3f}s",
              file=sys.stderr, flush=True)
        return ok

    def _combined_pippenger(
        self, rows: list[BatchRow], beta: Scalar, device_rlc: bool
    ) -> bool:
        """One MSM over all 4n+2 (point, scalar) terms == identity.

        The row count (not the term count) is padded to a power of two, so
        the jit cache stays small while padding waste stays ~0% — padding
        the 4n+2 terms directly would double device work at power-of-two
        batch sizes, the common full-batch serving case.  With
        CPZK_DEVICE_RLC=1 the per-term scalars and their signed digits
        come from the device scalar plane (``_pippenger_digits_device``)
        instead of per-row host big-int products.
        """
        t0 = time.perf_counter()
        elems = (
            [r.r1 for r in rows]
            + [r.y1 for r in rows]
            + [r.r2 for r in rows]
            + [r.y2 for r in rows]
            + [rows[0].g, rows[0].h]
        )
        m = 4 * _pad_pow2(len(rows)) + 2
        # window size is per-PROGRAM: past the chunk cap the MSM runs as
        # LANE_CHUNK-term tiles (chunked_msm_identity) and each device of
        # a mesh sees at most LANE_CHUNK lanes (_mesh_step), so the cost
        # model must see the chunk length, not the full term count —
        # sizing from m overshot c by 2 windows at 64k terms (ADVICE.md /
        # ROADMAP item 4 calibration-tail fix)
        c = msm.pick_window(min(m, LANE_CHUNK))
        # m is already shape-quantized (4*pow2+2), so below the chunk cap
        # it is used EXACTLY; above it, quantum padding keeps the waste to
        # under one LANE_QUANTUM of identity terms
        m_pad = m if m <= LANE_CHUNK else _pad_lanes(m)
        _note_pad_waste(4 * len(rows) + 2, m_pad)
        pts = _elems_soa(elems, m_pad, device=self._device)
        if device_rlc:
            digits = _pippenger_digits_device(rows, beta, m_pad, c)
        else:
            b = beta.value
            a = [r.alpha.value for r in rows]
            ch = [r.c.value for r in rows]
            s = [r.s.value for r in rows]
            ac = [x * y % L for x, y in zip(a, ch)]
            ba = [b * x % L for x in a]
            bac = [b * x % L for x in ac]
            sum_as = sum(x * y for x, y in zip(a, s)) % L
            scalars = a + ac + ba + bac + [
                (L - sum_as) % L, (L - b * sum_as % L) % L,
            ]
            digits = jnp.asarray(
                msm.scalars_to_signed_digits(
                    scalars + [0] * (m_pad - len(scalars)), c)
            )
        _note_marshal(t0)
        if self._sharded_msm is not None:
            return bool(self._sharded_msm(pts, digits, c))
        return chunked_msm_identity(c, pts, digits)

    def verify_each(self, rows: list[BatchRow]) -> list[bool]:
        with device_scope(self._device):
            return self._verify_each(rows)

    def _verify_each(self, rows: list[BatchRow]) -> list[bool]:
        n = len(rows)
        dev = self._device
        t0 = time.perf_counter()
        pad = _pad_lanes(n)
        _note_pad_waste(n, pad)
        shared = all(r.g == rows[0].g and r.h == rows[0].h for r in rows)
        if shared:
            g, h = self._gh(rows[0])
        else:
            g = _elems_soa([r.g for r in rows], pad, device=dev)
            h = _elems_soa([r.h for r in rows], pad, device=dev)
        y1 = _elems_soa([r.y1 for r in rows], pad, device=dev)
        y2 = _elems_soa([r.y2 for r in rows], pad, device=dev)
        r1 = _elems_soa([r.r1 for r in rows], pad, device=dev)
        r2 = _elems_soa([r.r2 for r in rows], pad, device=dev)
        ws = _windows([r.s.value for r in rows], pad)
        wc = _windows([r.c.value for r in rows], pad)
        _note_marshal(t0)

        if self._sharded_each is not None and shared:
            mask = self._sharded_each(g, h, y1, y2, r1, r2, ws, wc)
        elif pad > LANE_CHUNK:
            # per-row checks are lane-independent: tile and concatenate
            chunks = []
            for lo, hi in _chunk_bounds(pad):
                cg = g if shared else _chunk_point(g, lo, hi)
                ch_ = h if shared else _chunk_point(h, lo, hi)
                _jit_first_sight("each", hi - lo, shared)
                chunks.append(_each_shared(
                    hi - lo, cg, ch_,
                    _chunk_point(y1, lo, hi), _chunk_point(y2, lo, hi),
                    _chunk_point(r1, lo, hi), _chunk_point(r2, lo, hi),
                    ws[:, lo:hi], wc[:, lo:hi]))
            mask = jnp.concatenate(chunks, axis=-1)
        else:
            _jit_first_sight("each", pad, shared)
            mask = _each_shared(pad, g, h, y1, y2, r1, r2, ws, wc)
        if hasattr(mask, "is_fully_addressable") and not mask.is_fully_addressable:
            # multi-host job: the [n]-sharded result spans devices owned by
            # other processes; gather the global value everywhere
            from jax.experimental import multihost_utils

            mask = multihost_utils.process_allgather(mask, tiled=True)
        return [bool(v) for v in np.asarray(mask)[:n]]
