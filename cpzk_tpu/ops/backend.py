"""TPU/JAX ``VerifierBackend`` — the device data plane behind
:class:`cpzk_tpu.protocol.batch.BatchVerifier`.

Host side: scalar arithmetic mod l (Python ints are exact and cheap relative
to group ops), 4-bit window decomposition, and SoA limb marshalling of the
row points.  Device side: the batched kernels in :mod:`cpzk_tpu.ops.verify`.
Batch shapes are padded to powers of two so ``jax.jit`` caches a handful of
programs instead of one per batch size.

Semantics parity (reference ``src/verifier/batch.rs``): the combined check
is only an accelerator — on failure ``BatchVerifier`` falls back to
``verify_each``, whose per-row results are ground truth, so accept/reject
matches the reference bit-for-bit (SURVEY.md §3.2).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ..core import edwards
from ..core.ristretto import Ristretto255, Scalar
from ..core.scalars import L
from ..protocol.batch import BatchRow, VerifierBackend
from . import curve, verify


def _pad_pow2(n: int) -> int:
    size = 1
    while size < n:
        size *= 2
    return size


def _points_soa(points: list[edwards.Point], pad: int) -> curve.Point:
    pts = points + [edwards.IDENTITY] * (pad - len(points))
    return curve.points_to_device(pts)


def _windows(values: list[int], pad: int) -> jnp.ndarray:
    vals = values + [0] * (pad - len(values))
    return jnp.asarray(curve.scalars_to_windows(vals))


@partial(jax.jit, static_argnums=(0,))
def _each_shared(n_pad, g, h, y1, y2, r1, r2, ws, wc):
    del n_pad  # static cache key only
    return verify.verify_each_kernel(g, h, y1, y2, r1, r2, ws, wc)


@partial(jax.jit, static_argnums=(0,))
def _combined(n_pad, r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac):
    del n_pad
    return verify.combined_kernel(r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)


class TpuBackend(VerifierBackend):
    """Vectorized device backend (TPU when available, any JAX backend)."""

    prefers_combined = True

    def __init__(self):
        self._gh_cache: dict[tuple[bytes, bytes], tuple[curve.Point, curve.Point]] = {}

    def _gh(self, row: BatchRow) -> tuple[curve.Point, curve.Point]:
        key = (
            Ristretto255.element_to_bytes(row.g),
            Ristretto255.element_to_bytes(row.h),
        )
        if key not in self._gh_cache:
            self._gh_cache[key] = (
                curve.points_to_device([row.g.point]),
                curve.points_to_device([row.h.point]),
            )
            # single-point tables: squeeze the batch axis -> [20] coords
            self._gh_cache[key] = tuple(
                tuple(c[0] for c in pt) for pt in self._gh_cache[key]
            )
        return self._gh_cache[key]

    # -- VerifierBackend interface ------------------------------------------

    def verify_combined(self, rows: list[BatchRow], beta: Scalar) -> bool:
        n = len(rows)
        b = beta.value
        a = [r.alpha.value for r in rows]
        c = [r.c.value for r in rows]
        s = [r.s.value for r in rows]
        ac = [x * y % L for x, y in zip(a, c)]
        ba = [b * x % L for x in a]
        bac = [b * x % L for x in ac]
        sum_as = sum(x * y for x, y in zip(a, s)) % L

        # correction row: G in slot r1 with -sum(a s), H in slot y1 with
        # -b sum(a s); identity in the other two slots.
        g, h = rows[0].g.point, rows[0].h.point
        pad = _pad_pow2(n + 1)
        r1 = _points_soa([r.r1.point for r in rows] + [g], pad)
        y1 = _points_soa([r.y1.point for r in rows] + [h], pad)
        r2 = _points_soa([r.r2.point for r in rows], pad)
        y2 = _points_soa([r.y2.point for r in rows], pad)
        w_a = _windows(a + [(L - sum_as) % L], pad)
        w_ac = _windows(ac + [(L - b * sum_as % L) % L], pad)
        w_ba = _windows(ba, pad)
        w_bac = _windows(bac, pad)

        ok = _combined(pad, r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)
        return bool(ok)

    def verify_each(self, rows: list[BatchRow]) -> list[bool]:
        n = len(rows)
        pad = _pad_pow2(n)
        shared = all(r.g == rows[0].g and r.h == rows[0].h for r in rows)
        if shared:
            g, h = self._gh(rows[0])
        else:
            g = _points_soa([r.g.point for r in rows], pad)
            h = _points_soa([r.h.point for r in rows], pad)
        y1 = _points_soa([r.y1.point for r in rows], pad)
        y2 = _points_soa([r.y2.point for r in rows], pad)
        r1 = _points_soa([r.r1.point for r in rows], pad)
        r2 = _points_soa([r.r2.point for r in rows], pad)
        ws = _windows([r.s.value for r in rows], pad)
        wc = _windows([r.c.value for r in rows], pad)

        mask = _each_shared(pad, g, h, y1, y2, r1, r2, ws, wc)
        return [bool(v) for v in np.asarray(mask)[:n]]
