"""Batched Fiat-Shamir challenge derivation with device Keccak.

SURVEY.md §7 hard part 4: at the 1M proofs/sec north star, per-proof
Merlin transcript hashing (3 Keccak-f[1600] permutations per proof)
becomes a host bottleneck.  The STROBE byte bookkeeping is *data-
independent* when every row absorbs the same-shaped messages — which is
exactly the serving case (fixed 32-byte challenge-id contexts, 32-byte
point encodings) — so the entire transcript schedule reduces to:

    state_0  (shared prefix, concrete bytes, computed once on host)
    state ^= M_1 ; permute ; state ^= M_2 ; permute ; ... ; permute
    challenge = state[0:64]

where the XOR masks M_j are built on the host with vectorized numpy
(byte placement only — no hashing), and the permutations — all the
actual cryptographic work — run batched on the device
(:func:`cpzk_tpu.ops.keccak.keccak_f1600`, batch on the vector lanes).

``derive_challenges_device`` is bit-identical to the host/native
transcript paths (tests/test_ops_keccak.py differential); rows must
share one context length (None = no context append, like the bench and
example flows).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core import strobe as host_strobe
from ..core.strobe import FLAG_A, FLAG_C, FLAG_I, FLAG_M, STROBE_R
from ..core.transcript import CHALLENGE_DST, PROTOCOL_DST, PROTOCOL_LABEL
from . import keccak as dev_keccak

WIDE = 64


class _BatchStrobe:
    """Replays Strobe128's exact byte schedule over a batch.

    Shared bytes (labels, headers, length prefixes) broadcast; per-row
    bytes land as [n, L] numpy columns.  Produces the base state plus a
    list of XOR-mask blocks, one per permutation."""

    def __init__(self, base: "host_strobe.Strobe128", n: int):
        # concrete shared prefix: state bytes already contain absorbed-
        # but-unpermuted data, so masks simply continue from its pos
        self.n = n
        self.base_state = bytes(base.state)
        self.pos = base.pos
        self.pos_begin = base.pos_begin
        self.cur_flags = base.cur_flags
        self.cur = np.zeros((200, n), dtype=np.uint8)
        self.blocks: list[np.ndarray] = []

    # -- strobe internals (twin of core/strobe.py, mask-building form) --

    def _run_f(self) -> None:
        self.cur[self.pos] ^= self.pos_begin
        self.cur[self.pos + 1] ^= 0x04
        self.cur[STROBE_R + 1] ^= 0x80
        self.blocks.append(self.cur)
        self.cur = np.zeros((200, self.n), dtype=np.uint8)
        self.pos = 0
        self.pos_begin = 0

    def _absorb_shared(self, data: bytes) -> None:
        for byte in data:
            self.cur[self.pos] ^= byte
            self.pos += 1
            if self.pos == STROBE_R:
                self._run_f()

    def _absorb_cols(self, cols: np.ndarray) -> None:
        """cols: [n, L] uint8 per-row message bytes."""
        off, length = 0, cols.shape[1]
        while off < length:
            chunk = min(STROBE_R - self.pos, length - off)
            self.cur[self.pos : self.pos + chunk] ^= cols[:, off : off + chunk].T
            self.pos += chunk
            off += chunk
            if self.pos == STROBE_R:
                self._run_f()

    def _begin_op(self, flags: int, more: bool) -> None:
        if more:
            assert flags == self.cur_flags
            return
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb_shared(bytes([old_begin, flags]))
        if (flags & (FLAG_C | 0x20)) != 0 and self.pos != 0:
            self._run_f()

    # -- merlin framing --

    def append_message(self, label: bytes, cols: np.ndarray | bytes) -> None:
        length = len(cols) if isinstance(cols, bytes) else cols.shape[1]
        self._begin_op(FLAG_M | FLAG_A, False)
        self._absorb_shared(label)
        self._begin_op(FLAG_M | FLAG_A, True)
        self._absorb_shared(length.to_bytes(4, "little"))
        self._begin_op(FLAG_A, False)
        if isinstance(cols, bytes):
            self._absorb_shared(cols)
        else:
            self._absorb_cols(cols)

    def finish_challenge(self, label: bytes) -> None:
        """challenge_bytes(label, 64) up to (and including) the forced
        permutation; the 64 output bytes are then state[0:64]."""
        self._begin_op(FLAG_M | FLAG_A, False)
        self._absorb_shared(label)
        self._begin_op(FLAG_M | FLAG_A, True)
        self._absorb_shared(WIDE.to_bytes(4, "little"))
        self._begin_op(FLAG_I | FLAG_A | FLAG_C, False)
        # begin_op absorbed 2 header bytes, so pos != 0: the C flag always
        # forces a permutation here — exactly one final run_f
        assert self.pos == 0 and not self.cur.any(), "PRF must land on a boundary"


import functools


@functools.cache
def _shared_prefix() -> "host_strobe.Strobe128":
    """Strobe state after the shared Merlin + protocol-DST prefix.

    Depends only on module constants, so it is computed once — the init
    runs a pure-Python Keccak permutation, which would otherwise be paid
    per batch in a throughput-oriented API.  _BatchStrobe only reads the
    snapshot (copies the state bytes), never mutates the cached object.
    """
    s = host_strobe.Strobe128(b"Merlin v1.0")
    # MerlinTranscript(PROTOCOL_LABEL) then append protocol DST
    for label, msg in ((b"dom-sep", PROTOCOL_LABEL), (b"protocol", PROTOCOL_DST)):
        s.meta_ad(label, False)
        s.meta_ad(len(msg).to_bytes(4, "little"), True)
        s.ad(msg, False)
    return s


def _bytes_to_lanes_np(block: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """[200, n] uint8 -> (hi, lo) [25, n] int32 (little-endian lanes)."""
    b = block.reshape(25, 8, -1).astype(np.uint64)
    lane = np.zeros((25, b.shape[2]), dtype=np.uint64)
    for i in range(8):
        lane |= b[:, i] << np.uint64(8 * i)
    hi = (lane >> np.uint64(32)).astype(np.uint32).astype(np.int32)
    lo = (lane & np.uint64(0xFFFFFFFF)).astype(np.uint32).astype(np.int32)
    return hi, lo


@jax.jit
def _absorb_permute_chain(s_hi, s_lo, m_hi, m_lo):
    """state XOR mask -> permute, scanned over the [k, 25, n] mask stack."""

    def step(carry, m):
        hi, lo = carry
        hi, lo = dev_keccak.keccak_f1600((hi ^ m[0], lo ^ m[1]))
        return (hi, lo), None

    (hi, lo), _ = lax.scan(step, (s_hi, s_lo), (m_hi, m_lo))
    return hi, lo


def derive_challenges_device(
    context_cols: np.ndarray | None,
    g_cols: np.ndarray,
    h_cols: np.ndarray,
    y1_cols: np.ndarray,
    y2_cols: np.ndarray,
    r1_cols: np.ndarray,
    r2_cols: np.ndarray,
) -> np.ndarray:
    """[n, 64] challenge bytes for n rows (device permutations).

    Column args are [n, 32] uint8 (context optional, any shared length);
    the wide reduction mod l stays on the host — the caller feeds the
    bytes to ``sc_from_bytes_mod_order_wide`` (or keeps them for
    diagnostics)."""
    n = g_cols.shape[0]
    bs = _BatchStrobe(_shared_prefix(), n)
    if context_cols is not None:
        bs.append_message(b"context", np.asarray(context_cols, dtype=np.uint8))
    for label, cols in (
        (b"generator-g", g_cols), (b"generator-h", h_cols),
        (b"y1", y1_cols), (b"y2", y2_cols),
        (b"r1", r1_cols), (b"r2", r2_cols),
    ):
        bs.append_message(label, np.asarray(cols, dtype=np.uint8))
    bs.finish_challenge(CHALLENGE_DST)

    base = np.frombuffer(bs.base_state, dtype=np.uint8)[:, None]
    s_hi, s_lo = _bytes_to_lanes_np(np.broadcast_to(base, (200, n)).copy())
    masks = [_bytes_to_lanes_np(b) for b in bs.blocks]
    m_hi = jnp.asarray(np.stack([m[0] for m in masks]))
    m_lo = jnp.asarray(np.stack([m[1] for m in masks]))
    hi, lo = _absorb_permute_chain(
        jnp.asarray(s_hi), jnp.asarray(s_lo), m_hi, m_lo
    )
    lanes = dev_keccak.state_to_lanes((hi, lo))  # [n, 25] uint64
    le = lanes[:, :8].copy().view(np.uint8).reshape(n, 64)
    return le
