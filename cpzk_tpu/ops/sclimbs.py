"""Batched scalar-field arithmetic mod l on the device (Barrett).

l = 2^252 + 27742317777372353535851937790883648493 has a 125-bit "tail",
so the cheap fold trick the GF(2^255-19) plane uses (2^260 = 608 mod p,
``ops/limbs.py``) does not exist here — reduction is a textbook Barrett
with the precomputed reciprocal mu = floor(b^(2K) / l) at limb base
b = 2^13, K = 20 limbs.

Why this module exists (SURVEY.md §7 / the 1M proofs/s budget): the RLC
combined check needs per-row scalar products a*c, b*a, b*a*c, the inner
product sum(a*s) mod l, and signed-digit/window decomposition.  On the
host those are Python big-int loops — microseconds per row, i.e.
*seconds* per 1M-row batch; here they are vectorized device ops over
``[20, n]`` int32 arrays in the same limb-major layout as the rest of
the data plane, wired into ``TpuBackend`` behind ``CPZK_DEVICE_RLC=1``.
``reduce_wide``/``bytes_wide_to_limbs`` additionally provide the
64-byte wide challenge reduction on device — benchmarked as the fused
challenges->scalars alternative (``bench_kernels --only challenge``);
the serving path currently resolves challenges to host Scalars, whose
``int.from_bytes % L`` is cheap at per-RPC granularity.

Representation: values < 2^260 as 20x13-bit limbs (leading limb axis),
same conversions as :mod:`cpzk_tpu.ops.limbs`.  All outputs are fully
reduced (< l) — unlike the field plane's loose carried form, scalar
consumers (digit/window decomposition) need canonical values.

Bit-exact vs :mod:`cpzk_tpu.core.scalars` by tests/test_ops_sclimbs.py.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.scalars import L

NLIMBS = 20          # limbs for one reduced scalar (260 bits >= 253)
LIMB_BITS = 13
MASK = (1 << LIMB_BITS) - 1
K = NLIMBS

#: Barrett reciprocal mu = floor(b^(2K) / l), 41 limbs (b^(2K) = 2^520).
_MU = (1 << (2 * K * LIMB_BITS)) // L


def _int_to_limbs_np(v: int, width: int) -> np.ndarray:
    out = np.empty(width, dtype=np.int32)
    for i in range(width):
        out[i] = v & MASK
        v >>= LIMB_BITS
    if v:
        raise ValueError("value too wide")
    return out


_L_LIMBS = _int_to_limbs_np(L, NLIMBS)           # [20]
_MU_LIMBS = _int_to_limbs_np(_MU, 2 * K + 1)     # [41]


def _carry_strip(x: jnp.ndarray, width: int) -> jnp.ndarray:
    """Carry-normalize a non-negative limb vector to EXACTLY canonical
    ``width`` limbs in [0, 2^13) (carries beyond ``width`` must be zero
    by the caller's value bound).

    Two parallel widen rounds shrink limbs from < 2^31 to <= 2^13 + 1;
    a final sequential chain guarantees canonical form — parallel rounds
    alone can ripple 0x1FFF runs one limb per round and never settle,
    and the comparisons downstream (``_ge``) require canonical limbs."""
    pad_cfg = [(0, 0)] * (x.ndim - 1)
    x = jnp.pad(x, [(0, max(0, width - x.shape[0]))] + pad_cfg)[:width]
    for _ in range(2):
        lo = x & MASK
        hi = x >> LIMB_BITS
        x = lo + jnp.pad(hi[:-1], [(1, 0)] + pad_cfg)
    out = []
    carry = jnp.zeros_like(x[0])
    for i in range(width):
        t = x[i] + carry
        carry = t >> LIMB_BITS
        out.append(t & MASK)
    return jnp.stack(out, axis=0)


def _mul_raw(a: jnp.ndarray, b: jnp.ndarray, na: int, nb: int) -> jnp.ndarray:
    """Schoolbook [na, ...] x [nb, ...] -> carried [na+nb, ...] product.

    Anti-diagonal sums stay < max(na, nb) * 2^26 < 2^31 for na, nb <= 41.
    """
    batch = jnp.broadcast_shapes(a.shape[1:], b.shape[1:])
    a = jnp.broadcast_to(a, (na,) + batch)
    b = jnp.broadcast_to(b, (nb,) + batch)
    outer = a[:, None] * b[None, :]  # [na, nb, ...]
    pad_cfg = [(0, 0)] * len(batch)
    width = na + nb - 1
    outer = jnp.pad(outer, [(0, 0), (0, na)] + pad_cfg)  # [na, nb+na, ...]
    flat = outer.reshape((na * (nb + na),) + batch)
    flat = flat[: na * width]
    prod = flat.reshape((na, width) + batch).sum(axis=0)
    return _carry_strip(prod, na + nb)


def _ge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Limbwise lexicographic a >= b for canonical limb vectors -> [...]."""
    gt = a > b
    lt = a < b
    # most-significant difference decides: scan from the top limb down
    result = jnp.zeros(a.shape[1:], dtype=jnp.bool_)
    decided = jnp.zeros(a.shape[1:], dtype=jnp.bool_)
    for i in range(a.shape[0] - 1, -1, -1):
        result = jnp.where(~decided & gt[i], True, result)
        decided = decided | gt[i] | lt[i]
    return result | ~decided  # equal -> >= holds


def _sub_nonneg(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b for a >= b, canonical limbs in/out (sequential borrow)."""
    width = a.shape[0]
    out = []
    borrow = jnp.zeros_like(a[0])
    for i in range(width):
        t = a[i] - (b[i] if i < b.shape[0] else 0) - borrow
        borrow = (t < 0).astype(jnp.int32)
        out.append(t + borrow * (1 << LIMB_BITS))
    return jnp.stack(out, axis=0)


def _cond_sub_l(x: jnp.ndarray) -> jnp.ndarray:
    """x - l when x >= l (x < 2l, canonical [20, ...] limbs)."""
    lv = jnp.asarray(_L_LIMBS).reshape((NLIMBS,) + (1,) * (x.ndim - 1))
    lv = jnp.broadcast_to(lv, x.shape)
    need = _ge(x, lv)
    return jnp.where(need, _sub_nonneg(x, lv), x)


def reduce_wide(x: jnp.ndarray) -> jnp.ndarray:
    """Barrett: [W, ...] limbs (W <= 2K, value < b^(2K)) -> canonical
    [20, ...] limbs of x mod l.

    q_hat = floor( floor(x / b^(K-1)) * mu / b^(K+1) );  r = x - q_hat*l.
    The classic bound gives r < 3l, so the two conditional subtractions
    below finish the reduction.
    """
    batch = x.shape[1:]
    pad_cfg = [(0, 0)] * len(batch)
    w = x.shape[0]
    if w < 2 * K:
        x = jnp.pad(x, [(0, 2 * K - w)] + pad_cfg)
    x_hi = x[K - 1 :]  # floor(x / b^(K-1)), K+1 limbs
    mu = jnp.asarray(_MU_LIMBS).reshape((2 * K + 1,) + (1,) * len(batch))
    prod = _mul_raw(x_hi, mu, K + 1, 2 * K + 1)      # [3K+2, ...]
    q_hat = prod[K + 1 : 2 * K + 2]                   # floor(./b^(K+1)), K+1 limbs
    lv = jnp.asarray(_L_LIMBS).reshape((NLIMBS,) + (1,) * len(batch))
    ql = _mul_raw(q_hat, lv, K + 1, NLIMBS)           # [2K+1, ...]
    # r = x - q_hat*l with 0 <= r < 3l < 2^254: the value fits 20 limbs
    # (260 bits), so subtracting in a (K+2)-limb window cancels the higher
    # limbs exactly and limbs K, K+1 of the result are zero
    r = _sub_nonneg(x[: K + 2], ql[: K + 2])[:K]
    for _ in range(2):  # r < 3l: at most two subtractions
        r = _cond_sub_l(r)
    return r


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Canonical [20, ...] x [20, ...] -> canonical [20, ...] mod l."""
    return reduce_wide(_mul_raw(a, b, NLIMBS, NLIMBS))


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    # sum < 2l < 2^254 fits 20 limbs; one conditional subtract finishes
    return _cond_sub_l(_carry_strip(a + b, NLIMBS))


def neg(a: jnp.ndarray) -> jnp.ndarray:
    """l - a (canonical in/out; maps 0 -> 0 via the conditional subtract)."""
    lv = jnp.asarray(_L_LIMBS).reshape((NLIMBS,) + (1,) * (a.ndim - 1))
    lv = jnp.broadcast_to(lv, a.shape)
    return _cond_sub_l(_sub_nonneg(lv, a))


def sum_mod_l(a: jnp.ndarray) -> jnp.ndarray:
    """Sum canonical [20, n] scalars over the batch axis -> [20, 1].

    A single jnp.sum would overflow int32 past n = 2^18 (limb sums reach
    n * 2^13), so the reduction is hierarchical: chunks of 2^15 columns
    sum exactly (< 2^28), each chunk partial carries to canonical form
    (limbs < 2^13 again), and the n/2^15 partials sum once more — safe up
    to n = 2^33, far past any addressable batch — before one Barrett
    reduction."""
    chunk = 1 << 15
    n = a.shape[-1]
    if n <= chunk:
        s = jnp.sum(a, axis=-1, keepdims=True)
    else:
        pad = (-n) % chunk
        ap = jnp.pad(a, [(0, 0), (0, pad)])
        parts = jnp.sum(ap.reshape(NLIMBS, -1, chunk), axis=-1)  # [20, n/2^15]
        parts = _carry_strip(parts, 2 * K)                        # canonical
        s = jnp.sum(parts, axis=-1, keepdims=True)
    s = _carry_strip(s, 2 * K)
    return reduce_wide(s)


def to_signed_digits(a: jnp.ndarray, c: int) -> jnp.ndarray:
    """Canonical [20, ...] limbs -> [K, ...] signed c-bit digits (LSB
    window first), device twin of
    :func:`cpzk_tpu.ops.msm.scalars_to_signed_digits`.

    Unsigned c-bit windows come from the bit expansion; the borrow recode
    (digit in [-2^(c-1), 2^(c-1))) is a K-step ``lax.scan`` carry chain —
    K <= 64, trivially small next to the MSM it feeds.
    """
    from jax import lax

    from .msm import num_windows

    k = num_windows(c)
    batch = a.shape[1:]
    shifts = jnp.arange(LIMB_BITS, dtype=jnp.int32).reshape(
        (1, LIMB_BITS) + (1,) * len(batch)
    )
    bits = ((a[:, None] >> shifts) & 1).reshape((NLIMBS * LIMB_BITS,) + batch)
    pad_cfg = [(0, 0)] * len(batch)
    bits = jnp.pad(bits, [(0, k * c - NLIMBS * LIMB_BITS)] + pad_cfg)
    w = (1 << jnp.arange(c, dtype=jnp.int32)).reshape((1, c) + (1,) * len(batch))
    u = jnp.sum(bits.reshape((k, c) + batch) * w, axis=1)  # [K, ...] unsigned
    half = 1 << (c - 1)

    def step(carry, uw):
        t = uw + carry
        wrap = (t >= half).astype(jnp.int32)
        return wrap, t - wrap * (1 << c)

    _, digits = lax.scan(step, jnp.zeros(batch, dtype=jnp.int32), u)
    return digits


def to_windows(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical [20, ...] scalar limbs -> [64, ...] 4-bit windows,
    most-significant window first (the layout ``ops.curve`` ladders eat).

    Device twin of :func:`cpzk_tpu.ops.curve.scalars_to_windows`: expands
    the 13-bit limbs to a [260, ...] bit array and regroups nibbles —
    window bits can straddle limb boundaries, so bit granularity is the
    simple uniform formulation (~20 vector ops, no gathers).
    """
    batch = a.shape[1:]
    shifts = jnp.arange(LIMB_BITS, dtype=jnp.int32).reshape(
        (1, LIMB_BITS) + (1,) * len(batch)
    )
    bits = (a[:, None] >> shifts) & 1                   # [20, 13, ...]
    bits = bits.reshape((NLIMBS * LIMB_BITS,) + batch)  # [260, ...]
    bits = bits[:256]                                   # scalars < 2^253
    w = jnp.asarray([1, 2, 4, 8], dtype=jnp.int32).reshape(
        (1, 4) + (1,) * len(batch)
    )
    wins = jnp.sum(bits.reshape((64, 4) + batch) * w, axis=1)  # LSB first
    return wins[::-1]                                   # MSB first


# -- host conversions (shared layout with ops.limbs) ------------------------

def ints_to_limbs(values: list[int]) -> np.ndarray:
    """[n] python ints (mod l) -> [20, n] int32 canonical limbs."""
    return _ints(values)


def _ints(values: list[int]) -> np.ndarray:
    blob = b"".join((v % L).to_bytes(33, "little") for v in values)
    raw = np.frombuffer(blob, dtype=np.uint8).reshape(len(values), 33)
    bits = np.unpackbits(raw, axis=1, bitorder="little")[:, : NLIMBS * LIMB_BITS]
    weights = 1 << np.arange(LIMB_BITS, dtype=np.int32)
    rows = bits.reshape(len(values), NLIMBS, LIMB_BITS).astype(np.int32) @ weights
    return np.ascontiguousarray(rows.T)


def bytes_wide_to_limbs(blob: np.ndarray) -> np.ndarray:
    """[n, 64] uint8 (wide challenge bytes) -> [40, n] int32 limbs."""
    raw = np.asarray(blob, dtype=np.uint8).reshape(-1, 64)
    bits = np.unpackbits(raw, axis=1, bitorder="little")
    bits = np.pad(bits, [(0, 0), (0, 40 * LIMB_BITS - 512)])
    weights = 1 << np.arange(LIMB_BITS, dtype=np.int32)
    rows = bits.reshape(len(raw), 40, LIMB_BITS).astype(np.int32) @ weights
    return np.ascontiguousarray(rows.T)


def limbs_to_ints(limbs: np.ndarray) -> list[int]:
    arr = np.asarray(limbs).reshape(NLIMBS, -1)
    return [
        sum(int(arr[i, j]) << (LIMB_BITS * i) for i in range(NLIMBS))
        for j in range(arr.shape[1])
    ]
