"""Batched edwards25519 / ristretto255 point kernels (JAX).

Points are structure-of-arrays extended coordinates: a tuple
``(X, Y, Z, T)`` of ``[20, ...batch]`` int32 limb arrays (x = X/Z, y = Y/Z,
T = XY/Z).  The limb axis leads and the batch axes trail so the batch rides
the TPU vector lanes (see :mod:`cpzk_tpu.ops.limbs`).  Everything is batched
over trailing axes and shardable along them; no data-dependent control flow
(masks/selects only), so the whole thing stays inside one XLA program.

Re-design (not a port) of the point layer that curve25519-dalek provides
under the reference's ``src/primitives/ristretto.rs`` (SURVEY.md §2.2):

- unified add / double (HWCD'08 a=-1 formulas, same as the host twin
  :mod:`cpzk_tpu.core.edwards`)
- on-device ristretto DECODE (RFC 9496 §4.3.1) from wire bytes, returning a
  validity mask instead of raising — the adversarial checks of
  ``ristretto.rs:120-138`` become lane masks
- on-device ENCODE (RFC 9496 §4.3.2) for compressed output
- windowed (4-bit) double-and-add scalar multiplication with per-lane
  precomputed tables — scalars are public verification inputs here
  (vartime is fine; see docs/security.md)
- batch tree-reduction point sum for the combined RLC check

Table lookups use a bitwise select tree (4 levels of lane-masked where)
instead of gather HLOs: every step is a pure vector op with the batch on the
lanes, so the lookup cost is deterministic on TPU regardless of how XLA
would lower a lane-crossing gather.
"""

from __future__ import annotations

import threading

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..core import edwards as host_edwards
from . import limbs
from .limbs import NLIMBS

Point = tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]

WINDOW_BITS = 4
NWINDOWS = 64  # ceil(253 / 4) -> 64 windows cover 256 bits
TABLE = 1 << WINDOW_BITS


# ---------------------------------------------------------------------------
# host <-> device marshalling
# ---------------------------------------------------------------------------

def points_to_device(points: list[host_edwards.Point]) -> Point:
    """Host extended-coordinate points -> SoA limb arrays [20, n] x 4."""
    xs = limbs.ints_to_limbs([p[0] for p in points])
    ys = limbs.ints_to_limbs([p[1] for p in points])
    zs = limbs.ints_to_limbs([p[2] for p in points])
    ts = limbs.ints_to_limbs([p[3] for p in points])
    return (jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(zs), jnp.asarray(ts))


#: Per-thread reusable decode staging (coords + ok buffers per padded
#: shape).  The serving dispatch lane marshals every batch on ONE
#: persistent device thread, so each shape's 129*pad-byte staging pair is
#: allocated once and reused for the lifetime of the lane instead of per
#: batch; other threads (direct BatchVerifier users, tests) get their own
#: pool.  Bounded: the lane padding schedule keeps live shapes to a
#: handful, and the pool evicts FIFO past that.
_STAGING = threading.local()
_STAGING_SHAPES_MAX = 8


def _staging_buffers(pad: int) -> tuple[np.ndarray, np.ndarray]:
    pool = getattr(_STAGING, "pool", None)
    if pool is None:
        pool = _STAGING.pool = {}
    bufs = pool.get(pad)
    if bufs is None:
        while len(pool) >= _STAGING_SHAPES_MAX:
            pool.pop(next(iter(pool)))
        bufs = pool[pad] = (
            np.empty((pad, 4, 32), dtype=np.uint8),
            np.empty((pad,), dtype=np.uint8),
        )
    return bufs


def wires_to_device(wires: bytes, pad: int, device=None) -> Point | None:
    """n concatenated 32-byte wire encodings -> SoA limb arrays
    [20, pad] x 4, decoding on the native worker pool (~340 us/point of
    Python big-int decode avoided — the serving-path marshalling
    bottleneck) directly into the calling thread's reusable staging
    buffers (no per-batch coordinate-buffer allocation).  Identity-pads
    to ``pad`` columns.  ``device`` targets the transfer at a specific
    jax device (``jax.device_put``) — the per-device dispatch lanes pin
    each lane's batches to its own chip; None keeps the default-device
    behavior.  Staging buffers are per-THREAD, so each lane's persistent
    device thread owns its own pair and lanes never contend.  Returns
    None when the native core is unavailable (caller falls back to the
    Python path); raises on an invalid encoding (callers marshal elements
    that already passed parse-time validation, so this is a can't-happen
    guard, not a validation layer)."""
    from ..core import _native
    from ..errors import InvalidGroupElement

    n = len(wires) // 32
    if pad > n:
        wires = wires + bytes(32) * (pad - n)  # identity wire is all-zero
    rows, ok = _staging_buffers(pad)
    if _native.batch_decode_into(wires, rows, ok) is None:
        return None
    if not (ok == 1).all():
        raise InvalidGroupElement("batch decode of pre-validated wire failed")
    # bytes_to_limbs materializes fresh limb arrays, so the staging rows
    # are free for reuse the moment this returns
    if device is not None:
        from jax import device_put

        return tuple(
            device_put(
                limbs.bytes_to_limbs(np.ascontiguousarray(rows[:, k, :])),
                device,
            )
            for k in range(4)
        )
    return tuple(
        jnp.asarray(limbs.bytes_to_limbs(np.ascontiguousarray(rows[:, k, :])))
        for k in range(4)
    )


def points_soa(points: list[host_edwards.Point], pad: int) -> Point:
    """Identity-padded SoA limb marshal: the canonical way to build a
    [20, pad] x 4 device batch from host points.  Shared by the backend
    and the driver dryrun so their marshalling conventions cannot drift."""
    return points_to_device(points + [host_edwards.IDENTITY] * (pad - len(points)))


def scalar_windows(values: list[int], pad: int) -> jnp.ndarray:
    """Zero-padded window decomposition of a scalar batch (device array)."""
    return jnp.asarray(scalars_to_windows(values + [0] * (pad - len(values))))


def points_from_device(pt: Point) -> list[host_edwards.Point]:
    coords = [limbs.limbs_to_ints(np.asarray(c)) for c in pt]
    return list(zip(*coords))


def identity(shape: tuple[int, ...] = ()) -> Point:
    z = jnp.zeros((NLIMBS,) + shape, dtype=jnp.int32)
    one = jnp.broadcast_to(
        limbs.ONE[:, 0].reshape((NLIMBS,) + (1,) * len(shape)), (NLIMBS,) + shape
    )
    return (z, one, one, z)


# ---------------------------------------------------------------------------
# group operations
# ---------------------------------------------------------------------------

def _pallas():
    """Lazy opt-in hook for the explicit-tiling pallas kernels."""
    import os

    if os.environ.get("CPZK_PALLAS", "") not in ("1", "true", "on"):
        return None
    from . import pallas_kernels

    return pallas_kernels


def add(p: Point, q: Point) -> Point:
    """Unified a=-1 extended addition (add-2008-hwcd-3); twin of
    ``core.edwards.pt_add``."""
    pk = _pallas()
    if pk is not None and pk.supported(p) and p[0].shape == q[0].shape:
        return pk.point_add(p, q)
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = limbs.mul(limbs.sub(Y1, X1), limbs.sub(Y2, X2))
    B = limbs.mul(limbs.add(Y1, X1), limbs.add(Y2, X2))
    C = limbs.mul(limbs.mul(T1, limbs.D2), T2)
    Dv = limbs.mul_small(limbs.mul(Z1, Z2), 2)
    E = limbs.sub(B, A)
    F = limbs.sub(Dv, C)
    G = limbs.add(Dv, C)
    H = limbs.add(B, A)
    return (limbs.mul(E, F), limbs.mul(G, H), limbs.mul(F, G), limbs.mul(E, H))


def double(p: Point) -> Point:
    """a=-1 doubling (dbl-2008-hwcd); twin of ``core.edwards.pt_double``."""
    pk = _pallas()
    if pk is not None and pk.supported(p):
        return pk.point_double(p)
    X1, Y1, Z1, _ = p
    A = limbs.square(X1)
    B = limbs.square(Y1)
    C = limbs.mul_small(limbs.square(Z1), 2)
    H = limbs.add(A, B)
    E = limbs.sub(H, limbs.square(limbs.add(X1, Y1)))
    G = limbs.sub(A, B)
    F = limbs.add(C, G)
    return (limbs.mul(E, F), limbs.mul(G, H), limbs.mul(F, G), limbs.mul(E, H))


def double_k(p: Point, k: int) -> Point:
    """k consecutive doublings (k static).  With the Pallas path enabled
    this is ONE fused kernel keeping intermediates in VMEM — the ladder's
    WINDOW_BITS-per-step doubling run is the hottest op sequence in both
    verify kernels; the XLA fallback is a plain loop."""
    if k == 0:
        return p
    pk = _pallas()
    if pk is not None and pk.supported(p):
        return pk.point_double_k(p, k)
    for _ in range(k):
        p = double(p)
    return p


def negate(p: Point) -> Point:
    X, Y, Z, T = p
    return (limbs.neg(X), Y, Z, limbs.neg(T))


def select(mask: jnp.ndarray, p: Point, q: Point) -> Point:
    """Lane-wise where(mask, p, q); mask shaped [...batch] (no limb axis)."""
    return tuple(limbs.select(mask, a, b) for a, b in zip(p, q))


def cond_negate(mask: jnp.ndarray, p: Point) -> Point:
    """Lane-wise negate where mask is set (cheap: negate X and T)."""
    X, Y, Z, T = p
    return (jnp.where(mask, -X, X), Y, Z, jnp.where(mask, -T, T))


def eq(p: Point, q: Point) -> jnp.ndarray:
    """Ristretto (quotient-group) equality: X1*Y2 == Y1*X2 or
    Y1*Y2 == X1*X2 — twin of ``core.edwards.pt_eq``."""
    X1, Y1, _, _ = p
    X2, Y2, _, _ = q
    a = limbs.eq(limbs.mul(X1, Y2), limbs.mul(Y1, X2))
    b = limbs.eq(limbs.mul(Y1, Y2), limbs.mul(X1, X2))
    return a | b


def is_identity(p: Point) -> jnp.ndarray:
    """Identity test in the quotient group: X == 0 or Y == 0 (the identity
    coset {(0,±1),(±i,0)} is exactly X*Y == 0 among valid points)."""
    X, Y, _, _ = p
    return limbs.is_zero(X) | limbs.is_zero(Y)


# ---------------------------------------------------------------------------
# scalar multiplication
# ---------------------------------------------------------------------------

def scalars_to_windows(values: list[int]) -> np.ndarray:
    """Host: scalars (already reduced mod l) -> [64, n] int32 of 4-bit
    windows, most-significant window first (window axis leading, to match
    the device layout convention)."""
    blob = b"".join(int(v).to_bytes(32, "little") for v in values)
    raw = np.frombuffer(blob, dtype=np.uint8).reshape(len(values), 32)
    lo = raw & 0x0F
    hi = raw >> 4
    nibbles = np.empty((len(values), NWINDOWS), dtype=np.int32)
    nibbles[:, 0::2] = lo
    nibbles[:, 1::2] = hi
    return np.ascontiguousarray(nibbles[:, ::-1].T)  # [64, n], MSB first


def build_table(p: Point) -> tuple[jnp.ndarray, ...]:
    """[0..15] * p as stacked coords: 4 x [16, 20, ...batch].

    Built with a lax.scan of 14 batched adds so the XLA graph stays small.
    """
    def step(acc: Point, _):
        nxt = add(acc, p)
        return nxt, nxt

    _, rest = lax.scan(step, p, None, length=TABLE - 2)  # coords [14, 20, ...]
    ident = identity(p[0].shape[1:])
    return tuple(
        jnp.concatenate([ident[i][None], p[i][None], rest[i]], axis=0)
        for i in range(4)
    )


def table_gather(table: tuple[jnp.ndarray, ...], idx: jnp.ndarray) -> Point:
    """Select table[idx] per lane via a 4-level bit select tree.

    ``table`` coords are [16, 20, ...batch] (batch may be size-1 for shared
    tables); ``idx`` is [...batch] in [0, 16).  15 lane-masked selects per
    coordinate — all pure vector ops, no gather HLO.
    """
    out = []
    for c in table:
        t = c
        for k in range(WINDOW_BITS):
            bit = ((idx >> k) & 1).astype(jnp.bool_)
            t = jnp.where(bit, t[1::2], t[0::2])
        out.append(t[0])
    return tuple(out)


def scalar_mul(p: Point, windows: jnp.ndarray) -> Point:
    """Batched windowed double-and-add: [20, ...]-point ** [64, ...]-windows.

    Per lane: precompute table [0..15]*P (14 batched adds), then 64 steps of
    4 doublings + one selected table add.  ~255 doubles + 79 adds per lane,
    fully vectorized across the batch; variable-base, variable-time in the
    *public* scalar only (verification inputs).
    """
    table = build_table(p)

    def step(acc: Point, w: jnp.ndarray) -> tuple[Point, None]:
        acc = double_k(acc, WINDOW_BITS)
        return add(acc, table_gather(table, w)), None

    acc0 = identity(windows.shape[1:])
    acc, _ = lax.scan(step, acc0, windows)
    return acc


def tree_sum(p: Point, axis: int = -1) -> Point:
    """Reduce-sum of points along a batch ``axis`` by halving (log2 n
    batched adds).  Pads to a power of two with identity points."""
    coords = [jnp.moveaxis(c, axis if axis >= 0 else c.ndim + axis, 1) for c in p]
    n = coords[0].shape[1]
    size = 1
    while size < n:
        size *= 2
    if size != n:
        pad = identity((size - n,) + coords[0].shape[2:])
        coords = [jnp.concatenate([c, pc], axis=1) for c, pc in zip(coords, pad)]
    pt = tuple(coords)
    while pt[0].shape[1] > 1:
        half = pt[0].shape[1] // 2
        a = tuple(c[:, :half] for c in pt)
        b = tuple(c[:, half:] for c in pt)
        pt = add(a, b)
    return tuple(c[:, 0] for c in pt)


# ---------------------------------------------------------------------------
# ristretto decode / encode (device-side, batched)
# ---------------------------------------------------------------------------

def decode(wire: jnp.ndarray) -> tuple[Point, jnp.ndarray]:
    """RFC 9496 DECODE on [32, ...batch] byte arrays.

    Returns (point, valid_mask). Invalid lanes yield the identity point with
    ``valid == False`` — the reference's error returns
    (``ristretto.rs:120-138``) become mask bits the caller folds into its
    accept/reject output.
    """
    b = wire.astype(jnp.int32)
    s = limbs.from_bytes_le(b)
    # canonical check: re-encoding must reproduce the input bytes
    canonical_ok = jnp.all(limbs.to_bytes_le(s) == b, axis=0)
    even_ok = (b[0] & 1) == 0

    ss = limbs.square(s)
    u1 = limbs.sub(limbs.ONE, ss)
    u2 = limbs.add(limbs.ONE, ss)
    u2_sqr = limbs.square(u2)
    v = limbs.sub(limbs.neg(limbs.mul(limbs.D, limbs.square(u1))), u2_sqr)
    was_square, invsqrt = limbs.sqrt_ratio_m1(limbs.ONE, limbs.mul(v, u2_sqr))
    den_x = limbs.mul(invsqrt, u2)
    den_y = limbs.mul(limbs.mul(invsqrt, den_x), v)
    x = limbs.fabs(limbs.mul(limbs.mul_small(s, 2), den_x))
    y = limbs.mul(u1, den_y)
    t = limbs.mul(x, y)

    valid = (
        canonical_ok
        & even_ok
        & was_square
        & ~limbs.is_negative(t)
        & ~limbs.is_zero(y)
    )
    one = identity(x.shape[1:])[1]
    zero = jnp.zeros_like(x)
    pt = select(valid, (x, y, one, t), (zero, one, one, zero))
    return pt, valid


def encode(p: Point) -> jnp.ndarray:
    """RFC 9496 ENCODE -> [32, ...batch] int32 byte values; twin of
    ``core.edwards.ristretto_encode``."""
    X0, Y0, Z0, T0 = p
    u1 = limbs.mul(limbs.add(Z0, Y0), limbs.sub(Z0, Y0))
    u2 = limbs.mul(X0, Y0)
    _, invsqrt = limbs.sqrt_ratio_m1(limbs.ONE, limbs.mul(u1, limbs.square(u2)))
    den1 = limbs.mul(invsqrt, u1)
    den2 = limbs.mul(invsqrt, u2)
    z_inv = limbs.mul(limbs.mul(den1, den2), T0)

    ix0 = limbs.mul(X0, limbs.SQRT_M1)
    iy0 = limbs.mul(Y0, limbs.SQRT_M1)
    enchanted = limbs.mul(den1, limbs.INVSQRT_A_MINUS_D)
    rotate = limbs.is_negative(limbs.mul(T0, z_inv))

    x = limbs.select(rotate, iy0, X0)
    y = limbs.select(rotate, ix0, Y0)
    den_inv = limbs.select(rotate, enchanted, den2)

    y = limbs.select(limbs.is_negative(limbs.mul(x, z_inv)), limbs.neg(y), y)
    s = limbs.fabs(limbs.mul(den_inv, limbs.sub(Z0, y)))
    return limbs.to_bytes_le(s)
