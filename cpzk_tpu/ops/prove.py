"""TPU batch proof generation (BASELINE config 3; reference analog
``src/prover/mod.rs:115-131`` scaled to device batches).

Commitments (R1, R2) = (k·G, k·H) and statements (Y1, Y2) = (x·G, x·H) are
*fixed-base* scalar multiplications, so the kernel uses a comb method: for
each generator, precompute per-window tables T_w[j] = j·16^w·P (64 windows
x 16 entries, built once on device by a tiny scan program), then each point
is just 64 table-selects + adds per lane — **zero doublings**, ~5x fewer
point-ops than a variable-base ladder.  Ristretto encoding also happens on
device; the host only draws nonces, derives Fiat-Shamir challenges (C++
transcript core), and closes the responses s = k + c·x mod l.

SECURITY (docs/security.md, SURVEY.md §7 hard part 5): batch proving places
secrets (k, x) in device HBM as public-layout digit arrays.  Device memory
cannot be meaningfully zeroized and XLA may checkpoint buffers — this path
trusts the whole accelerator host and is OPT-IN for bulk workloads
(test-corpus generation, load benches, migration tooling).  Interactive
single-user proving belongs on the host path (``protocol.Prover``).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.ristretto import Ristretto255, Scalar
from ..core.rng import SecureRng
from ..core.scalars import L
from ..core.transcript import derive_challenges_batch
from ..protocol.gadgets import PROTOCOL_VERSION, Parameters, frame_fields
from . import curve
from .curve import NWINDOWS, Point, build_table, table_gather


def _comb_tables_kernel(p: Point):
    """[64, 16, 20, 1] per-window tables T_w[j] = j * 16^w * P."""

    def step(base: Point, _):
        table = build_table(base)  # [16, 20, 1] coords
        nb = base
        for _ in range(4):
            nb = curve.double(nb)  # next window base: 16 * base
        return nb, table

    _, tables = lax.scan(step, p, None, length=NWINDOWS)
    return tables


def _fixed_base_kernel(tables, digits: jnp.ndarray) -> Point:
    """sum_w T_w[digit_w] per lane; ``digits`` [64, n] LSB window first."""

    def step(acc: Point, tw_d):
        table, d = tw_d
        return curve.add(acc, table_gather(table, d)), None

    acc, _ = lax.scan(step, curve.identity((digits.shape[-1],)), (tables, digits))
    return acc


@jax.jit
def _commitments_kernel(tg, th, digits):
    """digits [64, n] -> (R1 wire bytes [32, n], R2 wire bytes [32, n])."""
    r1 = _fixed_base_kernel(tg, digits)
    r2 = _fixed_base_kernel(th, digits)
    return curve.encode(r1), curve.encode(r2)


def _windows_lsb(values: list[int]) -> jnp.ndarray:
    """[64, n] 4-bit windows, least-significant window first (comb order)."""
    return jnp.asarray(curve.scalars_to_windows(values)[::-1].copy())


class BatchProver:
    """Bulk proof generation on the device data plane.

    >>> bp = BatchProver(Parameters.new())
    >>> statements, proofs = bp.prove(witnesses, contexts, rng)

    Returns per-proof ((y1_bytes, y2_bytes), proof_wire_bytes); the wire
    bytes parse under ``Proof.from_bytes`` and verify with the standard
    ``Verifier`` — differential tests in ``tests/test_batch_prove.py``.
    """

    def __init__(self, params: Parameters | None = None,
                 mesh_devices: int | None = None):
        """``mesh_devices``: ``None`` pins single-device; ``0`` shards the
        digit batch axis over all visible devices; ``k > 1`` over the
        first k (pure DP — proofs are independent, no collectives)."""
        self.params = params or Parameters.new()
        g = curve.points_to_device([self.params.generator_g.point])
        h = curve.points_to_device([self.params.generator_h.point])
        build = jax.jit(_comb_tables_kernel)
        self._tg = jax.block_until_ready(build(g))
        self._th = jax.block_until_ready(build(h))
        self._sharded = None
        if mesh_devices is not None:
            from ..parallel import batch_mesh, make_sharded_prove, resolve_mesh_devices

            devices = resolve_mesh_devices(mesh_devices)
            if devices is not None:
                self._sharded = make_sharded_prove(batch_mesh(devices))

    def _fixed_base_bytes(self, scalars: list[int]) -> tuple[np.ndarray, np.ndarray]:
        """(P1, P2) wire bytes for (k·G, k·H) per scalar.

        Proofs are lane-independent, so batches past the device's proven
        program size run as LANE_CHUNK-lane tiles and the wire-byte
        columns concatenate (same >33k-lane XLA miscompile workaround as
        the verifier dispatch, ``ops/backend.py``)."""
        from .backend import LANE_CHUNK, _chunk_bounds, _pad_lanes

        n = len(scalars)
        pad = _pad_lanes(n)
        digits = _windows_lsb(scalars + [0] * (pad - n))
        if self._sharded is not None:
            b1, b2 = self._sharded(self._tg, self._th, digits)
        elif pad > LANE_CHUNK:
            parts = [
                _commitments_kernel(self._tg, self._th, digits[:, lo:hi])
                for lo, hi in _chunk_bounds(pad)
            ]
            b1 = jnp.concatenate([p[0] for p in parts], axis=-1)
            b2 = jnp.concatenate([p[1] for p in parts], axis=-1)
        else:
            b1, b2 = _commitments_kernel(self._tg, self._th, digits)
        return (
            np.asarray(b1, dtype=np.uint8)[:, :n],
            np.asarray(b2, dtype=np.uint8)[:, :n],
        )

    def statements(self, witnesses: list[Scalar]) -> list[tuple[bytes, bytes]]:
        """(y1, y2) wire bytes per witness (registration-side bulk helper)."""
        y1b, y2b = self._fixed_base_bytes([w.value for w in witnesses])
        return [
            (y1b[:, i].tobytes(), y2b[:, i].tobytes()) for i in range(len(witnesses))
        ]

    def prove(
        self,
        witnesses: list[Scalar],
        contexts: list[bytes | None] | None = None,
        rng: SecureRng | None = None,
        statements: list[tuple[bytes, bytes]] | None = None,
    ) -> tuple[list[tuple[bytes, bytes]], list[bytes]]:
        """NIZK proofs for every witness -> (statements, proof wire bytes).

        ``statements`` skips the statement recomputation when the caller
        already holds the registered (y1, y2) bytes.
        """
        rng = rng or SecureRng()
        n = len(witnesses)
        contexts = contexts if contexts is not None else [None] * n
        if len(contexts) != n:
            raise ValueError("contexts length mismatch")
        if statements is not None and len(statements) != n:
            raise ValueError("statements length mismatch")

        xs = [w.value for w in witnesses]
        if statements is None:
            statements = self.statements(witnesses)

        # nonces on the host CSPRNG; commitments on device
        ks = [Ristretto255.random_scalar(rng).value for _ in range(n)]
        r1b, r2b = self._fixed_base_bytes(ks)
        r1s = [r1b[:, i].tobytes() for i in range(n)]
        r2s = [r2b[:, i].tobytes() for i in range(n)]

        gb = Ristretto255.element_to_bytes(self.params.generator_g)
        hb = Ristretto255.element_to_bytes(self.params.generator_h)
        challenges = derive_challenges_batch(
            contexts,
            [gb] * n,
            [hb] * n,
            [st[0] for st in statements],
            [st[1] for st in statements],
            r1s,
            r2s,
        )

        proofs = []
        for i in range(n):
            s = (ks[i] + challenges[i].value * xs[i]) % L
            proofs.append(
                frame_fields(
                    PROTOCOL_VERSION, r1s[i], r2s[i], s.to_bytes(32, "little")
                )
            )
        return statements, proofs


__all__ = ["BatchProver"]
