"""TPU data plane: batched GF(2^255-19) / ristretto255 kernels as JAX programs.

This package is the TPU-native re-design of the compute that the reference
delegates to curve25519-dalek (SURVEY.md §2.2): field arithmetic, point
arithmetic, and batch verification — expressed as vectorized operations over
limb-major ``[NLIMBS, batch]`` int32 arrays — the batch axis rides the
128-wide vector lanes, with `jax.sharding` handling multi-chip scale (see
:mod:`cpzk_tpu.parallel`).

Public surface:

- :mod:`.limbs` / :mod:`.curve` — field + point kernels
- :mod:`.verify` — per-proof and per-row combined verification kernels
- :mod:`.msm` — windowed-Pippenger multi-scalar multiplication
- :mod:`.prove` — fixed-base comb batch proof generation (``BatchProver``)
- :mod:`.keccak` — batched Keccak-f[1600] permutation (hi/lo int32 lanes)
- :mod:`.challenge` — Fiat-Shamir challenge derivation with device Keccak
- :mod:`.backend` — the ``TpuBackend`` dispatching all of the above
- :mod:`.pallas_kernels` — opt-in explicit-tiling kernels (``CPZK_PALLAS=1``)

Submodules import jax lazily enough for host-only use of the package; pull
``TpuBackend``/``BatchProver`` via their submodules to keep import costs
where they are used.
"""
