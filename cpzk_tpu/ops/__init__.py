"""TPU data plane: batched GF(2^255-19) / ristretto255 kernels as JAX programs.

This package is the TPU-native re-design of the compute that the reference
delegates to curve25519-dalek (SURVEY.md §2.2): field arithmetic, point
arithmetic, and batch verification — expressed as vectorized operations over
limb-major ``[NLIMBS, batch]`` int32 arrays — the batch axis rides the
128-wide vector lanes, with `jax.sharding` handling multi-chip scale (see
:mod:`cpzk_tpu.parallel`).
"""
