"""Batched Keccak-f[1600] on the device (JAX int32 lanes).

SURVEY.md §7 hard part 4: challenge-hash throughput at 1M proofs/sec.
The host plane derives Fiat-Shamir challenges on a C++ thread pool
(``native/merlin.cpp``); this kernel is the device alternative — the
permutation batched over proofs, so the batch axis rides the TPU vector
lanes exactly like the limb arithmetic in :mod:`cpzk_tpu.ops.limbs`.

TPU has no 64-bit integer lanes, so each Keccak lane is an (hi, lo)
int32 pair and the state is two ``[25, n]`` int32 arrays.  64-bit XOR is
two 32-bit XORs; rotl64 decomposes into cross-word shifts on the pair
(a rotation by exactly 32 swaps the words).  Everything below is pure
jnp with a Python-unrolled 24-round loop — ~3.8k vector ops per
permutation, fully data-independent, so one ``jit`` covers any batch.

Bit-exact vs the host oracle (:mod:`cpzk_tpu.core.keccak`, itself
validated against hashlib SHA3) by ``tests/test_ops_keccak.py``.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..core import keccak as host_keccak

_RHO = host_keccak._RHO
_RC = host_keccak._ROUND_CONSTANTS

State = tuple[jnp.ndarray, jnp.ndarray]  # (hi, lo) each [25, ...] int32


def _rotl(hi: jnp.ndarray, lo: jnp.ndarray, n: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """64-bit rotate-left by a static n on (hi, lo) int32 pairs.

    Logical >> on int32 needs a mask after the arithmetic shift; << is
    exact mod 2^32.  n == 32 is a pure word swap; n < 32 shifts within
    words with cross-word carries, n > 32 is swap + shift.
    """
    n %= 64
    if n == 0:
        return hi, lo
    if n == 32:
        return lo, hi
    if n > 32:
        hi, lo = lo, hi
        n -= 32
    # 0 < n < 32: out_hi = hi << n | lo >>> (32-n), out_lo = lo << n | hi >>> (32-n)
    m = (1 << n) - 1  # mask for the (32-n) logical right shift result
    rhi = (hi << n) | ((lo >> (32 - n)) & m)
    rlo = (lo << n) | ((hi >> (32 - n)) & m)
    return rhi, rlo


_RC_PAIRS = np.array(
    [[(rc >> 32) & 0xFFFFFFFF, rc & 0xFFFFFFFF] for rc in _RC], dtype=np.uint32
).astype(np.int32)  # [24, 2] (hi, lo)


def _round(ahi: list, alo: list, rc_hi, rc_lo) -> tuple[list, list]:
    """One Keccak round on unstacked (hi, lo) lane lists."""
    # theta
    chi = [ahi[x] ^ ahi[x + 5] ^ ahi[x + 10] ^ ahi[x + 15] ^ ahi[x + 20] for x in range(5)]
    clo = [alo[x] ^ alo[x + 5] ^ alo[x + 10] ^ alo[x + 15] ^ alo[x + 20] for x in range(5)]
    for x in range(5):
        rh, rl = _rotl(chi[(x + 1) % 5], clo[(x + 1) % 5], 1)
        dh, dl = chi[(x + 4) % 5] ^ rh, clo[(x + 4) % 5] ^ rl
        for y in range(5):
            ahi[x + 5 * y] = ahi[x + 5 * y] ^ dh
            alo[x + 5 * y] = alo[x + 5 * y] ^ dl
    # rho + pi
    bhi: list = [None] * 25
    blo: list = [None] * 25
    for x in range(5):
        for y in range(5):
            src = x + 5 * y
            dst = y + 5 * ((2 * x + 3 * y) % 5)
            bhi[dst], blo[dst] = _rotl(ahi[src], alo[src], _RHO[src])
    # chi
    for y in range(5):
        row_h = bhi[5 * y : 5 * y + 5]
        row_l = blo[5 * y : 5 * y + 5]
        for x in range(5):
            ahi[x + 5 * y] = row_h[x] ^ (~row_h[(x + 1) % 5] & row_h[(x + 2) % 5])
            alo[x + 5 * y] = row_l[x] ^ (~row_l[(x + 1) % 5] & row_l[(x + 2) % 5])
    # iota
    ahi[0] = ahi[0] ^ rc_hi
    alo[0] = alo[0] ^ rc_lo
    return ahi, alo


def keccak_f1600(state: State) -> State:
    """One Keccak-f[1600] permutation over a batched state.

    ``state`` is (hi, lo) int32 arrays shaped [25, ...batch], lane index
    x + 5y matching the host oracle.  The 24 rounds run under a
    ``lax.scan`` over the round constants — a fully-unrolled permutation
    is ~12k tiny HLO ops and sends XLA compile time (and memory) through
    the roof; the scanned body is ~500 ops compiled once.
    """

    def body(carry, rc):
        hi, lo = carry
        ahi, alo = _round([hi[i] for i in range(25)], [lo[i] for i in range(25)],
                          rc[0], rc[1])
        return (jnp.stack(ahi, axis=0), jnp.stack(alo, axis=0)), None

    (hi, lo), _ = lax.scan(body, state, jnp.asarray(_RC_PAIRS))
    return hi, lo


# ---------------------------------------------------------------------------
# host <-> device state conversion (for tests and absorb phases)
# ---------------------------------------------------------------------------

def lanes_to_state(lanes: np.ndarray) -> State:
    """[n, 25] uint64 lane values -> device (hi, lo) [25, n] int32."""
    lanes = np.asarray(lanes, dtype=np.uint64).T  # [25, n]
    hi = (lanes >> np.uint64(32)).astype(np.uint32).astype(np.int32)
    lo = (lanes & np.uint64(0xFFFFFFFF)).astype(np.uint32).astype(np.int32)
    return jnp.asarray(hi), jnp.asarray(lo)


def state_to_lanes(state: State) -> np.ndarray:
    """Device (hi, lo) [25, n] -> [n, 25] uint64 lane values."""
    hi = np.asarray(state[0]).astype(np.uint32).astype(np.uint64)
    lo = np.asarray(state[1]).astype(np.uint32).astype(np.uint64)
    return ((hi << np.uint64(32)) | lo).T
