"""Pallas TPU kernels for the hot point operations (experimental, opt-in).

The XLA path in :mod:`cpzk_tpu.ops.curve` already fuses well, but it leaves
scheduling to the compiler.  These kernels pin the choices explicitly: one
VMEM-resident block of ``[20, BLOCK]`` limb-major coordinates per grid step,
with every field multiplication's intermediates (outer product, anti-
diagonal fold, carry rounds) staying on-chip — no HBM round-trips between
the 8 muls of a point add.  The in-kernel field math *reuses*
:mod:`cpzk_tpu.ops.limbs` directly: pallas traces the same jnp ops into
Mosaic, so the arithmetic cannot drift from the tested XLA twin.

Enable with ``CPZK_PALLAS=1`` (see :func:`enabled`); off-TPU backends run
the kernels in interpret mode, which the differential tests use.  This is
the explicit-tiling experiment VERDICT r1 asked for under component #3; the
XLA path remains the default until the Mosaic lowering is validated on real
hardware.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import limbs
from .limbs import NLIMBS

# invalid (non-positive / non-numeric) values fall back to the default
# instead of poisoning every `n % BLOCK` in supported() (ADVICE r2)
try:
    BLOCK = int(os.environ.get("CPZK_PALLAS_BLOCK", "512"))
except ValueError:
    BLOCK = 512
if BLOCK < 1:
    BLOCK = 512

Point = tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]


def enabled() -> bool:
    return os.environ.get("CPZK_PALLAS", "") in ("1", "true", "on")


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _add_kernel(x1, y1, z1, t1, x2, y2, z2, t2, d2, ox, oy, oz, ot):
    """Unified a=-1 extended addition on one [20, BLOCK] block.

    ``d2`` carries the 2d curve constant as a [20, 1] input block (pallas
    forbids captured constants)."""
    X1, Y1, Z1, T1 = x1[...], y1[...], z1[...], t1[...]
    X2, Y2, Z2, T2 = x2[...], y2[...], z2[...], t2[...]
    A = limbs.mul(limbs.sub(Y1, X1), limbs.sub(Y2, X2))
    B = limbs.mul(limbs.add(Y1, X1), limbs.add(Y2, X2))
    C = limbs.mul(limbs.mul(T1, d2[...]), T2)
    Dv = limbs.mul_small(limbs.mul(Z1, Z2), 2)
    E = limbs.sub(B, A)
    F = limbs.sub(Dv, C)
    G = limbs.add(Dv, C)
    H = limbs.add(B, A)
    ox[...] = limbs.mul(E, F)
    oy[...] = limbs.mul(G, H)
    oz[...] = limbs.mul(F, G)
    ot[...] = limbs.mul(E, H)


@functools.cache
def _add_call(n: int, block: int, interpret: bool):
    spec = pl.BlockSpec((NLIMBS, block), lambda i: (0, i))
    const = pl.BlockSpec((NLIMBS, 1), lambda i: (0, 0))
    out = jax.ShapeDtypeStruct((NLIMBS, n), jnp.int32)
    return pl.pallas_call(
        _add_kernel,
        grid=(n // block,),
        in_specs=[spec] * 8 + [const],
        out_specs=[spec] * 4,
        out_shape=[out] * 4,
        interpret=interpret,
    )


def _double_k_kernel(k: int, x1, y1, z1, ox, oy, oz, ot):
    """k fused a=-1 doublings on one [20, BLOCK] block.

    This is the payoff kernel: the windowed ladders do WINDOW_BITS
    consecutive doublings per step, and fusing them keeps all
    intermediate coordinates in VMEM — the XLA path round-trips 4 HBM
    arrays between each doubling.  T is only produced on the last round
    (doubling consumes X/Y/Z)."""
    X, Y, Z = x1[...], y1[...], z1[...]
    E = H = None
    for _ in range(k):
        A = limbs.square(X)
        B = limbs.square(Y)
        C = limbs.mul_small(limbs.square(Z), 2)
        H = limbs.add(A, B)
        E = limbs.sub(H, limbs.square(limbs.add(X, Y)))
        G = limbs.sub(A, B)
        F = limbs.add(C, G)
        X, Y, Z = limbs.mul(E, F), limbs.mul(G, H), limbs.mul(F, G)
    ox[...] = X
    oy[...] = Y
    oz[...] = Z
    ot[...] = limbs.mul(E, H)


@functools.cache
def _double_k_call(k: int, n: int, block: int, interpret: bool):
    spec = pl.BlockSpec((NLIMBS, block), lambda i: (0, i))
    out = jax.ShapeDtypeStruct((NLIMBS, n), jnp.int32)
    return pl.pallas_call(
        functools.partial(_double_k_kernel, k),
        grid=(n // block,),
        in_specs=[spec] * 3,
        out_specs=[spec] * 4,
        out_shape=[out] * 4,
        interpret=interpret,
    )


def supported(p: Point) -> bool:
    """Pallas path handles 2-D [20, n] coords with block-divisible n."""
    c = p[0]
    n = c.shape[-1]
    block = min(BLOCK, n)
    return c.ndim == 2 and c.shape[0] == NLIMBS and n % block == 0 and n >= 128


def point_add(p: Point, q: Point) -> Point:
    n = p[0].shape[-1]
    block = min(BLOCK, n)
    fn = _add_call(n, block, _interpret())
    return tuple(fn(*p, *q, limbs.D2))


def point_double(p: Point) -> Point:
    return point_double_k(p, 1)


def point_double_k(p: Point, k: int) -> Point:
    """k fused doublings in one kernel launch (k >= 1, static)."""
    assert k >= 1, "point_double_k needs k >= 1 (callers guard k == 0)"
    n = p[0].shape[-1]
    block = min(BLOCK, n)
    fn = _double_k_call(k, n, block, _interpret())
    return tuple(fn(p[0], p[1], p[2]))
