"""Batched GF(2^255 - 19) arithmetic over int32 limb vectors (JAX).

TPU-first design (not a port): the TPU vector unit has no 64-bit integer
lanes, so field elements are represented as ``[20, ...batch]`` int32 arrays
in radix 2^13 ("13x20"): value = sum(limb[i] * 2^(13 i)).  With
|limb| <= 2^13, a schoolbook product limb is a sum of at most 20 terms each
< 2^26, i.e. < 20 * 2^26 < 2^31 — the entire multiply fits int32 lanes with
no widening.  Intermediates may carry *signed* limbs (subtraction is
representation-level negative); the carry chain uses arithmetic shifts, and
wrap-around of the top carry uses 2^260 ≡ 608 (mod p) since 608 = 19 * 2^5.

Layout: the limb axis is the LEADING axis and the batch axes trail.  On TPU
the minor-most axis maps to the 128-wide vector lanes, so a ``[20, n]``
array puts the batch dimension on the lanes (100% occupancy for n >= 128)
instead of wasting 84% of each lane group on a 20-entry limb axis — this
single layout choice is worth ~5x arithmetic throughput over the
batch-major ``[n, 20]`` alternative.

Multiplication uses a pad-flatten-reshape alignment trick to sum the
schoolbook anti-diagonals in O(1) XLA ops (one outer product, one pad, one
reshape, one slice, one reduce) instead of 20 shifted adds — this keeps both
the op count per lane and the XLA graph size (compile time) small.

Every public op returns "carried" form: limbs in a loose symmetric bound
(|limb| <= ~9500), value congruent mod p.  ``canonical`` reduces to the
unique representative < p for encoding and equality.

Reference parity: the field layer of curve25519-dalek under
``src/primitives/ristretto.rs`` (SURVEY.md §2.2) — re-designed for batched
TPU execution; bit-exact against :mod:`cpzk_tpu.core.field` by the
differential tests in ``tests/test_ops_limbs.py``.
"""

from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core import field as host_field

NLIMBS = 20
LIMB_BITS = 13
LIMB_MASK = (1 << LIMB_BITS) - 1
NBITS = NLIMBS * LIMB_BITS  # 260
# 2^260 mod p = 19 * 2^5
TOP_FOLD = 19 << (NBITS - 255)

P = host_field.P


# ---------------------------------------------------------------------------
# host-side conversions (numpy; used for test oracles and data marshalling)
# ---------------------------------------------------------------------------

def int_to_limbs(v: int) -> np.ndarray:
    """One integer -> [NLIMBS] int32 (value must be in [0, 2^260))."""
    out = np.empty(NLIMBS, dtype=np.int32)
    for i in range(NLIMBS):
        out[i] = v & LIMB_MASK
        v >>= LIMB_BITS
    if v:
        raise ValueError("value too large for 20x13 limbs")
    return out

def ints_to_limbs(values: list[int]) -> np.ndarray:
    """Batch conversion -> [NLIMBS, n] int32 (limb-major device layout)."""
    blob = b"".join((v % P).to_bytes(33, "little") for v in values)
    raw = np.frombuffer(blob, dtype=np.uint8).reshape(len(values), 33)
    bits = np.unpackbits(raw, axis=1, bitorder="little")[:, :NBITS]
    weights = (1 << np.arange(LIMB_BITS, dtype=np.int32))
    rows = bits.reshape(len(values), NLIMBS, LIMB_BITS).astype(np.int32) @ weights
    return np.ascontiguousarray(rows.T)

def bytes_to_limbs(blob: bytes | np.ndarray) -> np.ndarray:
    """[n, 32] little-endian byte rows -> [NLIMBS, n] int32 limbs.

    Interprets all 256 bits; values >= 2^255 stay un-reduced (carried form
    handles them).  Vectorized — no per-row Python ints.
    """
    raw = np.asarray(blob, dtype=np.uint8).reshape(-1, 32)
    bits = np.unpackbits(raw, axis=1, bitorder="little")
    bits = np.pad(bits, [(0, 0), (0, NBITS - 256)])
    weights = (1 << np.arange(LIMB_BITS, dtype=np.int32))
    rows = bits.reshape(len(raw), NLIMBS, LIMB_BITS).astype(np.int32) @ weights
    return np.ascontiguousarray(rows.T)

def limbs_to_int(limbs) -> int:
    """One [NLIMBS] limb vector -> integer (host, for tests)."""
    arr = np.asarray(limbs, dtype=object).reshape(-1)
    return int(sum(int(arr[i]) << (LIMB_BITS * i) for i in range(NLIMBS)))

def limbs_to_ints(limbs) -> list[int]:
    """[NLIMBS, n] limb array -> list of n integers (host, for tests)."""
    arr = np.asarray(limbs).reshape(NLIMBS, -1)
    return [limbs_to_int(arr[:, j]) for j in range(arr.shape[1])]


def constant(v: int) -> jnp.ndarray:
    """Module-load-time field constant as a [NLIMBS, 1] device array."""
    return jnp.asarray(int_to_limbs(v % P))[:, None]


ZERO = None  # initialized below (after function defs, constants section)


def _align(a: jnp.ndarray, b: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Insert batch axes after the limb axis so [20, 1] constants broadcast
    against arbitrarily-batched [20, ...] operands."""
    if a.ndim < b.ndim:
        a = a.reshape(a.shape[:1] + (1,) * (b.ndim - a.ndim) + a.shape[1:])
    elif b.ndim < a.ndim:
        b = b.reshape(b.shape[:1] + (1,) * (a.ndim - b.ndim) + b.shape[1:])
    return a, b


# ---------------------------------------------------------------------------
# carry / reduction
# ---------------------------------------------------------------------------

def _chain(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Sequential signed carry chain along the limb axis (axis 0).

    Returns (limbs in [0, 2^13), top carry). Arithmetic (floor) shifts make
    this correct for negative limbs: the remainder x - (x>>13 << 13) is
    always in [0, 2^13).
    """
    n = x.shape[0]
    outs = []
    c = jnp.zeros_like(x[0])
    for i in range(n):
        t = x[i] + c
        c = t >> LIMB_BITS
        outs.append(t & LIMB_MASK)
    return jnp.stack(outs, axis=0), c


def _wrap_round(x: jnp.ndarray) -> jnp.ndarray:
    """One carry-save round on a 20-limb vector with modular wrap.

    Splits every limb into (low 13 bits, carry) in parallel and re-adds the
    carries one position up; the carry leaving limb 19 (weight 2^260) wraps
    to limb 0 scaled by 608 = 19 * 2^5.  Five whole-vector ops — no
    sequential chain, which is what keeps the XLA graphs (and compile time)
    small.  Works for signed limbs via arithmetic shifts.
    """
    lo = x & LIMB_MASK
    hi = x >> LIMB_BITS
    shifted = jnp.concatenate([hi[-1:] * TOP_FOLD, hi[:-1]], axis=0)
    return lo + shifted


def _round_widen(x: jnp.ndarray) -> jnp.ndarray:
    """One carry-save round without wrap; output is one limb wider."""
    lo = x & LIMB_MASK
    hi = x >> LIMB_BITS
    pad_cfg = [(0, 0)] * (x.ndim - 1)
    return jnp.pad(lo, [(0, 1)] + pad_cfg) + jnp.pad(hi, [(1, 0)] + pad_cfg)


def carry20(x: jnp.ndarray) -> jnp.ndarray:
    """Normalize a signed [20, ...] vector to |limb| <= ~9500 ("loose"
    carried form; BOUND).  Valid for inputs with |limb| < 2^22.5 — every
    caller in this module stays far inside that."""
    for _ in range(4):
        x = _wrap_round(x)
    return x


def carry_product(x: jnp.ndarray) -> jnp.ndarray:
    """Reduce a [39, ...] schoolbook product (|limb| < 2^30.8) to loose
    carried [20, ...] form.

    Three widening rounds bring product limbs to ~2^13; the 42-limb result
    is folded mod p in two steps (608 = 2^260 mod p per 20-limb block, with
    the top 2-limb block folded into the middle block first), then four wrap
    rounds restore the loose bound.  All bounds are validated by the
    adversarial max-limb tests in tests/test_ops_limbs.py.
    """
    pad_cfg = [(0, 0)] * (x.ndim - 1)
    x = jnp.pad(x, [(0, 3)] + pad_cfg)  # 42 limbs of headroom
    for _ in range(3):
        x = _round_widen(x)[:42]  # widened carries beyond 42 are zero
    c0 = x[:NLIMBS]
    c1 = x[NLIMBS : 2 * NLIMBS]
    c2 = jnp.pad(x[2 * NLIMBS :], [(0, NLIMBS - 2)] + pad_cfg)
    t = c1 + c2 * TOP_FOLD
    t = _wrap_round(_wrap_round(t))  # |t limb| <= 2^13 + 2^9.2
    return carry20(c0 + t * TOP_FOLD)


def _bump(x: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """x with v added at limb 0 (concat-based, no scatter HLO)."""
    return jnp.concatenate([x[:1] + v[None], x[1:]], axis=0)


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Unique representative < p, digits in [0, 2^13) (encode/compare).

    The only sequential-carry path left; used by eq / is_negative / byte
    encoding, not by the bulk arithmetic. Two fold rounds make the value
    non-negative for any loose input (including representation-negative
    subtraction results)."""
    x = carry20(x)
    x, c = _chain(x)
    x = _bump(x, c * TOP_FOLD)
    x, c = _chain(x)
    x = _bump(x, c * TOP_FOLD)
    x, _ = _chain(x)
    # fold bits 255..259 (top 5 bits of limb 19): 2^255 ≡ 19
    hi = x[NLIMBS - 1] >> (255 - LIMB_BITS * (NLIMBS - 1))  # >> 8
    x = jnp.concatenate(
        [x[:1] + (hi * 19)[None], x[1 : NLIMBS - 1], (x[NLIMBS - 1] & 0xFF)[None]],
        axis=0,
    )
    x, _ = _chain(x)  # value now < 2^255 + 608
    for _ in range(2):
        x = _cond_sub_p(x)
    return x


_P_LIMBS = None  # set in constants section


def _cond_sub_p(x: jnp.ndarray) -> jnp.ndarray:
    p = _P_LIMBS.reshape((NLIMBS,) + (1,) * (x.ndim - 1))
    y, borrow = _chain(x - p)
    return jnp.where(borrow < 0, x, y)


# ---------------------------------------------------------------------------
# arithmetic
# ---------------------------------------------------------------------------

def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    # raw sum <= 2*BOUND; one wrap round restores the loose bound
    a, b = _align(a, b)
    return _wrap_round(a + b)

def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    a, b = _align(a, b)
    return _wrap_round(a - b)

def neg(a: jnp.ndarray) -> jnp.ndarray:
    # |-limb| <= BOUND already: mul-safe without a round
    return -a


#: Multiplication strategy: "schoolbook" (default, VPU outer product +
#: fused anti-diagonal reduce) or "matmulfold" (the fold expressed as a
#: shared-matrix dot_general — the MXU-mapping experiment, see
#: ``_mul_matmulfold``).  Both are bit-exact (differential tests in
#: tests/test_ops_limbs.py).  CALIBRATED on TPU v5 lite (round-5 .hw/
#: sweep): matmulfold +13% at n=4096 (534 vs 472 Mmul/s) but -1.5% at
#: n=65536 (23.30 vs 23.66 GMul/s) — the MXU edge vanishes once the
#: vector lanes fill, so schoolbook stays the default and the flag
#: remains for A/B on other silicon.  A one-level Karatsuba variant was built and
#: REMOVED: with the loose carried-form bound (|limb| <= ~9500) the
#: subtractive middle product's anti-diagonal sums reach
#: 10*(2*9500)^2 = 3.61e9 > int32, and the carry passes needed to
#: restore headroom cost more vector ops than the 25% multiply saving
#: buys (exact bound walk in PROFILE.md §2).
_MUL_VARIANTS = ("schoolbook", "matmulfold")
MUL_VARIANT = os.environ.get("CPZK_MUL", "schoolbook")
if MUL_VARIANT not in _MUL_VARIANTS:
    raise ValueError(
        f"CPZK_MUL={MUL_VARIANT!r} is not one of {_MUL_VARIANTS} — refusing "
        "to silently benchmark the default under a mislabeled name"
    )


def _raw_schoolbook(a: jnp.ndarray, b: jnp.ndarray, n: int) -> jnp.ndarray:
    """[n, ...] x [n, ...] -> un-carried [2n-1, ...] anti-diagonal sums.

    The pad-flatten trick: pad the outer product's j axis from n to 2n,
    flatten (i, j) -> 2n i + j, reslice as rows of 2n-1 — then
    flat[(2n-1) i + k] lands at outer[i, k - i], so a single sum over i
    yields the anti-diagonals.  One multiply + one pad + one reduce
    instead of n shifted adds: ~6 XLA ops per product, which keeps
    compile time flat no matter how many muls a kernel inlines.
    """
    batch = a.shape[1:]
    outer = a[:, None] * b[None, :]  # [n, n, ...]
    pad_cfg = [(0, 0)] * len(batch)
    outer = jnp.pad(outer, [(0, 0), (0, n)] + pad_cfg)  # [n, 2n, ...]
    flat = outer.reshape((n * 2 * n,) + batch)
    flat = flat[: n * (2 * n - 1)]
    return flat.reshape((n, 2 * n - 1) + batch).sum(axis=0)  # [2n-1, ...]


_FOLD_MATRIX = None  # [39, 400] 0/1 anti-diagonal fold, built on first use


def _mul_matmulfold(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Anti-diagonal fold as a shared-matrix contraction (MXU experiment).

    The outer products stay elementwise (no shared contraction exists for
    a *batched* bilinear op — the MXU fundamentally contracts a shared
    dimension), but the fold prod[k] = sum_{i+j=k} outer[i,j] is a fixed
    linear map F [39, 400], so ``F @ outer_flat`` CAN ride the MXU.  The
    trade: outer_flat [400, n] must materialize through HBM (1.6 KB per
    element per mul), so this path is expected to lose to the fused VPU
    reduce on bandwidth — measured, not assumed (benches/bench_kernels.py,
    PROFILE.md).
    """
    global _FOLD_MATRIX
    if _FOLD_MATRIX is None:
        # kept as numpy: it becomes an XLA constant at trace time, and a
        # device array built inside a jit trace would leak a tracer
        f = np.zeros((2 * NLIMBS - 1, NLIMBS * NLIMBS), dtype=np.int32)
        for i in range(NLIMBS):
            for j in range(NLIMBS):
                f[i + j, i * NLIMBS + j] = 1
        _FOLD_MATRIX = f
    batch = a.shape[1:]
    outer = (a[:, None] * b[None, :]).reshape((NLIMBS * NLIMBS,) + batch)
    flat = outer.reshape(NLIMBS * NLIMBS, -1)  # [400, prod(batch)]
    prod = jax.lax.dot_general(
        _FOLD_MATRIX, flat, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return prod.reshape((2 * NLIMBS - 1,) + batch)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Batched field multiply: schoolbook 20x20 -> 39-limb product (or a
    CPZK_MUL-selected variant), then fold+carry."""
    a, b = _align(a, b)
    batch = jnp.broadcast_shapes(a.shape[1:], b.shape[1:])
    a = jnp.broadcast_to(a, a.shape[:1] + batch)
    b = jnp.broadcast_to(b, b.shape[:1] + batch)
    if MUL_VARIANT == "matmulfold":
        prod = _mul_matmulfold(a, b)
    else:
        prod = _raw_schoolbook(a, b, NLIMBS)
    return carry_product(prod)


def square(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small public integer: |k| * BOUND must stay < 2^22.5
    (carry20's input range), i.e. |k| <= ~400."""
    assert abs(k) <= 400, "mul_small bound"
    return carry20(a * jnp.int32(k))


def pow2k(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """a^(2^k) by k squarings (k is a static Python int)."""
    def body(_, x):
        return square(x)
    if k <= 4:
        for _ in range(k):
            a = square(a)
        return a
    return lax.fori_loop(0, k, body, a)


def _pow_p58(a: jnp.ndarray) -> jnp.ndarray:
    """a^((p-5)/8), dalek-style addition chain.

    (p-5)/8 = 2^252 - 3. Chain from curve25519 literature.
    """
    t0 = square(a)                     # a^2
    t1 = square(square(t0))            # a^8
    t2 = mul(a, t1)                    # a^9
    t3 = mul(t0, t2)                   # a^11
    t4 = square(t3)                    # a^22
    t5 = mul(t2, t4)                   # a^31 = a^(2^5 - 1)
    t6 = mul(pow2k(t5, 5), t5)         # a^(2^10 - 1)
    t7 = mul(pow2k(t6, 10), t6)        # a^(2^20 - 1)
    t8 = mul(pow2k(t7, 20), t7)        # a^(2^40 - 1)
    t9 = mul(pow2k(t8, 10), t6)        # a^(2^50 - 1)
    t10 = mul(pow2k(t9, 50), t9)       # a^(2^100 - 1)
    t11 = mul(pow2k(t10, 100), t10)    # a^(2^200 - 1)
    t12 = mul(pow2k(t11, 50), t9)      # a^(2^250 - 1)
    return mul(pow2k(t12, 2), a)       # a^(2^252 - 3)


def inv(a: jnp.ndarray) -> jnp.ndarray:
    """a^(p-2) (Fermat); p-2 = 8*(2^252 - 3) + 2^2 + 1 -> reuse the chain."""
    t = _pow_p58(a)            # a^(2^252 - 3)
    t = pow2k(t, 3)            # a^(2^255 - 24)
    return mul(t, mul(square(a), a))  # * a^3 = a^(2^255 - 21) = a^(p-2)


def is_negative(a: jnp.ndarray) -> jnp.ndarray:
    """RFC 9496 sign: parity of the canonical representative. [...] bool."""
    return (canonical(a)[0] & 1).astype(jnp.bool_)


def fabs(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(is_negative(a), neg(a), a)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Field equality -> [...] bool."""
    a, b = _align(a, b)
    return jnp.all(canonical(a) == canonical(b), axis=0)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == 0, axis=0)


def select(mask: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """where(mask, a, b) with mask shaped [...batch] (no limb axis)."""
    return jnp.where(mask, a, b)


def sqrt_ratio_m1(u: jnp.ndarray, v: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Batched SQRT_RATIO_M1 (RFC 9496 §3.1) — twin of
    :func:`cpzk_tpu.core.field.sqrt_ratio_m1`.

    Returns (was_square [...] bool, root [20, ...]).
    """
    v3 = mul(square(v), v)
    v7 = mul(square(v3), v)
    r = mul(mul(u, v3), _pow_p58(mul(u, v7)))
    check = mul(v, square(r))

    neg_u = neg(u)
    correct_sign = eq(check, u)
    flipped_sign = eq(check, neg_u)
    flipped_sign_i = eq(check, mul(neg_u, SQRT_M1))

    r = select(flipped_sign | flipped_sign_i, mul(r, SQRT_M1), r)
    r = fabs(r)
    return correct_sign | flipped_sign, r


# ---------------------------------------------------------------------------
# byte/bit conversions (device-side; byte axis leading, like the limb axis)
# ---------------------------------------------------------------------------

def from_bytes_le(b: jnp.ndarray) -> jnp.ndarray:
    """[32, ...] uint8/int32 little-endian bytes -> carried limbs [20, ...].

    Interprets all 256 bits (caller masks bit 255 if needed); result is
    carried but NOT canonicalized.
    """
    b = b.astype(jnp.int32)
    batch = b.shape[1:]
    shifts = jnp.arange(8, dtype=jnp.int32).reshape((1, 8) + (1,) * len(batch))
    bits = (b[:, None] >> shifts) & 1  # [32, 8, ...]
    bits = bits.reshape((256,) + batch)
    bits = jnp.concatenate(
        [bits, jnp.zeros((NBITS - 256,) + batch, dtype=jnp.int32)], axis=0
    )
    w = jnp.asarray(1 << np.arange(LIMB_BITS, dtype=np.int32)).reshape(
        (1, LIMB_BITS) + (1,) * len(batch)
    )
    return jnp.sum(bits.reshape((NLIMBS, LIMB_BITS) + batch) * w, axis=1)


def to_bytes_le(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical [32, ...] int32 byte values (0..255) of a field element."""
    x = canonical(a)
    batch = x.shape[1:]
    shifts = jnp.arange(LIMB_BITS, dtype=jnp.int32).reshape(
        (1, LIMB_BITS) + (1,) * len(batch)
    )
    bits = (x[:, None] >> shifts) & 1  # [20, 13, ...]
    bits = bits.reshape((NBITS,) + batch)[:256]
    w = jnp.asarray(1 << np.arange(8, dtype=np.int32)).reshape(
        (1, 8) + (1,) * len(batch)
    )
    return jnp.sum(bits.reshape((32, 8) + batch) * w, axis=1)


# ---------------------------------------------------------------------------
# constants (derived from the host field module — single source of truth)
# ---------------------------------------------------------------------------

_P_LIMBS = jnp.asarray(int_to_limbs(P))[:, None]

ZERO = constant(0)
ONE = constant(1)
D = constant(host_field.D)
D2 = constant(2 * host_field.D % P)
SQRT_M1 = constant(host_field.SQRT_M1)
ONE_MINUS_D_SQ = constant(host_field.ONE_MINUS_D_SQ)
D_MINUS_ONE_SQ = constant(host_field.D_MINUS_ONE_SQ)
SQRT_AD_MINUS_ONE = constant(host_field.SQRT_AD_MINUS_ONE)
INVSQRT_A_MINUS_D = constant(host_field.INVSQRT_A_MINUS_D)
