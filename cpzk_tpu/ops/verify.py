"""Batched Chaum-Pedersen verification kernels (JAX).

Two device programs, both free of data-dependent control flow:

- ``verify_each_kernel`` — per-proof ground truth. For each row checks
  ``s*G - c*y1 - r1 == O`` and ``s*H - c*y2 - r2 == O`` (the additive form
  of the reference's ``g^s == r1 * y1^c`` check, ``verifier/mod.rs:144-171``)
  using a *shared-doubling* double-scalar chain per equation: one 255-double
  ladder with two 4-bit window tables instead of two independent scalar
  multiplications.

- ``combined_kernel`` — the corrected randomized-linear-combination batch
  check (SURVEY.md §3.2; the reference's own equation at ``batch.rs:292-308``
  drops the alpha coefficient on the ``y^c`` term and always falls back).
  Per row computes ``a*r1 + (a*c)*y1 + (b*a)*r2 + (b*a*c)*y2`` with one
  shared-doubling chain and four tables, tree-sums all rows plus one
  host-built correction row carrying ``(-sum a*s)*G + (-b*sum a*s)*H``, and
  accepts iff the total is the identity coset.

All arrays are limb-major ([20, n] coords, [64, n] windows) so the batch
axis rides the TPU vector lanes.  Scalar decomposition (mod l) happens on
the host; the device sees only public 4-bit windows — verification inputs
are public, so vartime selects are fine (docs/security.md).

See :mod:`cpzk_tpu.ops.msm` for the windowed-Pippenger path that replaces
``combined_kernel``'s per-row table chains at large batch sizes.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from . import curve
from .curve import Point, WINDOW_BITS, build_table, table_gather


def _msm_rows(tables: list[tuple[jnp.ndarray, ...]], windows: list[jnp.ndarray]) -> Point:
    """Shared-doubling multi-term scalar-mul.

    ``tables[k]`` is the window table of point set k (coords [16, 20, ...] or
    broadcastable), ``windows[k]`` its [64, ...] window array (MSB first).
    Returns sum_k scalar_k * point_k per lane: one doubling ladder total.
    """
    shape = windows[0].shape[1:]
    wT = jnp.stack(windows, axis=1)  # [64, K, ...]

    def step(acc: Point, w):
        acc = curve.double_k(acc, WINDOW_BITS)
        for k, table in enumerate(tables):
            acc = curve.add(acc, table_gather(table, w[k]))
        return acc, None

    acc, _ = lax.scan(step, curve.identity(shape), wT)
    return acc


def verify_each_kernel(
    g: Point,
    h: Point,
    y1: Point,
    y2: Point,
    r1: Point,
    r2: Point,
    ws: jnp.ndarray,
    wc: jnp.ndarray,
) -> jnp.ndarray:
    """Per-proof checks -> [n] bool.

    ``g``/``h`` are [20, 1] (shared, broadcast) points; ``y*``/``r*`` are
    [20, n]; ``ws``/``wc`` are [64, n] windows of s and c.
    """
    tg = build_table(g)     # [16, 20, 1] coords, broadcast-selected per lane
    th = build_table(h)
    tny1 = build_table(curve.negate(y1))
    tny2 = build_table(curve.negate(y2))

    d1 = _msm_rows([tg, tny1], [ws, wc])
    d2 = _msm_rows([th, tny2], [ws, wc])
    d1 = curve.add(d1, curve.negate(r1))
    d2 = curve.add(d2, curve.negate(r2))
    return curve.is_identity(d1) & curve.is_identity(d2)


def combined_partial_kernel(
    r1: Point,
    y1: Point,
    r2: Point,
    y2: Point,
    w_a: jnp.ndarray,
    w_ac: jnp.ndarray,
    w_ba: jnp.ndarray,
    w_bac: jnp.ndarray,
) -> Point:
    """Partial sum of the combined check over one lane chunk -> [20, 1].

    Identity-padded lanes (zero windows, identity points) contribute the
    identity, so chunk partials add up to the full batch total.  Split out
    from :func:`combined_kernel` so the backend can tile large batches
    into lane chunks that stay inside the device's proven program size
    (PROFILE.md §7a: monolithic >~33k-lane programs miscompile on TPU
    v5 lite).
    """
    rows = _msm_rows(
        [build_table(r1), build_table(y1), build_table(r2), build_table(y2)],
        [w_a, w_ac, w_ba, w_bac],
    )
    total = curve.tree_sum(rows, axis=-1)
    return tuple(c[..., None] for c in total)


def combined_kernel(
    r1: Point,
    y1: Point,
    r2: Point,
    y2: Point,
    w_a: jnp.ndarray,
    w_ac: jnp.ndarray,
    w_ba: jnp.ndarray,
    w_bac: jnp.ndarray,
) -> jnp.ndarray:
    """Corrected-RLC combined check -> scalar bool.

    Callers append the correction row (points G, H, O, O with windows of
    ``-sum(a*s)``, ``-b*sum(a*s)``, 0, 0) before invoking, so acceptance is
    ``total == O``.
    """
    total = combined_partial_kernel(r1, y1, r2, y2, w_a, w_ac, w_ba, w_bac)
    return curve.is_identity(tuple(c[..., 0] for c in total))
