"""Windowed-Pippenger multi-scalar multiplication on TPU (JAX).

The flagship kernel (SURVEY.md §7 hard part #1): computes
``sum_i scalar_i * P_i`` for a large batch of (point, scalar) pairs —
the corrected-RLC combined batch check is one such MSM of size 4n+2
(reference accumulation loop: ``src/verifier/batch.rs:271-312``, which
performs 8 per-row scalar-muls instead of any real MSM).

TPU-shaped bucket accumulation
------------------------------
Pippenger's bucket scatter is data-dependent random access, which the TPU's
vector units cannot do.  The standard re-formulation (cuZK and friends) is
sort + segment-reduce; here the segment-reduce is expressed as a *prefix
scan with boundary differences*, which maps onto three primitives XLA
compiles well:

1. per window, sort lanes by bucket index (``argsort`` on int32 digits +
   one gather of the point coords);
2. one inclusive prefix scan of point adds along the lane axis
   (``lax.associative_scan`` — ~2m batched adds, log-depth);
3. bucket sums as differences ``prefix[end_j] - prefix[end_{j-1}]`` at the
   bucket boundary lanes (``searchsorted`` + gather; empty buckets come out
   as the identity automatically), then a reversed suffix scan over the
   bucket axis turns ``sum_j j * bucket_j`` into one more parallel scan.

Signed c-bit digits halve the bucket count (digits in [-2^(c-1), 2^(c-1)];
negation of a point is free).  The window loop is a ``lax.scan`` so the XLA
program stays small, and the per-window cost is ~2m + 3*2^(c-1) batched
point adds: ~K*(2 + 3B/m) adds *per MSM term* versus ~570 for the per-row
windowed chains in :mod:`cpzk_tpu.ops.verify` — plus the window size c
scales with m, so bigger batches amortize better (the long-context analog:
batch is our sequence axis, SURVEY.md §5).

Everything is limb-major ([20, m] coords, [K, m] digits) so the batch rides
the vector lanes.  All inputs are public verification data — vartime
sort/gather is fine (docs/security.md).
"""

from __future__ import annotations

import os

import numpy as np

import jax.numpy as jnp
from jax import lax

from . import curve
from .curve import Point


def pick_window(m: int) -> int:
    """Static window size minimizing ~K(c) * (2m + 3 * 2^(c-1)).

    ``CPZK_MSM_WINDOW`` (4..16) overrides the cost model — the knob the
    on-hardware sweep uses to calibrate it (PROFILE.md §4)."""
    override = os.environ.get("CPZK_MSM_WINDOW")
    if override:
        c = int(override)
        if not 4 <= c <= 16:
            raise ValueError(f"CPZK_MSM_WINDOW={c} outside 4..16")
        return c
    best_c, best_cost = 4, float("inf")
    for c in range(4, 17):
        cost = num_windows(c) * (2 * m + 3 * (1 << (c - 1)))
        if cost < best_cost:
            best_c, best_cost = c, cost
    return best_c


def num_windows(c: int) -> int:
    """Window count for signed-digit recoding (one extra for the carry)."""
    return -(-253 // c) + 1


def scalars_to_signed_digits(values: list[int], c: int) -> np.ndarray:
    """Host: scalars (mod l) -> [K, m] int32 signed c-bit digits, LSB window
    first; digit k weight is 2^(c k), digits in [-2^(c-1), 2^(c-1)].

    Vectorized over the batch (no per-row Python loops beyond the K-step
    carry recode).
    """
    k = num_windows(c)
    blob = b"".join(int(v).to_bytes(32, "little") for v in values)
    raw = np.frombuffer(blob, dtype=np.uint8).reshape(len(values), 32)
    bits = np.unpackbits(raw, axis=1, bitorder="little")  # [m, 256]
    bits = np.pad(bits, [(0, 0), (0, k * c - 256)]) if k * c > 256 else bits[:, : k * c]
    weights = (1 << np.arange(c, dtype=np.int64))
    u = bits.reshape(len(raw), k, c).astype(np.int64) @ weights  # [m, K] unsigned
    digits = np.empty((k, len(raw)), dtype=np.int32)
    carry = np.zeros(len(raw), dtype=np.int64)
    half = 1 << (c - 1)
    for w in range(k):
        t = u[:, w] + carry
        wrap = t >= half
        digits[w] = np.where(wrap, t - (1 << c), t).astype(np.int32)
        carry = wrap.astype(np.int64)
    if carry.any():
        raise ValueError("signed-digit recode overflow (scalar >= 2^(cK-1))")
    return digits


def _window_sum(points: Point, d: jnp.ndarray, n_buckets: int) -> Point:
    """One Pippenger window: sum_i d_i * P_i with |d_i| < n_buckets."""
    a = jnp.abs(d)
    perm = jnp.argsort(a)
    a_sorted = jnp.take(a, perm)
    d_sorted = jnp.take(d, perm)
    pts = tuple(jnp.take(cd, perm, axis=1) for cd in points)

    # sign and zero-digit handling on the sorted lanes
    pts = curve.cond_negate(d_sorted < 0, pts)
    pts = curve.select(a_sorted == 0, curve.identity(a_sorted.shape), pts)

    # inclusive prefix scan of point adds along the lane axis
    prefix = lax.associative_scan(curve.add, pts, axis=1)
    ident1 = curve.identity((1,))
    prefix_ext = tuple(
        jnp.concatenate([i1, c], axis=1) for i1, c in zip(ident1, prefix)
    )  # [20, m+1]

    # boundary lanes: idx[j] = count(a <= j); bucket_j = P[idx[j]] - P[idx[j-1]]
    idx = jnp.searchsorted(a_sorted, jnp.arange(n_buckets, dtype=a.dtype), side="right")
    at = tuple(jnp.take(c, idx, axis=1) for c in prefix_ext)  # [20, B]
    ends = tuple(c[:, 1:] for c in at)
    starts = tuple(c[:, :-1] for c in at)
    buckets = curve.add(ends, curve.negate(starts))  # [20, B-1]: buckets 1..B-1

    # sum_j j * bucket_j  ==  sum over suffix sums of the bucket axis
    suffix = lax.associative_scan(curve.add, buckets, axis=1, reverse=True)
    w = curve.tree_sum(suffix, axis=-1)
    return tuple(c[:, None] for c in w)  # [20, 1]: scan-carry compatible


def msm_kernel(points: Point, digits: jnp.ndarray, c: int) -> Point:
    """sum_i scalar_i * P_i -> single point ([20, 1] coords).

    ``points``: [20, m] SoA; ``digits``: [K, m] signed c-bit digits (LSB
    window first, from :func:`scalars_to_signed_digits`); ``c``: static.
    """
    n_buckets = (1 << (c - 1)) + 1  # bucket values 0..2^(c-1)

    def step(acc: Point, d):
        acc = curve.double_k(acc, c)
        w = _window_sum(points, d, n_buckets)
        return curve.add(acc, w), None

    # MSB window first for the Horner accumulation
    acc, _ = lax.scan(step, curve.identity((1,)), digits[::-1])
    return acc


def msm_is_identity_kernel(points: Point, digits: jnp.ndarray, c: int) -> jnp.ndarray:
    """Combined-check entry: MSM == identity -> scalar bool."""
    return curve.is_identity(msm_kernel(points, digits, c))
