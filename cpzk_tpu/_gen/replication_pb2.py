# -*- coding: utf-8 -*-
# Generated protocol buffer code for replication.proto (built from the
# FileDescriptorProto because protoc is unavailable in this environment;
# see cpzk_tpu/server/proto.py — regenerate with protoc when present).
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database

_sym_db = _symbol_database.Default()


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile(b'\n\x11replication.proto\x12\x0breplication"\xcd\x01\n\x12ShipSegmentRequest\x12\r\n\x05epoch\x18\x01 \x01(\x04\x12\x15\n\rsegment_index\x18\x02 \x01(\x04\x12\x11\n\tfirst_seq\x18\x03 \x01(\x04\x12\x10\n\x08last_seq\x18\x04 \x01(\x04\x12\x0e\n\x06frames\x18\x05 \x01(\x0c\x12\r\n\x05crc32\x18\x06 \x01(\x07\x12\x0e\n\x06sealed\x18\x07 \x01(\x08\x12\x13\n\x0bprimary_seq\x18\x08 \x01(\x04\x12\x14\n\x0csent_unix_ms\x18\t \x01(\x04\x12\x12\n\x04kind\x18\n \x01(\tR\x04kind"\\\n\x13ShipSegmentResponse\x12\x10\n\x08accepted\x18\x01 \x01(\x08\x12\x13\n\x0bapplied_seq\x18\x02 \x01(\x04\x12\r\n\x05epoch\x18\x03 \x01(\x04\x12\x0f\n\x07message\x18\x04 \x01(\t"S\n\x18ReplicationStatusRequest\x12\r\n\x05epoch\x18\x01 \x01(\x04\x12\x13\n\x0brenew_lease\x18\x02 \x01(\x08\x12\x13\n\x0bprimary_seq\x18\x03 \x01(\x04"\x98\x01\n\x19ReplicationStatusResponse\x12\x0c\n\x04role\x18\x01 \x01(\t\x12\r\n\x05epoch\x18\x02 \x01(\x04\x12\x13\n\x0bapplied_seq\x18\x03 \x01(\x04\x12\x13\n\x0blag_records\x18\x04 \x01(\x04\x12\x19\n\x11lease_remaining_s\x18\x05 \x01(\x01\x12\x19\n\x11segments_received\x18\x06 \x01(\x04"R\n\x0fHandoverRequest\x12\r\n\x05phase\x18\x01 \x01(\t\x12\r\n\x05epoch\x18\x02 \x01(\x04\x12\x11\n\tfence_seq\x18\x03 \x01(\x04\x12\x0e\n\x06reason\x18\x04 \x01(\t"\x88\x01\n\x10HandoverResponse\x12\n\n\x02ok\x18\x01 \x01(\x08\x12\x0c\n\x04role\x18\x02 \x01(\t\x12\r\n\x05epoch\x18\x03 \x01(\x04\x12\x13\n\x0bapplied_seq\x18\x04 \x01(\x04\x12\x0f\n\x07message\x18\x05 \x01(\t\x12\x11\n\tfence_seq\x18\x06 \x01(\x04\x12\x12\n\nduration_s\x18\x07 \x01(\x012\x93\x02\n\x12ReplicationService\x12P\n\x0bShipSegment\x12\x1f.replication.ShipSegmentRequest\x1a .replication.ShipSegmentResponse\x12b\n\x11ReplicationStatus\x12%.replication.ReplicationStatusRequest\x1a&.replication.ReplicationStatusResponse\x12G\n\x08Handover\x12\x1c.replication.HandoverRequest\x1a\x1d.replication.HandoverResponseb\x06proto3')

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'replication_pb2', globals())
