# -*- coding: utf-8 -*-
# Generated protocol buffer code for auth.proto (rebuilt from the
# FileDescriptorProto because protoc is unavailable in this environment;
# see cpzk_tpu/server/proto.py -- regenerate with protoc when present).
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database

_sym_db = _symbol_database.Default()


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile(b'\n\nauth.proto\x12\x04auth">\n\x13RegistrationRequest\x12\x0f\n\x07user_id\x18\x01 \x01(\t\x12\n\n\x02y1\x18\x02 \x01(\x0c\x12\n\n\x02y2\x18\x03 \x01(\x0c"8\n\x14RegistrationResponse\x12\x0f\n\x07success\x18\x01 \x01(\x08\x12\x0f\n\x07message\x18\x02 \x01(\t"#\n\x10ChallengeRequest\x12\x0f\n\x07user_id\x18\x01 \x01(\t"=\n\x11ChallengeResponse\x12\x14\n\x0cchallenge_id\x18\x01 \x01(\x0c\x12\x12\n\nexpires_at\x18\x02 \x01(\x03"K\n\x13VerificationRequest\x12\x0f\n\x07user_id\x18\x01 \x01(\t\x12\x14\n\x0cchallenge_id\x18\x02 \x01(\x0c\x12\r\n\x05proof\x18\x03 \x01(\x0c"f\n\x14VerificationResponse\x12\x0f\n\x07success\x18\x01 \x01(\x08\x12\x0f\n\x07message\x18\x02 \x01(\t\x12\x1a\n\rsession_token\x18\x03 \x01(\tH\x00\x88\x01\x01B\x10\n\x0e_session_token"S\n\x18BatchVerificationRequest\x12\x10\n\x08user_ids\x18\x01 \x03(\t\x12\x15\n\rchallenge_ids\x18\x02 \x03(\x0c\x12\x0e\n\x06proofs\x18\x03 \x03(\x0c"F\n\x19BatchVerificationResponse\x12)\n\x07results\x18\x01 \x03(\x0b2\x18.auth.VerificationResult"d\n\x12VerificationResult\x12\x0f\n\x07success\x18\x01 \x01(\x08\x12\x0f\n\x07message\x18\x02 \x01(\t\x12\x1a\n\rsession_token\x18\x03 \x01(\tH\x00\x88\x01\x01B\x10\n\x0e_session_token"R\n\x18BatchRegistrationRequest\x12\x10\n\x08user_ids\x18\x01 \x03(\t\x12\x11\n\ty1_values\x18\x02 \x03(\x0c\x12\x11\n\ty2_values\x18\x03 \x03(\x0c"F\n\x19BatchRegistrationResponse\x12)\n\x07results\x18\x01 \x03(\x0b2\x18.auth.RegistrationResult"6\n\x12RegistrationResult\x12\x0f\n\x07success\x18\x01 \x01(\x08\x12\x0f\n\x07message\x18\x02 \x01(\t"r\n\x13StreamVerifyRequest\x12\x0b\n\x03ids\x18\x01 \x03(\x04\x12\x10\n\x08user_ids\x18\x02 \x03(\t\x12\x15\n\rchallenge_ids\x18\x03 \x03(\x0c\x12\x0e\n\x06proofs\x18\x04 \x03(\x0c\x12\x15\n\rmint_sessions\x18\x05 \x01(\x08"v\n\x14StreamVerifyResponse\x12\x0b\n\x03ids\x18\x01 \x03(\x04\x12\x0f\n\x07success\x18\x02 \x03(\x08\x12\x10\n\x08messages\x18\x03 \x03(\t\x12\x16\n\x0esession_tokens\x18\x04 \x03(\t\x12\x16\n\x0eretry_after_ms\x18\x05 \x01(\r2\xd1\x03\n\x0bAuthService\x12A\n\x08Register\x12\x19.auth.RegistrationRequest\x1a\x1a.auth.RegistrationResponse\x12P\n\rRegisterBatch\x12\x1e.auth.BatchRegistrationRequest\x1a\x1f.auth.BatchRegistrationResponse\x12B\n\x0fCreateChallenge\x12\x16.auth.ChallengeRequest\x1a\x17.auth.ChallengeResponse\x12D\n\x0bVerifyProof\x12\x19.auth.VerificationRequest\x1a\x1a.auth.VerificationResponse\x12S\n\x10VerifyProofBatch\x12\x1e.auth.BatchVerificationRequest\x1a\x1f.auth.BatchVerificationResponse\x12N\n\x11VerifyProofStream\x12\x19.auth.StreamVerifyRequest\x1a\x1a.auth.StreamVerifyResponse(\x010\x01b\x06proto3')

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'auth_pb2', globals())
