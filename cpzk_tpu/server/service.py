"""gRPC AuthService implementation (asyncio).

Behavior parity with the reference service (``src/verifier/service.rs``):
identical validation limits and error strings, opaque "Authentication
failed" for anything secret-adjacent, challenge consumption BEFORE
verification (replay cannot retry a failed proof), per-item results for the
batch RPCs, 32-byte challenge ids and hex session tokens, and the same
metric names. The gRPC plumbing is hand-wired through grpcio's generic
handler API because the protoc gRPC plugin is unavailable (see proto.py).
"""

from __future__ import annotations

import asyncio
import time

import grpc

from dataclasses import dataclass, field

from .. import errors
from ..admission import RETRY_PUSHBACK_KEY, client_key
from ..fleet.partition_map import PARTITION_MAP_VERSION_KEY, PARTITION_OWNER_KEY
from ..audit.log import proof_record
from ..core.ristretto import Ristretto255
from ..core.rng import SecureRng
from ..core.transcript import Transcript
from ..observability import current_context, traced_rpc, traced_stream_rpc
from ..protocol.batch import BatchEntry, BatchVerifier, VerifierBackend
from ..protocol.gadgets import Parameters, Proof, Statement
from ..protocol.verifier import Verifier
from . import batching, metrics
from . import wire as wire_mod
from .config import RateLimiter, RateLimitExceeded
from .dispatch import DispatchLane
from .proto import SERVICE_NAME, load_pb2, method_types, stream_method_types
from .state import ServerState, UserData
from .state import user_id_error as _user_id_error

MAX_ELEMENT_WIRE = 4096
MAX_CHALLENGE_ID = 64
MAX_PROOF_WIRE = 8192
MAX_BATCH = 1000

#: Hard cap on entries per stream chunk message: a client packing more is
#: answered with per-entry failures, never a bigger allocation.
MAX_STREAM_CHUNK = 4096

#: "no verdict recorded" sentinel for a stream entry's result slot.
_UNSET = object()

#: Pushback advertised on RESOURCE_EXHAUSTED paths that have no better
#: estimate (no admission controller / no queue signal): one client
#: backoff's worth, so uninstrumented retry loops still spread out.
DEFAULT_RETRY_AFTER_S = 0.05


class AuthServiceImpl:
    """The five unary RPCs (service.rs:59-617 twin) plus the
    ``VerifyProofStream`` bidi-streaming verification surface."""

    def __init__(
        self,
        state: ServerState,
        rate_limiter: RateLimiter,
        backend: VerifierBackend | None = None,
        batcher=None,
        admission=None,
        replica=None,
        audit_log=None,
        stream_window: int = 8192,
        stream_entry_deadline_ms: float = 0.0,
        fleet=None,
        wire: str = "native",
    ):
        self.state = state
        self.rate_limiter = rate_limiter
        #: transport wire mode: "native" = C++ request parse with
        #: unconditional Python-protobuf fallback, "python" = protobuf
        #: runtime only (see server/wire.py; [server] wire knob)
        self.wire = wire
        self.backend = backend
        self.batcher = batcher  # DynamicBatcher | None (TPU serving path)
        self.admission = admission  # AdmissionController | None
        self.replica = replica  # StandbyReplica | None (replication standby)
        self.audit_log = audit_log  # audit.ProofLogWriter | None (opt-in)
        self.fleet = fleet  # fleet.FleetRouter | None (partition ownership)
        #: max proof entries in flight per VerifyProofStream before the
        #: reader stops pulling (gRPC flow control then pushes back on the
        #: sender) — bounds per-stream memory without killing the stream
        self.stream_window = max(1, int(stream_window))
        #: per-entry verification deadline for stream entries (0 = only
        #: the stream's own gRPC deadline applies); expired entries are
        #: shed by the batcher and answered with per-entry NOT-verdicts
        self.stream_entry_deadline_s = (
            stream_entry_deadline_ms / 1000.0
            if stream_entry_deadline_ms > 0 else None
        )
        self.pb2 = load_pb2()
        self.rng = SecureRng()
        # inline-verify concurrency: 2 lets one RPC's Python overlap
        # another's GIL-released crypto without unbounded to_thread
        # workers each spawning a cpu-wide native pool (crypto-vs-crypto
        # oversubscription under many concurrent batch RPCs)
        self._inline_verify = asyncio.Semaphore(2)
        # in-flight audit-log fsync tasks (handles kept: a dropped task
        # handle both leaks exceptions and trips ASYNC-002)
        self._audit_flushes: set[asyncio.Task] = set()
        # live VerifyProofStream registry behind the ops plane's /statusz
        # per-stream rows and the auth.stream.active gauge
        self._streams: dict[int, dict] = {}
        self._stream_seq = 0
        # write-time ownership fence: the entry-point _check_owner alone
        # cannot fence multi-await handlers (VerifyProof awaits the
        # batcher between its check and create_session) across a live
        # split's map flip, so state re-verifies ownership INSIDE the
        # shard lock on every acked user-keyed mutation and raises
        # errors.WrongPartition — answered below with the same redirect
        # as the entry check (see ServerState.attach_owner_fence)
        if fleet is not None and hasattr(state, "attach_owner_fence"):
            state.attach_owner_fence(self._wrong_partition_counted)

    # --- stream registry (ops plane introspection seam) -------------------

    def _stream_open(self, client: str, trace_id: str) -> dict:
        self._stream_seq += 1
        info = {
            "id": self._stream_seq,
            "client": client,
            "trace_id": trace_id,
            "opened_unix": time.time(),
            "chunks": 0,
            "entries": 0,
            "inflight": 0,
        }
        self._streams[info["id"]] = info
        metrics.gauge("auth.stream.active").set(len(self._streams))
        return info

    def _stream_close(self, info: dict) -> None:
        self._streams.pop(info["id"], None)
        metrics.gauge("auth.stream.active").set(len(self._streams))

    def stream_stats(self) -> dict:
        """Active VerifyProofStream sessions (the ``streams`` block of
        the ops plane's ``/statusz``)."""
        return {
            "active": len(self._streams),
            "streams": [dict(info) for info in self._streams.values()],
        }

    # --- helpers ---

    async def _abort_exhausted(self, context, msg: str, retry_after_s: float):
        """RESOURCE_EXHAUSTED carrying ``cpzk-retry-after-ms`` trailing
        metadata (gRFC A6 server pushback) — EVERY shed path goes through
        here, not only admission rejections, so a bare 'try again
        whenever' rejection no longer exists."""
        ms = max(0, int(round(retry_after_s * 1000.0)))
        md = ((RETRY_PUSHBACK_KEY, str(ms)),)
        try:
            await context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED, msg, trailing_metadata=md
            )
        except TypeError:  # hand-rolled test context without the kwarg
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, msg)

    def _pushback_s(self, default: float = DEFAULT_RETRY_AFTER_S) -> float:
        """Queue-drain-sized pushback when a controller is wired, else
        ``default``."""
        if self.admission is not None:
            return self.admission.retry_after_s()
        return default

    async def _admit(self, context, rpc: str) -> None:
        """Full admission stack for one RPC: the global token bucket
        (backstop), then the per-client keyed bucket and the adaptive
        priority threshold.  Rejections abort RESOURCE_EXHAUSTED with
        retry pushback.  A replication standby that has not been promoted
        refuses every auth RPC outright — its state is a replica of the
        primary's, and writes on it would fork history."""
        if self.replica is not None and self.replica.role != "primary":
            # counted like every other shed path so the /slo burn math and
            # dashboards see standby refusals, not a silent abort
            metrics.counter("admission.shed.standby").inc()
            await context.abort(
                grpc.StatusCode.UNAVAILABLE,
                "standby replica: not promoted (writes go to the primary)",
            )
        try:
            await self.rate_limiter.check_rate_limit()
        except RateLimitExceeded as e:
            metrics.counter("admission.shed.global").inc()
            await self._abort_exhausted(
                context, "Rate limit exceeded",
                getattr(e, "retry_after_s", 0.0) or DEFAULT_RETRY_AFTER_S,
            )
        if self.admission is None:
            return
        rejection = self.admission.admit(rpc, client_key(context))
        if rejection is not None:
            await self._abort_exhausted(
                context, rejection.message, rejection.retry_after_s
            )

    @staticmethod
    async def _validate_user_id(user_id: str, context) -> None:
        msg = _user_id_error(user_id)
        if msg is not None:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, msg)

    def _wrong_partition(self, user_id: str) -> str | None:
        """Redirect message when this partition does not own ``user_id``
        under the loaded map, else ``None``.  The single-partition fast
        path is a constant-time no-op inside ``FleetRouter.owns`` — fleet
        routing must cost the N=1 hot path nothing (perf-gate pinned).

        A coordinated handover fences the WHOLE node, challenge creates
        and consumes included: unlike a live split (where the consume
        stays open so an in-flight login can finish here), the standby
        holds every challenge shipped before the fence watermark, while
        a challenge minted here after it replicates nowhere — serving
        the challenge flow on a fenced/deposed primary strands logins
        for the whole drain window.  Checking BEFORE the consume keeps
        the redirect replay-safe: the login retries at the standby with
        its challenge intact there."""
        fleet = self.fleet
        if fleet is not None and not fleet.owns(user_id):
            owner = fleet.owner(user_id)
            return (
                f"wrong partition: user is owned by partition {owner.index} "
                f"at {owner.address} (map v{fleet.map.version})"
            )
        target = getattr(self.replica, "redirect_address", None)
        if target is not None:
            return (
                "wrong partition: handover in progress; writes go to "
                f"the standby at {target}"
            )
        return None

    def _wrong_partition_counted(self, user_id: str) -> str | None:
        """Per-entry form for the batch/stream paths: the same redirect
        message as :meth:`_check_owner`, counted, but answered as an
        individual failure (one misrouted entry must not abort its batch
        siblings — the client fans batches out per partition)."""
        msg = self._wrong_partition(user_id)
        if msg is not None:
            if self.fleet is not None:  # handover fences fleetless pairs too
                self.fleet.redirects += 1
            metrics.counter("fleet.redirects").inc()
        return msg

    async def _check_owner(self, user_id: str, context) -> None:
        """Partition-ownership enforcement, BEFORE any state access: a
        wrong-partition request aborts ``FAILED_PRECONDITION`` with the
        map version and the owning partition's address in trailing
        metadata (the same trailer discipline as retry pushback), so a
        stale-map client can refresh + re-route in one round trip.
        Running this ahead of every state touch is what makes the
        redirect replay-safe even for ``VerifyProof`` — the challenge is
        still unconsumed when the redirect goes out."""
        msg = self._wrong_partition_counted(user_id)
        if msg is None:
            return
        await self._redirect_abort(user_id, context, msg)

    async def _redirect_abort(self, user_id: str, context, msg: str) -> None:
        """The wrong-partition abort itself (counting is the caller's —
        or the write-time fence's — job): ``FAILED_PRECONDITION`` with
        the map version and the owning partition's address in trailing
        metadata, so a stale-map client can refresh + re-route in one
        round trip.  Shared by the entry check above and the
        ``errors.WrongPartition`` handlers on the mutation paths."""
        fleet = self.fleet
        # during a coordinated handover the write fence redirects at the
        # STANDBY, not at what the (not-yet-flipped) map says this
        # partition's owner is — and it must work with no fleet at all
        # (a plain replicated pair): the shipper carries the target
        target = getattr(self.replica, "redirect_address", None)
        if target:
            md = (
                (PARTITION_MAP_VERSION_KEY,
                 str(fleet.map.version) if fleet is not None else "0"),
                (PARTITION_OWNER_KEY, target),
            )
        else:
            owner = fleet.owner(user_id)
            md = (
                (PARTITION_MAP_VERSION_KEY, str(fleet.map.version)),
                (PARTITION_OWNER_KEY, owner.address),
            )
        try:
            await context.abort(
                grpc.StatusCode.FAILED_PRECONDITION, msg,
                trailing_metadata=md,
            )
        except TypeError:  # hand-rolled test context without the kwarg
            await context.abort(grpc.StatusCode.FAILED_PRECONDITION, msg)

    @staticmethod
    def _note_wire(request) -> None:
        """wire_parse trace span for natively-parsed requests (no-op on
        the protobuf path and outside an instrumented RPC)."""
        rctx = current_context.get()
        wire_mod.note_wire_parse(
            request, rctx.trace_id if rctx is not None else None
        )

    @staticmethod
    def _request_context(context):
        """The decorator-minted :class:`RequestContext` of this RPC (trace
        id + absolute deadline), or a fresh one when the handler was
        invoked outside ``traced_rpc`` (hand-rolled test harnesses)."""
        rctx = current_context.get()
        if rctx is None:
            from ..observability import RequestContext, rpc_deadline

            rctx = RequestContext.from_grpc(
                context, deadline=rpc_deadline(context)
            )
        return rctx

    def _audit_note(
        self, items: list[tuple[str, Statement, bytes, bytes, bool]]
    ) -> None:
        """Append verification outcomes to the proof log (no-op unless
        ``[audit]`` wired one in).  ``items``: (user_id, statement,
        challenge_id, proof_wire, verdict) per VERIFIED entry — shed or
        errored entries never reached the verifier and are not audit
        events.  The append is one buffered ``os.write``; the fsync (when
        the policy wants one) runs on a worker thread with its task
        handle retained."""
        log = self.audit_log
        if log is None or not items:
            return
        eb = Ristretto255.element_to_bytes
        try:
            log.append_proofs([
                proof_record(uid, eb(st.y1), eb(st.y2), ctx, wire, ok)
                for uid, st, ctx, wire, ok in items
            ])
        except OSError:
            metrics.counter("audit.log.errors").inc()
            return
        if log.needs_sync():
            task = asyncio.get_running_loop().create_task(
                asyncio.to_thread(log.sync)
            )
            self._audit_flushes.add(task)
            task.add_done_callback(self._audit_flushes.discard)

    def _parse_statement(self, y1_bytes: bytes, y2_bytes: bytes) -> Statement:
        """Shared register-path statement validation; raises errors.Error
        with the reference's message prefixes."""
        try:
            y1 = Ristretto255.element_from_bytes(y1_bytes)
        except errors.Error as e:
            raise errors.InvalidParams(f"Invalid y1: {e}") from None
        try:
            y2 = Ristretto255.element_from_bytes(y2_bytes)
        except errors.Error as e:
            raise errors.InvalidParams(f"Invalid y2: {e}") from None
        statement = Statement(y1, y2)
        try:
            statement.validate()
        except errors.Error as e:
            raise errors.InvalidParams(f"Invalid statement: {e}") from None
        if Ristretto255.is_identity(y1) or Ristretto255.is_identity(y2):
            raise errors.InvalidParams("Statement contains identity elements")
        return statement

    # --- RPCs ---

    # requests/success/failure counters and the duration histogram for
    # every RPC live in the traced_rpc decorator (one lifecycle, no
    # skipped .observe() on early-abort paths); handler bodies keep only
    # their domain-specific counters.

    @traced_rpc("Register", "auth.register")
    async def register(self, request, context):
        await self._admit(context, "Register")
        await self._validate_user_id(request.user_id, context)
        await self._check_owner(request.user_id, context)

        if not request.y1 or not request.y2:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "Empty y1 or y2 values")
        if len(request.y1) > MAX_ELEMENT_WIRE or len(request.y2) > MAX_ELEMENT_WIRE:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "y1 or y2 values too large")

        try:
            statement = self._parse_statement(request.y1, request.y2)
        except errors.Error as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))

        try:
            await self.state.register_user(
                UserData(
                    user_id=request.user_id,
                    statement=statement,
                    registered_at=int(time.time()),
                )
            )
        except errors.WrongPartition as e:
            # ownership moved between the entry check and the insert (a
            # live split flipped the map mid-flight): redirect, no ack
            await self._redirect_abort(request.user_id, context, str(e))
        except errors.Error as e:
            await context.abort(grpc.StatusCode.ALREADY_EXISTS, f"Registration failed: {e}")

        return self.pb2.RegistrationResponse(
            success=True,
            message=f"User '{request.user_id}' registered successfully",
        )

    @traced_rpc("RegisterBatch", "auth.register_batch")
    async def register_batch(self, request, context):
        await self._admit(context, "RegisterBatch")

        n = len(request.user_ids)
        if n == 0:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "Empty batch")
        if n != len(request.y1_values) or n != len(request.y2_values):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "Mismatched array lengths in batch request"
            )
        if n > MAX_BATCH:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"Batch size exceeds maximum limit of {MAX_BATCH}",
            )
        metrics.counter("auth.register_batch.users_count").inc(n)

        results = []
        for i in range(n):
            user_id = request.user_ids[i]
            y1b, y2b = request.y1_values[i], request.y2_values[i]

            msg = _user_id_error(user_id)
            if msg is None:
                msg = self._wrong_partition_counted(user_id)
            if msg is None:
                if not y1b or not y2b:
                    msg = f"Empty y1 or y2 values for user {i}"
                elif len(y1b) > MAX_ELEMENT_WIRE or len(y2b) > MAX_ELEMENT_WIRE:
                    msg = f"y1 or y2 values too large for user {i}"
            if msg is not None:
                results.append(self.pb2.RegistrationResult(success=False, message=msg))
                metrics.counter("auth.register_batch.individual_failure").inc()
                continue

            try:
                statement = self._parse_statement(y1b, y2b)
                await self.state.register_user(
                    UserData(
                        user_id=user_id,
                        statement=statement,
                        registered_at=int(time.time()),
                    )
                )
            except errors.Error as e:
                text = str(e)
                if "already registered" in text or "capacity" in text:
                    text = f"Registration failed: {text}"
                results.append(self.pb2.RegistrationResult(success=False, message=text))
                metrics.counter("auth.register_batch.individual_failure").inc()
                continue

            results.append(
                self.pb2.RegistrationResult(
                    success=True,
                    message=f"User '{user_id}' registered successfully",
                )
            )
            metrics.counter("auth.register_batch.individual_success").inc()

        return self.pb2.BatchRegistrationResponse(results=results)

    @traced_rpc("CreateChallenge", "auth.challenge")
    async def create_challenge(self, request, context):
        self._note_wire(request)
        await self._admit(context, "CreateChallenge")
        await self._validate_user_id(request.user_id, context)
        await self._check_owner(request.user_id, context)

        user = await self.state.get_user(request.user_id)
        if user is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"User '{request.user_id}' not found"
            )

        # the id carries the owning user's shard index in byte 0, so
        # VerifyProof routes straight to the shard that issued it
        challenge_id = self.state.tag_challenge_id(
            user.user_id, self.rng.fill_bytes(32)
        )
        try:
            expires_at = await self.state.create_challenge(user.user_id, challenge_id)
        except errors.WrongPartition as e:
            await self._redirect_abort(request.user_id, context, str(e))
        except errors.Error as e:
            # per-user challenge-cap overload: pushback rides along like
            # every other RESOURCE_EXHAUSTED (satellite fix)
            await self._abort_exhausted(
                context, f"Challenge creation failed: {e}", self._pushback_s()
            )

        return self.pb2.ChallengeResponse(challenge_id=challenge_id, expires_at=expires_at)

    @traced_rpc("VerifyProof", "auth.verify")
    async def verify_proof(self, request, context):
        await self._admit(context, "VerifyProof")
        await self._validate_user_id(request.user_id, context)
        # ownership BEFORE consume_challenge: a redirected VerifyProof
        # never burned its challenge, so re-routing it is safe
        await self._check_owner(request.user_id, context)

        msg = _proof_args_error(request.challenge_id, request.proof)
        if msg is not None:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, msg)

        try:
            challenge = await self.state.consume_challenge(request.challenge_id)
        except errors.Error:
            await context.abort(grpc.StatusCode.PERMISSION_DENIED, "Authentication failed")
        if challenge.user_id != request.user_id:
            await context.abort(grpc.StatusCode.PERMISSION_DENIED, "Authentication failed")

        user = await self.state.get_user(request.user_id)
        if user is None:
            await context.abort(grpc.StatusCode.PERMISSION_DENIED, "Authentication failed")

        try:
            proof = Proof.from_bytes(request.proof)
        except errors.Error as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"Invalid proof: {e}")

        if self.batcher is not None:
            # TPU serving path: coalesce with concurrent RPCs into one
            # device batch; per-entry result has identical semantics
            rctx = self._request_context(context)
            try:
                verify_err = await self.batcher.submit(
                    Parameters.new(), user.statement, proof,
                    bytes(request.challenge_id),
                    deadline=rctx.deadline,
                    trace_id=rctx.trace_id,
                )
            except batching.QueueFull:
                await self._abort_exhausted(
                    context, "Server overloaded", self._pushback_s()
                )
            except batching.DeadlineExceeded:
                await context.abort(
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                    "Deadline expired before verification",
                )
        else:
            verifier = Verifier(Parameters.new(), user.statement)
            transcript = Transcript()
            transcript.append_context(request.challenge_id)
            try:
                verifier.verify_with_transcript(proof, transcript)
                verify_err = None
            except errors.Error as e:
                verify_err = e
        # audit trail BEFORE the failure abort: rejected proofs are audit
        # events too (the bulk pipeline re-checks both verdicts)
        self._audit_note([(
            request.user_id, user.statement, bytes(request.challenge_id),
            bytes(request.proof), verify_err is None,
        )])
        if verify_err is not None:
            await context.abort(
                grpc.StatusCode.PERMISSION_DENIED, f"Verification failed: {verify_err}"
            )

        # shard-tagged like the challenge id: validate/revoke route
        # straight to the issuing shard
        token = self.state.tag_session_token(
            request.user_id, self.rng.fill_bytes(32).hex()
        )
        try:
            await self.state.create_session(token, request.user_id)
        except errors.WrongPartition as e:
            # the reviewer-scenario race: ownership was checked at entry,
            # the batcher await straddled a live split's map flip, and the
            # session write reached a partition that no longer owns the
            # user.  The fence rejected it BEFORE any state or WAL touch,
            # so no token is acked that exists on neither partition — the
            # client re-routes (its challenge is gone here, so the login
            # restarts at the new owner; a failed attempt, never a lie)
            await self._redirect_abort(request.user_id, context, str(e))
        except errors.Error as e:
            await context.abort(grpc.StatusCode.INTERNAL, f"Failed to create session: {e}")

        return self.pb2.VerificationResponse(
            success=True,
            message=f"User '{request.user_id}' authenticated successfully",
            session_token=token,
        )

    @traced_rpc("VerifyProofBatch", "auth.verify_batch")
    async def verify_proof_batch(self, request, context):
        self._note_wire(request)
        await self._admit(context, "VerifyProofBatch")

        n = len(request.user_ids)
        if n == 0:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "Empty batch")
        if n != len(request.challenge_ids) or n != len(request.proofs):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "Mismatched array lengths in batch request"
            )
        if n > MAX_BATCH:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"Batch size exceeds maximum limit of {MAX_BATCH}",
            )
        metrics.counter("auth.verify_batch.proofs_count").inc(n)

        # materialize the repeated fields once: protobuf repeated-field
        # __getitem__ costs add up over 3 accesses x 1000 items (the
        # native wire views already hold plain lists — no copy needed)
        user_ids = request.user_ids
        if type(user_ids) is not list:
            user_ids = list(user_ids)
        challenge_ids = request.challenge_ids
        if type(challenge_ids) is not list:
            challenge_ids = list(challenge_ids)
        proof_wires = request.proofs
        if type(proof_wires) is not list:
            proof_wires = list(proof_wires)

        batch = BatchVerifier(backend=self.backend)
        contexts: list[str | None] = []  # user_id once queued for verify, else None
        statements: dict[int, Statement] = {}  # queued-for-verify audit trail
        error_msgs: list[str] = []
        # stage 1: argument validation (no awaits)
        staged: list[int] = []  # indices that passed arg validation
        for i in range(n):
            msg = _user_id_error(user_ids[i])
            if msg is None:
                # ownership BEFORE staging: a misrouted entry is answered
                # with the redirect message and its challenge is NEVER
                # consumed, so re-sending it to the owner succeeds
                msg = self._wrong_partition_counted(user_ids[i])
            if msg is None:
                msg = _proof_args_error(challenge_ids[i], proof_wires[i], index=i)
            contexts.append(None)
            error_msgs.append(msg or "")
            if msg is None:
                staged.append(i)

        # stage 2: consume BEFORE verification — single-use even on failure
        # (service.rs:478; docs/protocol.md:174-176).  Bulk state calls:
        # one lock acquisition for all n consumes (and one for the user
        # lookups) instead of 2n event-loop round-trips.
        challenges = await self.state.consume_challenges(
            [challenge_ids[i] for i in staged])
        users = await self.state.get_users(
            [user_ids[i] for i in staged])
        live: list[tuple[int, UserData]] = []
        for i, challenge, user in zip(staged, challenges, users, strict=True):
            if (
                challenge is None
                or challenge.user_id != user_ids[i]
                or user is None
            ):
                error_msgs[i] = "Authentication failed"
                continue
            live.append((i, user))
        # Bulk parse: one native validation pass for the whole batch,
        # commitment point decodes DEFERRED on every path — the
        # batch-verify stage decodes them anyway (BatchVerifier settles
        # failures with the exact parse error).  On the batcher path the
        # deferred screening runs in BatchVerifier.prepare_batch on the
        # dispatch lane's prep thread, overlapped with the previous
        # batch's device compute, so the decode cost leaves the RPC's
        # serial path entirely.
        # when every entry survived screening, the native wire view's
        # contiguous proof buffer (gathered in C straight off the socket
        # bytes) feeds the batched parse with no Python re-join
        packed = (
            request.packed_proofs(n)
            if len(live) == n and hasattr(request, "packed_proofs")
            else None
        )
        parsed = Proof.from_bytes_batch(
            [proof_wires[i] for i, _ in live],
            defer_point_validation=True,
            packed=packed,
        )
        params = Parameters.new()  # shared generators: one instance per RPC
        for (i, user), proof in zip(live, parsed, strict=True):
            if isinstance(proof, errors.Error):
                error_msgs[i] = f"Invalid proof: {proof}"
                continue
            try:
                batch.add_with_context(
                    params, user.statement, proof, bytes(challenge_ids[i]),
                )
            except errors.Error as e:
                error_msgs[i] = f"Failed to add proof to batch: {e}"
                continue
            contexts[i] = user_ids[i]
            statements[i] = user.statement

        batch_results: list = []
        if len(batch) > 0:
            try:
                if self.batcher is not None:
                    # one bulk enqueue; all-or-nothing on backpressure, so
                    # no orphaned sibling submits to drain on QueueFull.
                    # All entries share this RPC's deadline: past it the
                    # batcher sheds them instead of burning device time.
                    rctx = self._request_context(context)
                    for entry in batch.entries:
                        entry.deadline = rctx.deadline
                        entry.trace_id = rctx.trace_id
                    batch_results = await self.batcher.submit_many(batch.entries)
                else:
                    # worker thread, not the event loop: the native verify
                    # releases the GIL, so a concurrent RPC's Python
                    # (parse, state ops, response build) overlaps this
                    # batch's crypto instead of queueing behind ~100ms of
                    # blocked loop — and health checks stay responsive
                    async with self._inline_verify:
                        batch_results = await asyncio.to_thread(
                            batch.verify, self.rng)
            except batching.QueueFull:
                await self._abort_exhausted(
                    context, "Server overloaded", self._pushback_s()
                )
            except batching.DeadlineExceeded:
                await context.abort(
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                    "Deadline expired before verification",
                )
            except errors.Error as e:
                await context.abort(grpc.StatusCode.INTERNAL, f"Batch verification failed: {e}")

        # session issuance for verified items — one bulk mint (single lock,
        # single CSPRNG draw sliced into per-item tokens)
        verified: list[int] = []
        tokens: dict[int, str] = {}
        batch_index = 0
        verify_errs: dict[int, object] = {}
        audit_items = []
        for i in range(n):
            if contexts[i] is None:
                continue
            verify_errs[i] = batch_results[batch_index]
            batch_index += 1
            if verify_errs[i] is None:
                verified.append(i)
            audit_items.append((
                contexts[i], statements[i], bytes(challenge_ids[i]),
                bytes(proof_wires[i]), verify_errs[i] is None,
            ))
        self._audit_note(audit_items)
        token_pool = self.rng.fill_bytes(32 * len(verified)).hex()
        for k, i in enumerate(verified):
            tokens[i] = self.state.tag_session_token(
                contexts[i], token_pool[64 * k: 64 * (k + 1)]
            )
        # cpzk-lint: disable=AWAIT-001 -- bulk mint: the fence verdict comes back per-entry from create_sessions (re-checked inside its shard lock) and is mapped to redirect-shaped messages below — the batch wire contract has no single redirect to raise
        session_errs = await self.state.create_sessions(
            [(tokens[i], contexts[i]) for i in verified])
        session_err_by_index = dict(zip(verified, session_errs, strict=True))

        results = []
        n_failure = 0
        Result = self.pb2.VerificationResult
        for i in range(n):
            user_id = contexts[i]
            if user_id is None:
                results.append(Result(success=False, message=error_msgs[i]))
                n_failure += 1
                continue
            verr = verify_errs[i]
            if verr is not None:
                # a deferred-parse proof whose commitment wire failed to
                # decode reports the exact parse-time message; genuine
                # verification failures stay opaque (service.rs:528)
                if isinstance(verr, errors.InvalidProofEncoding):
                    msg = f"Invalid proof: {verr}"
                else:
                    msg = "Authentication failed"
                results.append(Result(success=False, message=msg))
                n_failure += 1
                continue
            serr = session_err_by_index[i]
            if serr is not None:
                # a write-time fence rejection (live split flipped the map
                # mid-batch) keeps the entry-check redirect shape so the
                # client's per-entry re-route handling sees one format
                if serr.startswith("wrong partition"):
                    msg = serr
                else:
                    msg = f"Failed to create session: {serr}"
                results.append(Result(success=False, message=msg))
                n_failure += 1
                continue
            results.append(Result(
                success=True,
                message=f"User '{user_id}' authenticated successfully",
                session_token=tokens[i],
            ))
        if n_failure:
            metrics.counter("auth.verify_batch.individual_failure").inc(n_failure)
        if n - n_failure:
            metrics.counter("auth.verify_batch.individual_success").inc(n - n_failure)

        return self.pb2.BatchVerificationResponse(results=results)

    # --- streaming verification -------------------------------------------

    @traced_stream_rpc("VerifyProofStream", "auth.verify_stream")
    async def verify_proof_stream(self, request_iterator, context):
        """Bidirectional streaming verification: the client streams proof
        entries (possibly several per message — parallel arrays keyed by
        ``ids``), the server streams verdicts as their device batches
        settle.  Entries enqueue straight into the dynamic batcher, so
        one stream gives the dispatch lane naturally deep, TPU-sized
        batches without per-RPC overhead.

        Contract highlights (pinned in ``tests/test_streaming.py``):

        - **flow control**: at most ``stream_window`` entries in flight;
          past it the reader stops pulling and gRPC's own flow control
          pushes back on the sender — memory stays bounded, the stream
          stays open;
        - **admission per proof, not per RPC**: the keyed token bucket is
          charged for every entry (client id read once at stream open);
          a shed entry answers a per-entry NOT-verdict with the pushback
          delay in ``retry_after_ms`` (and trailing metadata at stream
          end) — the stream is never killed for overload;
        - **per-entry deadline shedding**: expired entries come back as
          NOT-verdicts while their batch siblings carry real verdicts;
        - **failure isolation**: a backend blow-up is confined to its
          chunk (NOT-verdicts), the stream and the lane both survive;
        - **verdict order** follows entry order.
        """
        if self.replica is not None and self.replica.role != "primary":
            metrics.counter("admission.shed.standby").inc()
            await context.abort(
                grpc.StatusCode.UNAVAILABLE,
                "standby replica: not promoted (writes go to the primary)",
            )
        # the global bucket is an RPC-level backstop: one charge per
        # stream open; per-PROOF fairness is the keyed bucket below
        try:
            await self.rate_limiter.check_rate_limit()
        except RateLimitExceeded as e:
            metrics.counter("admission.shed.global").inc()
            await self._abort_exhausted(
                context, "Rate limit exceeded",
                getattr(e, "retry_after_s", 0.0) or DEFAULT_RETRY_AFTER_S,
            )
        client = client_key(context)  # read once at stream open
        rctx = self._request_context(context)
        stream_info = self._stream_open(client, rctx.trace_id)
        pushback_ms = 0

        def note_pushback(ms: int) -> None:
            nonlocal pushback_ms
            pushback_ms = max(pushback_ms, ms)

        # reader task + responder loop: chunks dispatch the moment they
        # arrive and verdicts flow back the moment their batches settle —
        # a client that reads verdicts before sending its next chunk must
        # never deadlock against a handler that only flushes on pressure.
        # The window condition is the flow-control seam: past
        # ``stream_window`` in-flight entries the reader stops pulling,
        # and gRPC's transport-level flow control pushes back on the
        # sender without killing the stream.
        cond = asyncio.Condition()
        inflight = 0
        unsettled: set[_StreamChunk] = set()
        out_q: asyncio.Queue[_StreamChunk | None] = asyncio.Queue()

        async def reader() -> None:
            nonlocal inflight
            try:
                async for request in request_iterator:
                    async with cond:
                        while inflight > self.stream_window:
                            await cond.wait()
                    work = self._stream_start_chunk(
                        request, client, rctx, note_pushback
                    )
                    inflight += work.size
                    stream_info["chunks"] += 1
                    stream_info["entries"] += len(work.ids)
                    stream_info["inflight"] = inflight
                    unsettled.add(work)
                    out_q.put_nowait(work)
            finally:
                out_q.put_nowait(None)

        reader_task = asyncio.get_running_loop().create_task(reader())
        try:
            while True:
                work = await out_q.get()
                if work is None:
                    break
                resp = await self._stream_settle(work)
                unsettled.discard(work)
                async with cond:
                    inflight -= work.size
                    cond.notify_all()
                stream_info["inflight"] = inflight
                yield resp
            await reader_task  # surface a reader-side transport error
        finally:
            self._stream_close(stream_info)
            # client gone / handler torn down with chunks in flight:
            # cancel the reader and every unsettled verify task so no
            # batcher future leaks (cancelled chunk futures are shed as
            # 'abandoned' before device dispatch)
            if not reader_task.done():
                reader_task.cancel()
            doomed = [w.task for w in unsettled if w.task is not None]
            for task in doomed:
                task.cancel()
            if doomed or not reader_task.done():
                await asyncio.gather(
                    reader_task, *doomed, return_exceptions=True,
                )
            if pushback_ms > 0:
                try:
                    context.set_trailing_metadata(
                        ((RETRY_PUSHBACK_KEY, str(pushback_ms)),)
                    )
                except Exception:  # hand-rolled test context
                    pass

    def _stream_start_chunk(
        self, request, client: str, rctx, note_pushback
    ) -> "_StreamChunk":
        """Validate + admit one chunk message, consume its challenges,
        and dispatch the survivors into the batcher WITHOUT awaiting —
        the caller keeps reading while the device works."""
        wire_mod.note_wire_parse(request, rctx.trace_id)
        ids = request.ids
        ids = list(ids) if type(ids) is not list else ids
        n = len(ids)
        work = _StreamChunk(ids=ids, size=max(n, 1),
                            mint=bool(request.mint_sessions))
        if (
            n == 0
            or n != len(request.user_ids)
            or n != len(request.challenge_ids)
            or n != len(request.proofs)
        ):
            work.chunk_error = (
                "Mismatched array lengths in stream chunk"
                if n else "Empty stream chunk"
            )
            return work
        if n > MAX_STREAM_CHUNK:
            work.chunk_error = (
                f"Stream chunk exceeds maximum of {MAX_STREAM_CHUNK} entries"
            )
            return work
        metrics.counter("auth.stream.proofs_count").inc(n)
        user_ids = request.user_ids
        user_ids = list(user_ids) if type(user_ids) is not list else user_ids
        challenge_ids = request.challenge_ids
        if type(challenge_ids) is not list:
            challenge_ids = list(challenge_ids)
        proof_wires = request.proofs
        if type(proof_wires) is not list:
            proof_wires = list(proof_wires)
        if hasattr(request, "packed_proofs"):
            work.packed = request.packed_proofs(n)
        work.messages = [""] * n
        work.results = [_UNSET] * n
        work.user_ids = user_ids
        work.challenge_ids = challenge_ids
        work.proof_wires = proof_wires
        staged: list[int] = []
        uid_memo: dict[str, str | None] = {}  # streams repeat user ids
        for i in range(n):
            # keyed fair admission charged per PROOF (satellite contract):
            # a hot streamer exhausts its own bucket entry by entry and
            # gets NOT-verdicts + pushback, never a dead stream
            if self.admission is not None:
                rejection = self.admission.admit("VerifyProof", client)
                if rejection is not None:
                    ms = max(0, int(round(rejection.retry_after_s * 1000.0)))
                    note_pushback(ms)
                    work.messages[i] = rejection.message
                    work.shed[i] = ms
                    metrics.counter("auth.stream.shed").inc()
                    continue
            uid = user_ids[i]
            if uid in uid_memo:
                msg = uid_memo[uid]
            else:
                # ownership rides the same memo as user-id validation
                # (streams repeat user ids): one hash per distinct user,
                # misrouted entries answered per-entry, stream survives
                msg = uid_memo[uid] = (
                    _user_id_error(uid) or self._wrong_partition_counted(uid)
                )
            msg = msg or _proof_args_error(challenge_ids[i], proof_wires[i])
            if msg is not None:
                work.messages[i] = msg
                continue
            staged.append(i)
        work.staged = staged
        if staged:
            work.task = asyncio.get_running_loop().create_task(
                self._stream_verify(work, rctx)
            )
        return work

    async def _stream_verify(self, work: "_StreamChunk", rctx) -> None:
        """One chunk's consume -> lookup -> parse -> dispatch, recording
        per-entry outcomes onto ``work`` (runs as a task so the stream
        reader is never blocked on the device)."""
        staged = work.staged
        challenges = await self.state.consume_challenges(
            [work.challenge_ids[i] for i in staged])
        users = await self.state.get_users(
            [work.user_ids[i] for i in staged])
        live: list[int] = []
        for i, challenge, user in zip(staged, challenges, users, strict=True):
            if (
                challenge is None
                or challenge.user_id != work.user_ids[i]
                or user is None
            ):
                work.messages[i] = "Authentication failed"
                continue
            work.users[i] = user
            live.append(i)
        parsed = Proof.from_bytes_batch(
            [work.proof_wires[i] for i in live],
            defer_point_validation=True,
            # native wire views: the C-gathered contiguous proof buffer
            # feeds the batched parse when every entry survived screening
            packed=(
                work.packed
                if len(live) == len(work.proof_wires) else None
            ),
        )
        params = Parameters.new()
        deadline = rctx.deadline
        if self.stream_entry_deadline_s is not None:
            entry_deadline = time.monotonic() + self.stream_entry_deadline_s
            deadline = (
                entry_deadline if deadline is None
                else min(deadline, entry_deadline)
            )
        entries: list[BatchEntry] = []
        queued: list[int] = []
        for i, proof in zip(live, parsed, strict=True):
            if isinstance(proof, errors.Error):
                work.messages[i] = f"Invalid proof: {proof}"
                continue
            entries.append(BatchEntry(
                params, work.users[i].statement, proof,
                bytes(work.challenge_ids[i]),
                deadline=deadline, trace_id=rctx.trace_id,
            ))
            queued.append(i)
        if not entries:
            return
        if self.batcher is not None:
            try:
                results = await self.batcher.submit_group(entries)
            except batching.QueueFull:
                results = [batching.QueueFull("Server overloaded")] * len(entries)
        else:
            # inline CPU path: same dispatch seam, worker thread, bounded
            # crypto concurrency (GIL-released native verify)
            async with self._inline_verify:
                try:
                    results = await asyncio.to_thread(
                        DispatchLane.verify_once,
                        self.backend, self.rng, entries,
                    )
                except errors.Error as exc:
                    results = [exc] * len(entries)
        for i, res in zip(queued, results, strict=True):
            work.results[i] = res

    async def _stream_settle(self, work: "_StreamChunk"):
        """Await a chunk's verification task and build its verdict
        message (sessions minted in bulk, audit records appended)."""
        Resp = self.pb2.StreamVerifyResponse
        if work.chunk_error is not None:
            return Resp(
                ids=work.ids,
                success=[False] * len(work.ids),
                messages=[work.chunk_error] * len(work.ids),
            )
        if work.task is not None:
            try:
                await work.task
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # chunk-confined: stream survives
                blowup = RuntimeError(f"Batch verification failed: {exc}")
                for i in work.staged:
                    if work.results[i] is _UNSET and work.users.get(i) is not None:
                        work.results[i] = blowup
        n = len(work.ids)
        success = [False] * n
        audit_items = []
        verified: list[int] = []
        retry_ms = 0
        shed = work.shed
        results = work.results
        for i in range(n):
            if shed and i in shed:
                retry_ms = max(retry_ms, shed[i])
                continue
            res = results[i]
            if res is _UNSET:
                continue  # message already set (validation/auth failure)
            if res is None:
                success[i] = True
                verified.append(i)
            elif isinstance(res, batching.DeadlineExceeded):
                work.messages[i] = "Deadline expired before verification"
                metrics.counter("auth.stream.shed").inc()
            elif isinstance(res, batching.QueueFull):
                work.messages[i] = "Server overloaded"
                ms = max(0, int(round(self._pushback_s() * 1000.0)))
                retry_ms = max(retry_ms, ms)
                metrics.counter("auth.stream.shed").inc()
            elif isinstance(res, errors.InvalidProofEncoding):
                work.messages[i] = f"Invalid proof: {res}"
            elif isinstance(res, errors.Error):
                work.messages[i] = "Authentication failed"
            else:  # dispatch blow-up (backend raise) confined to chunk
                work.messages[i] = "Verification unavailable"
            if isinstance(res, (type(None), errors.Error)):
                user = work.users.get(i)
                if user is not None:
                    audit_items.append((
                        work.user_ids[i], user.statement,
                        bytes(work.challenge_ids[i]),
                        bytes(work.proof_wires[i]), res is None,
                    ))
        self._audit_note(audit_items)
        tokens: dict[int, str] = {}
        if work.mint and verified:
            pool = self.rng.fill_bytes(32 * len(verified)).hex()
            pairs = []
            for k, i in enumerate(verified):
                tokens[i] = self.state.tag_session_token(
                    work.user_ids[i], pool[64 * k: 64 * (k + 1)]
                )
                pairs.append((tokens[i], work.user_ids[i]))
            session_errs = await self.state.create_sessions(pairs)
            for i, err in zip(verified, session_errs, strict=True):
                if err is not None:
                    success[i] = False
                    # fence rejections keep the redirect shape (see
                    # verify_proof_batch) so stream consumers re-route
                    work.messages[i] = (
                        err if err.startswith("wrong partition")
                        else f"Failed to create session: {err}"
                    )
                    tokens.pop(i, None)
        resp = Resp(
            ids=work.ids,
            success=success,
            messages=work.messages,
            retry_after_ms=retry_ms,
        )
        if tokens:
            resp.session_tokens.extend(
                tokens.get(i, "") for i in range(n)
            )
        return resp


@dataclass(eq=False)  # identity hash: chunks live in the handler's
class _StreamChunk:     # unsettled set until their verdicts are yielded
    """One VerifyProofStream chunk moving through the pipeline."""

    ids: list[int]
    size: int
    mint: bool
    chunk_error: str | None = None
    messages: list[str] = field(default_factory=list)
    user_ids: list[str] = field(default_factory=list)
    challenge_ids: list = field(default_factory=list)
    proof_wires: list = field(default_factory=list)
    staged: list[int] = field(default_factory=list)
    shed: dict[int, int] = field(default_factory=dict)        # i -> retry ms
    users: dict[int, UserData] = field(default_factory=dict)
    results: list = field(default_factory=list)  # i -> verdict | _UNSET
    task: asyncio.Task | None = None
    #: native wire view's contiguous proof buffer (None on the protobuf
    #: path or when any proof has a non-canonical size)
    packed: bytes | None = None


def _proof_args_error(challenge_id: bytes, proof: bytes, index: int | None = None) -> str | None:
    sfx = "" if index is None else f" for proof {index}"
    if not challenge_id:
        return f"Empty challenge ID{sfx}"
    if len(challenge_id) > MAX_CHALLENGE_ID:
        return f"Challenge ID too long{sfx}"
    if not proof:
        return f"Empty proof{sfx}" if index is None else f"Empty proof {index}"
    if len(proof) > MAX_PROOF_WIRE:
        return f"Proof too large{sfx}" if index is None else f"Proof {index} too large"
    return None


def request_deserializers(pb2, wire: str = "native") -> dict:
    """{rpc name: request deserializer} for all six RPCs.  With
    ``wire="native"`` the three hot messages (``CreateChallenge``,
    ``VerifyProofBatch``, ``VerifyProofStream`` chunks) go through the
    native wire parser first (``server/wire.py``), falling back to the
    protobuf runtime for anything outside its recognized subset — with
    ``wire="python"`` (or no loadable ``.so``) every message takes
    ``FromString``, today's path unchanged.  Shared by the in-process
    listener and the sharded-ingest processes so the two ingest shapes
    cannot drift."""
    types = method_types(pb2)
    stream_types = stream_method_types(pb2)
    out = {name: req.FromString for name, (req, _resp) in types.items()}
    out["VerifyProofStream"] = stream_types["VerifyProofStream"][0].FromString
    if wire == "native" and wire_mod.native_available():
        for name in ("CreateChallenge", "VerifyProofBatch",
                     "VerifyProofStream"):
            req_cls = (stream_types if name == "VerifyProofStream"
                       else types)[name][0]
            deser = wire_mod.make_deserializer(name, req_cls)
            if deser is not None:
                out[name] = deser
    return out


def make_generic_handler(service: AuthServiceImpl) -> grpc.GenericRpcHandler:
    """Register the six RPCs without generated *_pb2_grpc stubs."""
    pb2 = service.pb2
    types = method_types(pb2)
    desers = request_deserializers(pb2, service.wire)
    impl = {
        "Register": service.register,
        "RegisterBatch": service.register_batch,
        "CreateChallenge": service.create_challenge,
        "VerifyProof": service.verify_proof,
        "VerifyProofBatch": service.verify_proof_batch,
    }
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            impl[name],
            request_deserializer=desers[name],
            response_serializer=types[name][1].SerializeToString,
        )
        for name in impl
    }
    stream_types = stream_method_types(pb2)
    handlers["VerifyProofStream"] = grpc.stream_stream_rpc_method_handler(
        service.verify_proof_stream,
        request_deserializer=desers["VerifyProofStream"],
        response_serializer=stream_types["VerifyProofStream"][1].SerializeToString,
    )
    return grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)


async def serve(
    state: ServerState,
    rate_limiter: RateLimiter,
    host: str = "127.0.0.1",
    port: int = 50051,
    backend: VerifierBackend | None = None,
    batcher=None,
    tls: tuple[bytes, bytes] | None = None,
    admission=None,
    replica=None,
    audit_log=None,
    stream_window: int = 8192,
    stream_entry_deadline_ms: float = 0.0,
    fleet=None,
    wire: str = "native",
    listen: bool = True,
):
    """Build and start an aio server; returns (server, bound_port).

    ``tls`` is an optional (private_key_pem, cert_chain_pem) pair — wired
    for real, unlike the reference where validated TLS settings never reach
    the transport (SURVEY.md §3.3).  ``batcher`` is an optional started-here
    :class:`~cpzk_tpu.server.batching.DynamicBatcher` routing verification
    through the TPU data plane; it is exposed as ``server.batcher`` so the
    daemon can drain it on shutdown.  ``admission`` is an optional
    :class:`~cpzk_tpu.admission.AdmissionController` gating every RPC
    (per-client fairness + priority shedding + retry pushback).
    ``replica`` is an optional
    :class:`~cpzk_tpu.replication.StandbyReplica`: its ReplicationService
    handler is registered alongside the auth service, readiness reports
    NOT_SERVING until promotion, and every auth RPC aborts UNAVAILABLE
    while the node is still a standby.  ``audit_log`` is an optional
    :class:`~cpzk_tpu.audit.ProofLogWriter` the verify paths append
    (statement, challenge, proof, verdict) records to — the bulk audit
    pipeline's input; the daemon closes it after the batcher drains.
    ``stream_window`` / ``stream_entry_deadline_ms`` are the
    VerifyProofStream flow-control knobs (``[tpu]`` config).  ``fleet``
    is an optional :class:`~cpzk_tpu.fleet.FleetRouter`: every auth RPC
    then checks partition ownership before touching state and redirects
    wrong-partition requests with the map version + owner address in
    trailing metadata (docs/operations.md §"Partitioned fleet").
    ``wire`` selects the transport parse path ("native" = the C++ wire
    scanner with unconditional protobuf fallback, "python" = protobuf
    runtime only — see ``server/wire.py``); ``listen=False`` starts the
    server portless for the sharded-ingest mode, where SO_REUSEPORT
    listener processes own the public address and feed the handlers over
    the :class:`~cpzk_tpu.server.ingest.IngestSupervisor` seam
    (docs/operations.md §"Wire path & ingest shards").
    """
    server = grpc.aio.server()
    service = AuthServiceImpl(
        state, rate_limiter, backend=backend, batcher=batcher,
        admission=admission, replica=replica, audit_log=audit_log,
        stream_window=stream_window,
        stream_entry_deadline_ms=stream_entry_deadline_ms,
        fleet=fleet, wire=wire,
    )
    server.add_generic_rpc_handlers((make_generic_handler(service),))
    if replica is not None:
        server.add_generic_rpc_handlers((replica.handler(),))
    health = _add_health_service(server, backend=backend)
    if replica is not None:
        health.standby = replica.role != "primary"
        replica.health = health  # promotion flips readiness to SERVING
    server.health = health  # for shutdown: server.health.serving = False
    server.auth_service = service  # ops plane: /statusz stream rows
    server.batcher = batcher
    server.admission = admission
    server.replica = replica
    server.audit_log = audit_log  # daemon closes it after the batcher drains
    server.fleet = fleet  # ops plane: /partitionmap + /statusz fleet block
    if batcher is not None:
        batcher.start()
    if not listen:
        # sharded-ingest mode: the SO_REUSEPORT listener processes own
        # the public port; this dispatch-process server starts portless
        # (handlers reachable only through the ingest supervisor's
        # framed unix-socket seam)
        await server.start()
        return server, None
    addr = f"{host}:{port}"
    if tls is not None:
        creds = grpc.ssl_server_credentials([tls])
        bound = server.add_secure_port(addr, creds)
    else:
        bound = server.add_insecure_port(addr)
    await server.start()
    return server, bound


#: ``HealthCheckRequest.service`` values that select the READINESS view
#: (the auth service name also works, for LB configs that probe it).
READINESS_SERVICE = "readiness"


class HealthService:
    """Standard gRPC health protocol, hand-wired (tonic-health twin,
    bin/server.rs:208-211), split into liveness and readiness views:

    - ``service=""`` — **liveness**: SERVING while the process is up and
      not draining (``serving = False`` flips it at graceful shutdown,
      bin/server.rs:420-422).  An open failover breaker does NOT flip
      liveness — the CPU fallback still answers correctly.
    - ``service="readiness"`` (or the auth service name) — **readiness**:
      additionally NOT_SERVING while WAL recovery/replay is still running
      (``recovering``), while the failover breaker holds the backend
      degraded, and while the node is an unpromoted replication standby
      (``standby`` — lease-based promotion flips it to SERVING), so load
      balancers stop routing to a replica that would only shed or answer
      at fallback speed, without restart-looping it.
    """

    def __init__(self, backend=None):
        from .proto import load_health_pb2

        self.pb2 = load_health_pb2()
        self.serving = True
        #: True while boot-time WAL recovery/replay runs (set by whoever
        #: drives recovery with the listener already up; the stock daemon
        #: recovers before binding, where "not ready" is simply
        #: connection-refused).
        self.recovering = False
        #: True while this node is an unpromoted replication standby —
        #: liveness stays SERVING (the process is healthy), readiness is
        #: NOT_SERVING until lease expiry or /promote flips the role.
        self.standby = False
        self.backend = backend  # FailoverBackend | None

    def _ready(self) -> bool:
        if not self.serving or self.recovering or self.standby:
            return False
        backend = self.backend
        return not (backend is not None and getattr(backend, "degraded", False))

    async def check(self, request, context):
        del context
        st = self.pb2.HealthCheckResponse.ServingStatus
        service = getattr(request, "service", "") or ""
        if service in (READINESS_SERVICE, SERVICE_NAME):
            ok = self._ready()
        else:
            ok = self.serving
        return self.pb2.HealthCheckResponse(
            status=st.SERVING if ok else st.NOT_SERVING
        )

    def handler(self) -> grpc.GenericRpcHandler:
        return grpc.method_handlers_generic_handler(
            "grpc.health.v1.Health",
            {
                "Check": grpc.unary_unary_rpc_method_handler(
                    self.check,
                    request_deserializer=self.pb2.HealthCheckRequest.FromString,
                    response_serializer=self.pb2.HealthCheckResponse.SerializeToString,
                )
            },
        )


def _add_health_service(server, backend=None) -> "HealthService":
    health = HealthService(backend=backend)
    server.add_generic_rpc_handlers((health.handler(),))
    return health
