"""gRPC AuthService implementation (asyncio).

Behavior parity with the reference service (``src/verifier/service.rs``):
identical validation limits and error strings, opaque "Authentication
failed" for anything secret-adjacent, challenge consumption BEFORE
verification (replay cannot retry a failed proof), per-item results for the
batch RPCs, 32-byte challenge ids and hex session tokens, and the same
metric names. The gRPC plumbing is hand-wired through grpcio's generic
handler API because the protoc gRPC plugin is unavailable (see proto.py).
"""

from __future__ import annotations

import asyncio
import time

import grpc

from .. import errors
from ..admission import RETRY_PUSHBACK_KEY, client_key
from ..core.ristretto import Ristretto255
from ..core.rng import SecureRng
from ..core.transcript import Transcript
from ..observability import current_context, traced_rpc
from ..protocol.batch import BatchVerifier, VerifierBackend
from ..protocol.gadgets import Parameters, Proof, Statement
from ..protocol.verifier import Verifier
from . import batching, metrics
from .config import RateLimiter, RateLimitExceeded
from .proto import SERVICE_NAME, load_pb2, method_types
from .state import ServerState, UserData
from .state import user_id_error as _user_id_error

MAX_ELEMENT_WIRE = 4096
MAX_CHALLENGE_ID = 64
MAX_PROOF_WIRE = 8192
MAX_BATCH = 1000

#: Pushback advertised on RESOURCE_EXHAUSTED paths that have no better
#: estimate (no admission controller / no queue signal): one client
#: backoff's worth, so uninstrumented retry loops still spread out.
DEFAULT_RETRY_AFTER_S = 0.05


class AuthServiceImpl:
    """The five RPCs (service.rs:59-617 twin)."""

    def __init__(
        self,
        state: ServerState,
        rate_limiter: RateLimiter,
        backend: VerifierBackend | None = None,
        batcher=None,
        admission=None,
        replica=None,
    ):
        self.state = state
        self.rate_limiter = rate_limiter
        self.backend = backend
        self.batcher = batcher  # DynamicBatcher | None (TPU serving path)
        self.admission = admission  # AdmissionController | None
        self.replica = replica  # StandbyReplica | None (replication standby)
        self.pb2 = load_pb2()
        self.rng = SecureRng()
        # inline-verify concurrency: 2 lets one RPC's Python overlap
        # another's GIL-released crypto without unbounded to_thread
        # workers each spawning a cpu-wide native pool (crypto-vs-crypto
        # oversubscription under many concurrent batch RPCs)
        self._inline_verify = asyncio.Semaphore(2)

    # --- helpers ---

    async def _abort_exhausted(self, context, msg: str, retry_after_s: float):
        """RESOURCE_EXHAUSTED carrying ``cpzk-retry-after-ms`` trailing
        metadata (gRFC A6 server pushback) — EVERY shed path goes through
        here, not only admission rejections, so a bare 'try again
        whenever' rejection no longer exists."""
        ms = max(0, int(round(retry_after_s * 1000.0)))
        md = ((RETRY_PUSHBACK_KEY, str(ms)),)
        try:
            await context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED, msg, trailing_metadata=md
            )
        except TypeError:  # hand-rolled test context without the kwarg
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, msg)

    def _pushback_s(self, default: float = DEFAULT_RETRY_AFTER_S) -> float:
        """Queue-drain-sized pushback when a controller is wired, else
        ``default``."""
        if self.admission is not None:
            return self.admission.retry_after_s()
        return default

    async def _admit(self, context, rpc: str) -> None:
        """Full admission stack for one RPC: the global token bucket
        (backstop), then the per-client keyed bucket and the adaptive
        priority threshold.  Rejections abort RESOURCE_EXHAUSTED with
        retry pushback.  A replication standby that has not been promoted
        refuses every auth RPC outright — its state is a replica of the
        primary's, and writes on it would fork history."""
        if self.replica is not None and self.replica.role != "primary":
            await context.abort(
                grpc.StatusCode.UNAVAILABLE,
                "standby replica: not promoted (writes go to the primary)",
            )
        try:
            await self.rate_limiter.check_rate_limit()
        except RateLimitExceeded as e:
            metrics.counter("admission.shed.global").inc()
            await self._abort_exhausted(
                context, "Rate limit exceeded",
                getattr(e, "retry_after_s", 0.0) or DEFAULT_RETRY_AFTER_S,
            )
        if self.admission is None:
            return
        rejection = self.admission.admit(rpc, client_key(context))
        if rejection is not None:
            await self._abort_exhausted(
                context, rejection.message, rejection.retry_after_s
            )

    @staticmethod
    async def _validate_user_id(user_id: str, context) -> None:
        msg = _user_id_error(user_id)
        if msg is not None:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, msg)

    @staticmethod
    def _request_context(context):
        """The decorator-minted :class:`RequestContext` of this RPC (trace
        id + absolute deadline), or a fresh one when the handler was
        invoked outside ``traced_rpc`` (hand-rolled test harnesses)."""
        rctx = current_context.get()
        if rctx is None:
            from ..observability import RequestContext, rpc_deadline

            rctx = RequestContext.from_grpc(
                context, deadline=rpc_deadline(context)
            )
        return rctx

    def _parse_statement(self, y1_bytes: bytes, y2_bytes: bytes) -> Statement:
        """Shared register-path statement validation; raises errors.Error
        with the reference's message prefixes."""
        try:
            y1 = Ristretto255.element_from_bytes(y1_bytes)
        except errors.Error as e:
            raise errors.InvalidParams(f"Invalid y1: {e}") from None
        try:
            y2 = Ristretto255.element_from_bytes(y2_bytes)
        except errors.Error as e:
            raise errors.InvalidParams(f"Invalid y2: {e}") from None
        statement = Statement(y1, y2)
        try:
            statement.validate()
        except errors.Error as e:
            raise errors.InvalidParams(f"Invalid statement: {e}") from None
        if Ristretto255.is_identity(y1) or Ristretto255.is_identity(y2):
            raise errors.InvalidParams("Statement contains identity elements")
        return statement

    # --- RPCs ---

    # requests/success/failure counters and the duration histogram for
    # every RPC live in the traced_rpc decorator (one lifecycle, no
    # skipped .observe() on early-abort paths); handler bodies keep only
    # their domain-specific counters.

    @traced_rpc("Register", "auth.register")
    async def register(self, request, context):
        await self._admit(context, "Register")
        await self._validate_user_id(request.user_id, context)

        if not request.y1 or not request.y2:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "Empty y1 or y2 values")
        if len(request.y1) > MAX_ELEMENT_WIRE or len(request.y2) > MAX_ELEMENT_WIRE:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "y1 or y2 values too large")

        try:
            statement = self._parse_statement(request.y1, request.y2)
        except errors.Error as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))

        try:
            await self.state.register_user(
                UserData(
                    user_id=request.user_id,
                    statement=statement,
                    registered_at=int(time.time()),
                )
            )
        except errors.Error as e:
            await context.abort(grpc.StatusCode.ALREADY_EXISTS, f"Registration failed: {e}")

        return self.pb2.RegistrationResponse(
            success=True,
            message=f"User '{request.user_id}' registered successfully",
        )

    @traced_rpc("RegisterBatch", "auth.register_batch")
    async def register_batch(self, request, context):
        await self._admit(context, "RegisterBatch")

        n = len(request.user_ids)
        if n == 0:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "Empty batch")
        if n != len(request.y1_values) or n != len(request.y2_values):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "Mismatched array lengths in batch request"
            )
        if n > MAX_BATCH:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"Batch size exceeds maximum limit of {MAX_BATCH}",
            )
        metrics.counter("auth.register_batch.users_count").inc(n)

        results = []
        for i in range(n):
            user_id = request.user_ids[i]
            y1b, y2b = request.y1_values[i], request.y2_values[i]

            msg = _user_id_error(user_id)
            if msg is None:
                if not y1b or not y2b:
                    msg = f"Empty y1 or y2 values for user {i}"
                elif len(y1b) > MAX_ELEMENT_WIRE or len(y2b) > MAX_ELEMENT_WIRE:
                    msg = f"y1 or y2 values too large for user {i}"
            if msg is not None:
                results.append(self.pb2.RegistrationResult(success=False, message=msg))
                metrics.counter("auth.register_batch.individual_failure").inc()
                continue

            try:
                statement = self._parse_statement(y1b, y2b)
                await self.state.register_user(
                    UserData(
                        user_id=user_id,
                        statement=statement,
                        registered_at=int(time.time()),
                    )
                )
            except errors.Error as e:
                text = str(e)
                if "already registered" in text or "capacity" in text:
                    text = f"Registration failed: {text}"
                results.append(self.pb2.RegistrationResult(success=False, message=text))
                metrics.counter("auth.register_batch.individual_failure").inc()
                continue

            results.append(
                self.pb2.RegistrationResult(
                    success=True,
                    message=f"User '{user_id}' registered successfully",
                )
            )
            metrics.counter("auth.register_batch.individual_success").inc()

        return self.pb2.BatchRegistrationResponse(results=results)

    @traced_rpc("CreateChallenge", "auth.challenge")
    async def create_challenge(self, request, context):
        await self._admit(context, "CreateChallenge")
        await self._validate_user_id(request.user_id, context)

        user = await self.state.get_user(request.user_id)
        if user is None:
            await context.abort(
                grpc.StatusCode.NOT_FOUND, f"User '{request.user_id}' not found"
            )

        # the id carries the owning user's shard index in byte 0, so
        # VerifyProof routes straight to the shard that issued it
        challenge_id = self.state.tag_challenge_id(
            user.user_id, self.rng.fill_bytes(32)
        )
        try:
            expires_at = await self.state.create_challenge(user.user_id, challenge_id)
        except errors.Error as e:
            # per-user challenge-cap overload: pushback rides along like
            # every other RESOURCE_EXHAUSTED (satellite fix)
            await self._abort_exhausted(
                context, f"Challenge creation failed: {e}", self._pushback_s()
            )

        return self.pb2.ChallengeResponse(challenge_id=challenge_id, expires_at=expires_at)

    @traced_rpc("VerifyProof", "auth.verify")
    async def verify_proof(self, request, context):
        await self._admit(context, "VerifyProof")
        await self._validate_user_id(request.user_id, context)

        msg = _proof_args_error(request.challenge_id, request.proof)
        if msg is not None:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, msg)

        try:
            challenge = await self.state.consume_challenge(request.challenge_id)
        except errors.Error:
            await context.abort(grpc.StatusCode.PERMISSION_DENIED, "Authentication failed")
        if challenge.user_id != request.user_id:
            await context.abort(grpc.StatusCode.PERMISSION_DENIED, "Authentication failed")

        user = await self.state.get_user(request.user_id)
        if user is None:
            await context.abort(grpc.StatusCode.PERMISSION_DENIED, "Authentication failed")

        try:
            proof = Proof.from_bytes(request.proof)
        except errors.Error as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"Invalid proof: {e}")

        if self.batcher is not None:
            # TPU serving path: coalesce with concurrent RPCs into one
            # device batch; per-entry result has identical semantics
            rctx = self._request_context(context)
            try:
                verify_err = await self.batcher.submit(
                    Parameters.new(), user.statement, proof,
                    bytes(request.challenge_id),
                    deadline=rctx.deadline,
                    trace_id=rctx.trace_id,
                )
            except batching.QueueFull:
                await self._abort_exhausted(
                    context, "Server overloaded", self._pushback_s()
                )
            except batching.DeadlineExceeded:
                await context.abort(
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                    "Deadline expired before verification",
                )
        else:
            verifier = Verifier(Parameters.new(), user.statement)
            transcript = Transcript()
            transcript.append_context(request.challenge_id)
            try:
                verifier.verify_with_transcript(proof, transcript)
                verify_err = None
            except errors.Error as e:
                verify_err = e
        if verify_err is not None:
            await context.abort(
                grpc.StatusCode.PERMISSION_DENIED, f"Verification failed: {verify_err}"
            )

        # shard-tagged like the challenge id: validate/revoke route
        # straight to the issuing shard
        token = self.state.tag_session_token(
            request.user_id, self.rng.fill_bytes(32).hex()
        )
        try:
            await self.state.create_session(token, request.user_id)
        except errors.Error as e:
            await context.abort(grpc.StatusCode.INTERNAL, f"Failed to create session: {e}")

        return self.pb2.VerificationResponse(
            success=True,
            message=f"User '{request.user_id}' authenticated successfully",
            session_token=token,
        )

    @traced_rpc("VerifyProofBatch", "auth.verify_batch")
    async def verify_proof_batch(self, request, context):
        await self._admit(context, "VerifyProofBatch")

        n = len(request.user_ids)
        if n == 0:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "Empty batch")
        if n != len(request.challenge_ids) or n != len(request.proofs):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "Mismatched array lengths in batch request"
            )
        if n > MAX_BATCH:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"Batch size exceeds maximum limit of {MAX_BATCH}",
            )
        metrics.counter("auth.verify_batch.proofs_count").inc(n)

        # materialize the repeated fields once: protobuf repeated-field
        # __getitem__ costs add up over 3 accesses x 1000 items
        user_ids = list(request.user_ids)
        challenge_ids = list(request.challenge_ids)
        proof_wires = list(request.proofs)

        batch = BatchVerifier(backend=self.backend)
        contexts: list[str | None] = []  # user_id once queued for verify, else None
        error_msgs: list[str] = []
        # stage 1: argument validation (no awaits)
        staged: list[int] = []  # indices that passed arg validation
        for i in range(n):
            msg = _user_id_error(user_ids[i])
            if msg is None:
                msg = _proof_args_error(challenge_ids[i], proof_wires[i], index=i)
            contexts.append(None)
            error_msgs.append(msg or "")
            if msg is None:
                staged.append(i)

        # stage 2: consume BEFORE verification — single-use even on failure
        # (service.rs:478; docs/protocol.md:174-176).  Bulk state calls:
        # one lock acquisition for all n consumes (and one for the user
        # lookups) instead of 2n event-loop round-trips.
        challenges = await self.state.consume_challenges(
            [challenge_ids[i] for i in staged])
        users = await self.state.get_users(
            [user_ids[i] for i in staged])
        live: list[tuple[int, UserData]] = []
        for i, challenge, user in zip(staged, challenges, users, strict=True):
            if (
                challenge is None
                or challenge.user_id != user_ids[i]
                or user is None
            ):
                error_msgs[i] = "Authentication failed"
                continue
            live.append((i, user))
        # Bulk parse: one native validation pass for the whole batch,
        # commitment point decodes DEFERRED on every path — the
        # batch-verify stage decodes them anyway (BatchVerifier settles
        # failures with the exact parse error).  On the batcher path the
        # deferred screening runs in BatchVerifier.prepare_batch on the
        # dispatch lane's prep thread, overlapped with the previous
        # batch's device compute, so the decode cost leaves the RPC's
        # serial path entirely.
        parsed = Proof.from_bytes_batch(
            [proof_wires[i] for i, _ in live],
            defer_point_validation=True,
        )
        params = Parameters.new()  # shared generators: one instance per RPC
        for (i, user), proof in zip(live, parsed, strict=True):
            if isinstance(proof, errors.Error):
                error_msgs[i] = f"Invalid proof: {proof}"
                continue
            try:
                batch.add_with_context(
                    params, user.statement, proof, bytes(challenge_ids[i]),
                )
            except errors.Error as e:
                error_msgs[i] = f"Failed to add proof to batch: {e}"
                continue
            contexts[i] = user_ids[i]

        batch_results: list = []
        if len(batch) > 0:
            try:
                if self.batcher is not None:
                    # one bulk enqueue; all-or-nothing on backpressure, so
                    # no orphaned sibling submits to drain on QueueFull.
                    # All entries share this RPC's deadline: past it the
                    # batcher sheds them instead of burning device time.
                    rctx = self._request_context(context)
                    for entry in batch.entries:
                        entry.deadline = rctx.deadline
                        entry.trace_id = rctx.trace_id
                    batch_results = await self.batcher.submit_many(batch.entries)
                else:
                    # worker thread, not the event loop: the native verify
                    # releases the GIL, so a concurrent RPC's Python
                    # (parse, state ops, response build) overlaps this
                    # batch's crypto instead of queueing behind ~100ms of
                    # blocked loop — and health checks stay responsive
                    async with self._inline_verify:
                        batch_results = await asyncio.to_thread(
                            batch.verify, self.rng)
            except batching.QueueFull:
                await self._abort_exhausted(
                    context, "Server overloaded", self._pushback_s()
                )
            except batching.DeadlineExceeded:
                await context.abort(
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                    "Deadline expired before verification",
                )
            except errors.Error as e:
                await context.abort(grpc.StatusCode.INTERNAL, f"Batch verification failed: {e}")

        # session issuance for verified items — one bulk mint (single lock,
        # single CSPRNG draw sliced into per-item tokens)
        verified: list[int] = []
        tokens: dict[int, str] = {}
        batch_index = 0
        verify_errs: dict[int, object] = {}
        for i in range(n):
            if contexts[i] is None:
                continue
            verify_errs[i] = batch_results[batch_index]
            batch_index += 1
            if verify_errs[i] is None:
                verified.append(i)
        token_pool = self.rng.fill_bytes(32 * len(verified)).hex()
        for k, i in enumerate(verified):
            tokens[i] = self.state.tag_session_token(
                contexts[i], token_pool[64 * k: 64 * (k + 1)]
            )
        session_errs = await self.state.create_sessions(
            [(tokens[i], contexts[i]) for i in verified])
        session_err_by_index = dict(zip(verified, session_errs, strict=True))

        results = []
        n_failure = 0
        Result = self.pb2.VerificationResult
        for i in range(n):
            user_id = contexts[i]
            if user_id is None:
                results.append(Result(success=False, message=error_msgs[i]))
                n_failure += 1
                continue
            verr = verify_errs[i]
            if verr is not None:
                # a deferred-parse proof whose commitment wire failed to
                # decode reports the exact parse-time message; genuine
                # verification failures stay opaque (service.rs:528)
                if isinstance(verr, errors.InvalidProofEncoding):
                    msg = f"Invalid proof: {verr}"
                else:
                    msg = "Authentication failed"
                results.append(Result(success=False, message=msg))
                n_failure += 1
                continue
            serr = session_err_by_index[i]
            if serr is not None:
                results.append(Result(
                    success=False, message=f"Failed to create session: {serr}"
                ))
                n_failure += 1
                continue
            results.append(Result(
                success=True,
                message=f"User '{user_id}' authenticated successfully",
                session_token=tokens[i],
            ))
        if n_failure:
            metrics.counter("auth.verify_batch.individual_failure").inc(n_failure)
        if n - n_failure:
            metrics.counter("auth.verify_batch.individual_success").inc(n - n_failure)

        return self.pb2.BatchVerificationResponse(results=results)


def _proof_args_error(challenge_id: bytes, proof: bytes, index: int | None = None) -> str | None:
    sfx = "" if index is None else f" for proof {index}"
    if not challenge_id:
        return f"Empty challenge ID{sfx}"
    if len(challenge_id) > MAX_CHALLENGE_ID:
        return f"Challenge ID too long{sfx}"
    if not proof:
        return f"Empty proof{sfx}" if index is None else f"Empty proof {index}"
    if len(proof) > MAX_PROOF_WIRE:
        return f"Proof too large{sfx}" if index is None else f"Proof {index} too large"
    return None


def make_generic_handler(service: AuthServiceImpl) -> grpc.GenericRpcHandler:
    """Register the five RPCs without generated *_pb2_grpc stubs."""
    pb2 = service.pb2
    types = method_types(pb2)
    impl = {
        "Register": service.register,
        "RegisterBatch": service.register_batch,
        "CreateChallenge": service.create_challenge,
        "VerifyProof": service.verify_proof,
        "VerifyProofBatch": service.verify_proof_batch,
    }
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            impl[name],
            request_deserializer=types[name][0].FromString,
            response_serializer=types[name][1].SerializeToString,
        )
        for name in impl
    }
    return grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)


async def serve(
    state: ServerState,
    rate_limiter: RateLimiter,
    host: str = "127.0.0.1",
    port: int = 50051,
    backend: VerifierBackend | None = None,
    batcher=None,
    tls: tuple[bytes, bytes] | None = None,
    admission=None,
    replica=None,
):
    """Build and start an aio server; returns (server, bound_port).

    ``tls`` is an optional (private_key_pem, cert_chain_pem) pair — wired
    for real, unlike the reference where validated TLS settings never reach
    the transport (SURVEY.md §3.3).  ``batcher`` is an optional started-here
    :class:`~cpzk_tpu.server.batching.DynamicBatcher` routing verification
    through the TPU data plane; it is exposed as ``server.batcher`` so the
    daemon can drain it on shutdown.  ``admission`` is an optional
    :class:`~cpzk_tpu.admission.AdmissionController` gating every RPC
    (per-client fairness + priority shedding + retry pushback).
    ``replica`` is an optional
    :class:`~cpzk_tpu.replication.StandbyReplica`: its ReplicationService
    handler is registered alongside the auth service, readiness reports
    NOT_SERVING until promotion, and every auth RPC aborts UNAVAILABLE
    while the node is still a standby.
    """
    server = grpc.aio.server()
    service = AuthServiceImpl(
        state, rate_limiter, backend=backend, batcher=batcher,
        admission=admission, replica=replica,
    )
    server.add_generic_rpc_handlers((make_generic_handler(service),))
    if replica is not None:
        server.add_generic_rpc_handlers((replica.handler(),))
    health = _add_health_service(server, backend=backend)
    if replica is not None:
        health.standby = replica.role != "primary"
        replica.health = health  # promotion flips readiness to SERVING
    server.health = health  # for shutdown: server.health.serving = False
    server.batcher = batcher
    server.admission = admission
    server.replica = replica
    if batcher is not None:
        batcher.start()
    addr = f"{host}:{port}"
    if tls is not None:
        creds = grpc.ssl_server_credentials([tls])
        bound = server.add_secure_port(addr, creds)
    else:
        bound = server.add_insecure_port(addr)
    await server.start()
    return server, bound


#: ``HealthCheckRequest.service`` values that select the READINESS view
#: (the auth service name also works, for LB configs that probe it).
READINESS_SERVICE = "readiness"


class HealthService:
    """Standard gRPC health protocol, hand-wired (tonic-health twin,
    bin/server.rs:208-211), split into liveness and readiness views:

    - ``service=""`` — **liveness**: SERVING while the process is up and
      not draining (``serving = False`` flips it at graceful shutdown,
      bin/server.rs:420-422).  An open failover breaker does NOT flip
      liveness — the CPU fallback still answers correctly.
    - ``service="readiness"`` (or the auth service name) — **readiness**:
      additionally NOT_SERVING while WAL recovery/replay is still running
      (``recovering``), while the failover breaker holds the backend
      degraded, and while the node is an unpromoted replication standby
      (``standby`` — lease-based promotion flips it to SERVING), so load
      balancers stop routing to a replica that would only shed or answer
      at fallback speed, without restart-looping it.
    """

    def __init__(self, backend=None):
        from .proto import load_health_pb2

        self.pb2 = load_health_pb2()
        self.serving = True
        #: True while boot-time WAL recovery/replay runs (set by whoever
        #: drives recovery with the listener already up; the stock daemon
        #: recovers before binding, where "not ready" is simply
        #: connection-refused).
        self.recovering = False
        #: True while this node is an unpromoted replication standby —
        #: liveness stays SERVING (the process is healthy), readiness is
        #: NOT_SERVING until lease expiry or /promote flips the role.
        self.standby = False
        self.backend = backend  # FailoverBackend | None

    def _ready(self) -> bool:
        if not self.serving or self.recovering or self.standby:
            return False
        backend = self.backend
        return not (backend is not None and getattr(backend, "degraded", False))

    async def check(self, request, context):
        del context
        st = self.pb2.HealthCheckResponse.ServingStatus
        service = getattr(request, "service", "") or ""
        if service in (READINESS_SERVICE, SERVICE_NAME):
            ok = self._ready()
        else:
            ok = self.serving
        return self.pb2.HealthCheckResponse(
            status=st.SERVING if ok else st.NOT_SERVING
        )

    def handler(self) -> grpc.GenericRpcHandler:
        return grpc.method_handlers_generic_handler(
            "grpc.health.v1.Health",
            {
                "Check": grpc.unary_unary_rpc_method_handler(
                    self.check,
                    request_deserializer=self.pb2.HealthCheckRequest.FromString,
                    response_serializer=self.pb2.HealthCheckResponse.SerializeToString,
                )
            },
        )


def _add_health_service(server, backend=None) -> "HealthService":
    health = HealthService(backend=backend)
    server.add_generic_rpc_handlers((health.handler(),))
    return health
