"""Serving plane: the gRPC password-less authentication system.

Re-design of the reference's server stack (SURVEY.md §2.1 #10-#14) on
asyncio grpcio: same ``auth.proto`` wire contract, same validation limits
and state-machine semantics (single-use challenges, TTLs, per-user caps),
with the lock-order hazard of the reference's five-lock state store fixed
by a single asyncio lock (SURVEY.md §5 race-detection note).
"""

from .config import RateLimiter, ServerConfig
from .state import ChallengeData, ServerState, SessionData, UserData

__all__ = [
    "ChallengeData",
    "RateLimiter",
    "ServerConfig",
    "ServerState",
    "SessionData",
    "UserData",
]
