"""RPC metrics: counters, gauges, and duration histograms with Prometheus
export.

The reference instruments every RPC through the ``metrics`` facade with a
``metrics-exporter-prometheus`` scrape endpoint (``service.rs`` passim,
``bin/server.rs:194-206``). Same metric names here (dots become underscores
in the Prometheus exposition, matching the exporter's convention), backed by
``prometheus_client`` when importable and by inert stand-ins otherwise so
the service code never branches — and so :func:`read` /
:func:`read_histogram` return the same numbers against either backing.

Observability-PR additions on the original flat facade:

- **labels**: ``counter(name, labelnames=("rpc", "outcome"))`` returns a
  labeled family; call ``.labels(rpc=..., outcome=...)`` for a child.
  The no-prometheus backing implements the same ``labels`` API.
- **histogram reads**: histograms track observation count and sum on both
  backings; ``read(name, "h")`` returns the sum (total seconds) and
  :func:`read_histogram` returns ``(count, sum)`` — tests and the admin
  REPL can assert on durations, not just counters.
- **buckets**: histogram buckets default to a schedule tuned for TPU
  dispatch latencies (sub-ms host stages through multi-second cold
  compiles) and are overridable per-histogram or process-wide via
  :func:`set_default_buckets` (``observability.latency_buckets_ms``).
- **introspection**: :func:`registered` lists (kind, name) pairs for the
  docs-inventory drift guard in CI.
"""

from __future__ import annotations

try:
    from prometheus_client import Counter as _PCounter
    from prometheus_client import Gauge as _PGauge
    from prometheus_client import Histogram as _PHistogram
    from prometheus_client import start_http_server as _start_http_server

    HAVE_PROMETHEUS = True
except ImportError:  # pragma: no cover
    HAVE_PROMETHEUS = False

_REGISTRY: dict[str, object] = {}

#: Histogram bucket upper bounds (seconds) tuned for the TPU serving
#: plane: 100 us resolution through the host stages, ms resolution
#: through device dispatch, coarse tail for cold-compile outliers.
DEFAULT_LATENCY_BUCKETS: tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)

_default_buckets: tuple[float, ...] = DEFAULT_LATENCY_BUCKETS


def set_default_buckets(buckets) -> None:
    """Process-wide default for histograms created AFTER this call (the
    ``observability.latency_buckets_ms`` config knob resolves here)."""
    global _default_buckets
    _default_buckets = tuple(sorted(float(b) for b in buckets))


def _sanitize(name: str) -> str:
    return name.replace(".", "_")


class _Cell:
    """Minimal value holder mirroring prometheus_client's ``_value`` API so
    :func:`read` works identically against either backing."""

    def __init__(self) -> None:
        self._v = 0.0

    def get(self) -> float:
        return self._v


class _NoopMetric:
    """Stand-in without prometheus_client: no exposition endpoint, but
    counts, gauge values, histogram observation count/sum, AND labeled
    children are all tracked, so :func:`read` / :func:`read_histogram`
    (REPL ``/status``, chaos + observability tests) see identical numbers
    either way."""

    def __init__(self, labelnames: tuple[str, ...] = ()) -> None:
        self._labelnames = tuple(labelnames)
        self._children: dict[tuple, "_NoopMetric"] = {}
        self._value = _Cell()
        self._sum = _Cell()
        self._count = _Cell()

    def labels(self, *labelvalues, **labelkwargs) -> "_NoopMetric":
        if labelkwargs:
            key = tuple(str(labelkwargs[k]) for k in self._labelnames)
        else:
            key = tuple(str(v) for v in labelvalues)
        child = self._children.get(key)
        if child is None:
            child = self._children[key] = _NoopMetric()
        return child

    def inc(self, amount: float = 1.0) -> None:
        self._value._v += amount

    def observe(self, value: float) -> None:
        # count/sum accumulate exactly like a real histogram child, so the
        # no-prometheus fallback is observably equivalent (satellite fix:
        # this used to discard the value)
        self._count._v += 1.0
        self._sum._v += float(value)

    def set(self, value: float) -> None:
        self._value._v = float(value)


def counter(name: str, labelnames: tuple[str, ...] = ()):
    """counter!("auth.register.requests") twin; with ``labelnames`` the
    result is a labeled family — use ``.labels(...)`` for children."""
    key = "c:" + name
    if key not in _REGISTRY:
        if HAVE_PROMETHEUS:
            _REGISTRY[key] = _PCounter(
                _sanitize(name), f"counter {name}", tuple(labelnames)
            )
        else:
            _REGISTRY[key] = _NoopMetric(tuple(labelnames))
    return _REGISTRY[key]


def histogram(
    name: str,
    labelnames: tuple[str, ...] = (),
    buckets: tuple[float, ...] | None = None,
):
    """histogram!("auth.register.duration") twin.  ``buckets`` overrides
    the process default (see :func:`set_default_buckets`) at creation
    time; both are ignored on the no-prometheus backing, which tracks
    count/sum only."""
    key = "h:" + name
    if key not in _REGISTRY:
        if HAVE_PROMETHEUS:
            bounds = tuple(buckets if buckets is not None else _default_buckets)
            if not bounds or bounds[-1] != float("inf"):
                bounds = bounds + (float("inf"),)
            _REGISTRY[key] = _PHistogram(
                _sanitize(name),
                f"histogram {name}",
                tuple(labelnames),
                buckets=bounds,
            )
        else:
            _REGISTRY[key] = _NoopMetric(tuple(labelnames))
    return _REGISTRY[key]


def gauge(name: str, labelnames: tuple[str, ...] = ()):
    """TPU serving gauges (queue depth, batch fill ratio, ...) — the
    additions VERDICT r1 asked for on top of the reference's counters."""
    key = "g:" + name
    if key not in _REGISTRY:
        if HAVE_PROMETHEUS:
            _REGISTRY[key] = _PGauge(
                _sanitize(name), f"gauge {name}", tuple(labelnames)
            )
        else:
            _REGISTRY[key] = _NoopMetric(tuple(labelnames))
    return _REGISTRY[key]


def _hist_count_sum(metric) -> tuple[float, float]:
    """(observation count, value sum) of a histogram child on either
    backing."""
    buckets = getattr(metric, "_buckets", None)
    if buckets is not None:  # prometheus_client backing
        return (
            float(sum(b.get() for b in buckets)),
            float(metric._sum.get()),
        )
    return float(metric._count.get()), float(metric._sum.get())


def _resolve(name: str, kind: str, labels: dict | None):
    metric = _REGISTRY.get(f"{kind}:{name}")
    if metric is not None and labels:
        try:
            metric = metric.labels(**labels)
        except Exception:  # unknown label set: treated as never-touched
            return None
    return metric


def read(name: str, kind: str = "c", labels: dict | None = None) -> float:
    """Current value of a counter (``kind="c"``), gauge (``"g"``), or
    histogram (``"h"`` — the observation SUM, so duration totals are
    assertable) — 0.0 when the metric was never touched.  ``labels``
    selects a child of a labeled family.  In-process observability seam
    for the admin REPL and the test suites; Prometheus exposition remains
    the operator surface."""
    metric = _resolve(name, kind, labels)
    if metric is None:
        return 0.0
    if kind == "h":
        return _hist_count_sum(metric)[1]
    try:
        return float(metric._value.get())  # type: ignore[union-attr]
    except AttributeError:  # pragma: no cover - unexpected backing object
        return 0.0


def read_histogram(
    name: str, labels: dict | None = None
) -> tuple[float, float]:
    """(observation count, value sum) of a histogram — (0.0, 0.0) when
    never touched.  Identical on both backings."""
    metric = _resolve(name, "h", labels)
    if metric is None:
        return (0.0, 0.0)
    return _hist_count_sum(metric)


def registered() -> list[tuple[str, str]]:
    """Sorted (kind, name) pairs of every metric created so far — the
    seam the CI drift guard uses to cross-check the docs inventory."""
    out = []
    for key in _REGISTRY:
        kind, _, name = key.partition(":")
        out.append((kind, name))
    return sorted(out)


def start_exporter(host: str, port: int) -> bool:
    """Serve the Prometheus scrape endpoint (bin/server.rs:194-206 twin).

    Returns False when prometheus_client is unavailable — the daemon then
    serves :func:`render_exposition` through the ops plane instead (and
    says so loudly), rather than silently leaving a configured metrics
    port with no listener.
    """
    if not HAVE_PROMETHEUS:
        return False
    _start_http_server(port, addr=host)
    return True


# -- text exposition (the ops plane's /metrics body) --------------------------


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _format_sample(name: str, labels: dict, value: float) -> str:
    if labels:
        body = ",".join(
            f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
        )
        return f"{name}{{{body}}} {value}"
    return f"{name} {value}"


def _noop_samples(kind: str, name: str, child: "_NoopMetric",
                  labels: dict) -> list[str]:
    if kind == "c":
        return [_format_sample(name + "_total", labels, child._value.get())]
    if kind == "g":
        return [_format_sample(name, labels, child._value.get())]
    count, total = child._count.get(), child._sum.get()
    return [
        _format_sample(
            name + "_bucket", {**labels, "le": "+Inf"}, count
        ),
        _format_sample(name + "_count", labels, count),
        _format_sample(name + "_sum", labels, total),
    ]


def render_exposition() -> str:
    """Prometheus/OpenMetrics-style text exposition rendered from THIS
    facade's registry, on either backing.

    With ``prometheus_client`` present, each metric's own ``collect()``
    supplies the samples (full bucket vectors included); without it, the
    no-op backing renders the counts/gauges/histogram count+sum it
    already tracks — so the family set is identical either way, and the
    no-prometheus fallback finally has real exposition instead of
    nothing (the ops plane's ``/metrics`` serves this string)."""
    lines: list[str] = []
    for key in sorted(_REGISTRY, key=lambda k: k.partition(":")[2]):
        kind, _, name = key.partition(":")
        metric = _REGISTRY[key]
        sname = _sanitize(name)
        kind_word = {"c": "counter", "g": "gauge", "h": "histogram"}[kind]
        lines.append(f"# HELP {sname} {kind_word} {name}")
        lines.append(f"# TYPE {sname} {kind_word}")
        if HAVE_PROMETHEUS:
            for family in metric.collect():  # type: ignore[attr-defined]
                for s in family.samples:
                    lines.append(
                        _format_sample(s.name, dict(s.labels), s.value)
                    )
        else:
            noop: _NoopMetric = metric  # type: ignore[assignment]
            if noop._labelnames:
                for key_values, child in sorted(noop._children.items()):
                    labels = dict(
                        zip(noop._labelnames, key_values, strict=True)
                    )
                    lines.extend(_noop_samples(kind, sname, child, labels))
            else:
                lines.extend(_noop_samples(kind, sname, noop, {}))
    lines.append("# EOF")
    return "\n".join(lines) + "\n"
