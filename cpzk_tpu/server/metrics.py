"""RPC metrics: counters + duration histograms with Prometheus export.

The reference instruments every RPC through the ``metrics`` facade with a
``metrics-exporter-prometheus`` scrape endpoint (``service.rs`` passim,
``bin/server.rs:194-206``). Same metric names here (dots become underscores
in the Prometheus exposition, matching the exporter's convention), backed by
``prometheus_client`` when importable and by inert no-ops otherwise so the
service code never branches.
"""

from __future__ import annotations

try:
    from prometheus_client import Counter as _PCounter
    from prometheus_client import Gauge as _PGauge
    from prometheus_client import Histogram as _PHistogram
    from prometheus_client import start_http_server as _start_http_server

    HAVE_PROMETHEUS = True
except ImportError:  # pragma: no cover
    HAVE_PROMETHEUS = False

_REGISTRY: dict[str, object] = {}


def _sanitize(name: str) -> str:
    return name.replace(".", "_")


class _Cell:
    """Minimal value holder mirroring prometheus_client's ``_value`` API so
    :func:`read` works identically against either backing."""

    def __init__(self) -> None:
        self._v = 0.0

    def get(self) -> float:
        return self._v


class _NoopMetric:
    """Inert stand-in without prometheus_client: no exposition endpoint,
    but values are still tracked so :func:`read` (REPL ``/status``, chaos
    tests) sees real numbers either way."""

    def __init__(self) -> None:
        self._value = _Cell()

    def inc(self, amount: float = 1.0) -> None:
        self._value._v += amount

    def observe(self, *_a) -> None:
        pass

    def set(self, value: float) -> None:
        self._value._v = float(value)


def counter(name: str):
    """counter!("auth.register.requests") twin."""
    key = "c:" + name
    if key not in _REGISTRY:
        if HAVE_PROMETHEUS:
            _REGISTRY[key] = _PCounter(_sanitize(name), f"counter {name}")
        else:
            _REGISTRY[key] = _NoopMetric()
    return _REGISTRY[key]


def histogram(name: str):
    """histogram!("auth.register.duration") twin."""
    key = "h:" + name
    if key not in _REGISTRY:
        if HAVE_PROMETHEUS:
            _REGISTRY[key] = _PHistogram(_sanitize(name), f"histogram {name}")
        else:
            _REGISTRY[key] = _NoopMetric()
    return _REGISTRY[key]


def gauge(name: str):
    """TPU serving gauges (queue depth, batch fill ratio, ...) — the
    additions VERDICT r1 asked for on top of the reference's counters."""
    key = "g:" + name
    if key not in _REGISTRY:
        if HAVE_PROMETHEUS:
            _REGISTRY[key] = _PGauge(_sanitize(name), f"gauge {name}")
        else:
            _REGISTRY[key] = _NoopMetric()
    return _REGISTRY[key]


def read(name: str, kind: str = "c") -> float:
    """Current value of a counter (``kind="c"``) or gauge (``"g"``) — 0.0
    when the metric was never touched.  In-process observability seam for
    the admin REPL and the chaos test suite; Prometheus exposition remains
    the operator surface."""
    metric = _REGISTRY.get(f"{kind}:{name}")
    if metric is None:
        return 0.0
    try:
        return float(metric._value.get())  # type: ignore[union-attr]
    except AttributeError:  # pragma: no cover - unexpected backing object
        return 0.0


def start_exporter(host: str, port: int) -> bool:
    """Serve the Prometheus scrape endpoint (bin/server.rs:194-206 twin).

    Returns False when prometheus_client is unavailable.
    """
    if not HAVE_PROMETHEUS:
        return False
    _start_http_server(port, addr=host)
    return True
