"""SO_REUSEPORT sharded ingest: N listener processes, one dispatch process.

The in-process listener pays the whole gRPC/HTTP/2 + parse + serialize
tax on the dispatch process's single event loop; past one busy core that
loop IS the serving ceiling (ROADMAP item 4).  With ``[server]
ingest_shards = N`` (N > 1) the daemon instead spawns N **ingest shard**
processes.  Each shard binds the public listener itself — gRPC's
``SO_REUSEPORT`` (on by default on Linux) lets every shard bind the same
``host:port`` and the kernel spreads incoming connections across them —
and runs the transport work: HTTP/2, request reads, the **native wire
parse** (the same ``server/wire.py`` parser the in-process path uses),
and response writes.  Parsed requests travel to the single
dispatch/state process over a unix-domain socket speaking the
proof-log's CRC-framed discipline (the exact ``length u32 | crc32 u32 |
payload`` header ``wal.iter_frames`` scans), where the REAL
``AuthServiceImpl`` handlers run against the one batcher/state plane —
so ingest scales with host cores the way PR 12 made the device plane
scale with chips.

Division of labor (and why admission lives where it does): the shards
own sockets and parse; **admission, priority shedding, and rate
limiting stay in the dispatch process**, where the batcher's queue
signals live and where the keyed buckets see every client exactly once
no matter which shard its connections hashed to.  That placement is
what makes the satellite-3 parity guarantee structural: a request
answers with byte-identical verdicts, trailers, and metrics whether it
entered in-process or through any shard.

Failure model: a shard is stateless — SIGKILL one and its open
connections reset (clients retry per their policy), the daemon keeps
serving through the remaining shards, and the supervisor respawns the
dead shard with exponential full-jitter backoff
(``ingest.shard.respawns``).  A shard that keeps dying trips the
crash-loop guard — N deaths in M seconds and the supervisor abandons it
(``ingest.shard.crashloop``, ``crashloop`` marker in /statusz) instead
of spinning forever on a doomed binary.  The dispatch process dying
takes the service down exactly like today.

``ingest_shards = 1`` never constructs any of this (spy-pinned): the
daemon binds in-process and the hot path is byte-identical to the
pre-shard code.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
import pickle
import random
import tempfile
import time

from ..durability.wal import (
    HEADER_BYTES,
    frame_crc_ok,
    frame_payload,
    unpack_frame_header,
)
from . import metrics

log = logging.getLogger("cpzk_tpu.server.ingest")

#: Frame payload cap: the largest legal gRPC request (4 MiB default
#: receive limit) plus pickle overhead, with headroom.  A garbage
#: length field must not make either side allocate gigabytes.
MAX_INGEST_FRAME = 64 << 20

#: Outstanding chunks a shard may forward per stream before waiting for
#: dispatch-side credits — keeps the parent-side queue bounded so gRPC's
#: own flow control (shard stops reading) pushes back on the sender.
STREAM_CREDITS = 8

#: RPCs the shards proxy (full method path -> unary/stream kind).
AUTH_SERVICE = "auth.AuthService"
HEALTH_SERVICE = "grpc.health.v1.Health"
UNARY_METHODS = (
    "Register", "RegisterBatch", "CreateChallenge",
    "VerifyProof", "VerifyProofBatch",
)
STREAM_METHOD = "VerifyProofStream"

#: Native-parse message kinds a shard ships pre-parsed ("v" payloads).
_WIRE_KINDS = {"CreateChallenge": 1, "VerifyProofBatch": 2,
               "VerifyProofStream": 3}


def pack_frame(payload: bytes) -> bytes:
    """One CRC-framed message — the WAL's exact header discipline, via
    the shared :func:`~cpzk_tpu.durability.wal.frame_payload` helper (one
    copy of the framing contract across WAL/proof-log/ingest; FRAME-001
    pins it)."""
    if len(payload) > MAX_INGEST_FRAME:
        raise ValueError(f"ingest frame exceeds {MAX_INGEST_FRAME} bytes")
    return frame_payload(payload)


async def read_frame(reader: asyncio.StreamReader) -> bytes | None:
    """Next frame payload, or None on clean EOF.  Raises ValueError on a
    corrupt header/CRC — the connection is then torn down (both sides
    treat the stream as append-only and unrecoverable past corruption,
    like a torn WAL tail)."""
    try:
        head = await reader.readexactly(HEADER_BYTES)
    except asyncio.IncompleteReadError:
        return None
    length, crc = unpack_frame_header(head)
    if length == 0 or length > MAX_INGEST_FRAME:
        raise ValueError(f"ingest frame length {length} out of bounds")
    payload = await reader.readexactly(length)
    if not frame_crc_ok(payload, crc):
        raise ValueError("ingest frame CRC mismatch")
    return payload


class _FrameWriter:
    """Serialized frame writes over one StreamWriter (many dispatcher
    tasks answer concurrently; interleaved partial writes would corrupt
    the framing)."""

    def __init__(self, writer: asyncio.StreamWriter):
        self._writer = writer
        self._lock = asyncio.Lock()

    async def send(self, msg: tuple) -> None:
        frame = pack_frame(pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL))
        async with self._lock:
            self._writer.write(frame)
            await self._writer.drain()

    def close(self) -> None:
        with contextlib.suppress(Exception):
            self._writer.close()


# ---------------------------------------------------------------------------
# dispatch-process side
# ---------------------------------------------------------------------------


class ShardAbort(Exception):
    """A handler called context.abort() on a shard-forwarded RPC."""

    def __init__(self, code, details: str, trailers):
        super().__init__(details)
        self.code = code
        self.details = details
        self.trailers = tuple(trailers or ())


class ShardContext:
    """The gRPC server-context surface the real handlers touch, backed by
    facts the shard forwarded (metadata, peer, deadline).  Hand-rolled
    contexts are an established pattern in this service (every abort site
    tolerates them); this one additionally raises :class:`ShardAbort` so
    the dispatcher can relay (code, details, trailers) byte-identically
    to what the in-process listener would have sent."""

    def __init__(self, metadata, peer: str, remaining_s: float | None):
        self._metadata = tuple(metadata or ())
        self._peer = peer
        self._deadline = (
            time.monotonic() + remaining_s if remaining_s is not None else None
        )
        self.trailers: tuple = ()
        self.aborted: ShardAbort | None = None

    def invocation_metadata(self):
        return self._metadata

    def peer(self) -> str:
        return self._peer

    def time_remaining(self):
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def set_trailing_metadata(self, md) -> None:
        self.trailers = tuple(md or ())

    async def abort(self, code, details: str = "", trailing_metadata=()):
        exc = ShardAbort(code, details, trailing_metadata or self.trailers)
        self.aborted = exc
        raise exc


class IngestSupervisor:
    """Dispatch-process owner of the shard fleet: spawns the N listener
    processes, serves the framed unix socket they feed, dispatches into
    the real service handlers, and respawns dead shards."""

    def __init__(
        self,
        service,                  # AuthServiceImpl (the real handlers)
        health,                   # HealthService
        shards: int,
        host: str,
        port: int,
        wire: str = "native",
        tls: tuple[bytes, bytes] | None = None,
        uds_dir: str | None = None,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 30.0,
        crashloop_deaths: int = 5,
        crashloop_window_s: float = 60.0,
    ):
        from .proto import load_pb2, method_types, stream_method_types
        from .service import request_deserializers

        self.service = service
        self.health = health
        self.shards = shards
        self.host = host
        self.port = port
        self.wire = wire
        self.tls = tls
        self._uds_dir = uds_dir or tempfile.mkdtemp(prefix="cpzk-ingest-")
        os.chmod(self._uds_dir, 0o700)  # the socket carries pickled frames
        self.uds_path = os.path.join(self._uds_dir, "dispatch.sock")
        self._server: asyncio.AbstractServer | None = None
        # index -> multiprocessing Process (spawn context; typed loosely —
        # the spawn context's Process class is resolved at runtime)
        self._procs: dict = {}
        self._monitor: asyncio.Task | None = None
        self._stopping = False
        self.respawns = 0
        # crash-loop guard: dead shards respawn with exponential full-jitter
        # backoff, and crashloop_deaths deaths inside crashloop_window_s
        # stop the respawning entirely — a bad shard binary (bad port, bad
        # TLS material, instant-exit bug) must not spin the supervisor
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.crashloop_deaths = crashloop_deaths
        self.crashloop_window_s = crashloop_window_s
        self._death_times: dict[int, list[float]] = {}
        self._respawn_at: dict[int, float] = {}
        self._backoff_rng = random.Random()  # injectable for deterministic tests
        #: per-shard counters behind /statusz (index -> row dict)
        self.shard_stats: dict[int, dict] = {
            i: {"shard": i, "pid": None, "connected": False, "rpcs": 0,
                "streams": 0, "parses": 0, "fallbacks": 0, "errors": 0,
                "respawns": 0, "crashloop": False}
            for i in range(shards)
        }

        pb2 = service.pb2
        desers = request_deserializers(pb2, wire)
        types = method_types(pb2)
        stream_types = stream_method_types(pb2)
        self._unary = {}
        impl = {
            "Register": service.register,
            "RegisterBatch": service.register_batch,
            "CreateChallenge": service.create_challenge,
            "VerifyProof": service.verify_proof,
            "VerifyProofBatch": service.verify_proof_batch,
        }
        for name in UNARY_METHODS:
            self._unary[f"/{AUTH_SERVICE}/{name}"] = (
                desers[name], impl[name],
                types[name][1].SerializeToString,
            )
        self._unary[f"/{HEALTH_SERVICE}/Check"] = (
            health.pb2.HealthCheckRequest.FromString, health.check,
            health.pb2.HealthCheckResponse.SerializeToString,
        )
        self._stream_path = f"/{AUTH_SERVICE}/{STREAM_METHOD}"
        self._stream_deser = desers[STREAM_METHOD]
        self._stream_ser = (
            stream_types[STREAM_METHOD][1].SerializeToString
        )
        load_pb2()  # shards ship raw bytes for punted messages

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        self._server = await asyncio.start_unix_server(
            self._handle_shard, path=self.uds_path
        )
        os.chmod(self.uds_path, 0o700)
        for i in range(self.shards):
            self._spawn(i)
        self._monitor = asyncio.get_running_loop().create_task(
            self._monitor_loop()
        )
        metrics.gauge("ingest.shards").set(self.shards)
        log.info(
            "sharded ingest: %d listener processes on %s:%d (SO_REUSEPORT), "
            "dispatch seam at %s", self.shards, self.host, self.port,
            self.uds_path,
        )

    def _spawn(self, index: int) -> None:
        import multiprocessing

        ctx = multiprocessing.get_context("spawn")
        proc = ctx.Process(
            target=run_shard,
            args=(index, self.uds_path, {
                "host": self.host,
                "port": self.port,
                "wire": self.wire,
                "tls": self.tls,
            }),
            name=f"cpzk-ingest-{index}",
            daemon=True,
        )
        proc.start()
        self._procs[index] = proc
        self.shard_stats[index]["pid"] = proc.pid

    async def _monitor_loop(self) -> None:
        """Respawn dead shards (SIGKILL, OOM, crash) with exponential
        full-jitter backoff; one shard dying only resets its own
        connections, and a shard that keeps dying (``crashloop_deaths``
        deaths inside ``crashloop_window_s``) is abandoned — marked
        ``crashloop`` in /statusz, counted once, never respawned again —
        so the daemon keeps serving on the healthy shards instead of
        burning the supervisor on a doomed binary."""
        while not self._stopping:
            await asyncio.sleep(0.5)
            now = time.monotonic()
            for index, proc in list(self._procs.items()):
                if self._stopping or proc.is_alive():
                    continue
                code = proc.exitcode
                await asyncio.to_thread(proc.join, 1.0)
                del self._procs[index]
                self.shard_stats[index]["connected"] = False
                self._on_shard_death(index, proc.pid, code, now)
            for index, due in list(self._respawn_at.items()):
                if self._stopping or now < due:
                    continue
                del self._respawn_at[index]
                self.respawns += 1
                self.shard_stats[index]["respawns"] += 1
                metrics.counter("ingest.shard.respawns").inc()
                self._spawn(index)

    def _on_shard_death(self, index: int, pid, code, now: float) -> None:
        """One shard death: record it, then either give up (crash-loop)
        or schedule a jittered respawn."""
        deaths = self._death_times.setdefault(index, [])
        deaths.append(now)
        cutoff = now - self.crashloop_window_s
        while deaths and deaths[0] < cutoff:
            deaths.pop(0)
        if len(deaths) >= self.crashloop_deaths:
            self.shard_stats[index]["crashloop"] = True
            metrics.counter("ingest.shard.crashloop").inc()
            log.warning(
                "ingest shard %d (pid %s) crash-looping: %d deaths in "
                "%.0fs (last exit code %s) — giving up on this shard; "
                "the daemon keeps serving on the remaining %d",
                index, pid, len(deaths), self.crashloop_window_s, code,
                sum(1 for p in self._procs.values() if p.is_alive()),
            )
            return
        ceiling = min(
            self.backoff_max_s,
            self.backoff_base_s * (2 ** (len(deaths) - 1)),
        )
        delay = self._backoff_rng.uniform(0.0, ceiling)  # full jitter
        self._respawn_at[index] = now + delay
        log.warning(
            "ingest shard %d (pid %s) died with exit code %s; respawn "
            "in %.2fs (death %d/%d in the last %.0fs)",
            index, pid, code, delay, len(deaths),
            self.crashloop_deaths, self.crashloop_window_s,
        )

    async def stop(self) -> None:
        self._stopping = True
        if self._monitor is not None:
            self._monitor.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._monitor
        for proc in self._procs.values():
            with contextlib.suppress(Exception):
                proc.terminate()
        for proc in self._procs.values():
            with contextlib.suppress(Exception):
                await asyncio.to_thread(proc.join, 5.0)
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        with contextlib.suppress(OSError):
            os.unlink(self.uds_path)
        with contextlib.suppress(OSError):
            os.rmdir(self._uds_dir)

    def status(self) -> dict:
        """The ``ingest`` block of /statusz."""
        return {
            "shards": self.shards,
            "respawns": self.respawns,
            "crashloop_shards": sum(
                1 for i in range(self.shards)
                if self.shard_stats[i].get("crashloop")
            ),
            "per_shard": [
                dict(self.shard_stats[i]) for i in range(self.shards)
            ],
        }

    # -- shard connection handling ------------------------------------------

    async def _handle_shard(self, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> None:
        out = _FrameWriter(writer)
        stats = None
        tasks: dict[tuple[str, int], asyncio.Task] = {}
        streams: dict[int, _DispatchStream] = {}
        try:
            while True:
                payload = await read_frame(reader)
                if payload is None:
                    return
                msg = pickle.loads(payload)
                kind = msg[0]
                if kind == "hello":
                    index = int(msg[1])
                    stats = self.shard_stats.setdefault(
                        index, {"shard": index, "respawns": 0})
                    stats.update(pid=msg[2], connected=True, rpcs=0,
                                 streams=0, parses=0, fallbacks=0, errors=0)
                    metrics.gauge("ingest.shard.connected").set(
                        sum(1 for s in self.shard_stats.values()
                            if s.get("connected"))
                    )
                    continue
                if stats is None:
                    raise ValueError("shard spoke before hello")
                if kind == "u":          # unary request
                    _, req_id, path, md, peer, remaining, body = msg
                    stats["rpcs"] += 1
                    self._note_parse(stats, path, body)
                    task = asyncio.get_running_loop().create_task(
                        self._dispatch_unary(
                            out, req_id, path, md, peer, remaining, body)
                    )
                    tasks[("u", req_id)] = task
                    task.add_done_callback(
                        lambda _t, k=("u", req_id): tasks.pop(k, None))
                elif kind == "ux":       # unary cancelled client-side
                    task = tasks.get(("u", msg[1]))
                    if task is not None:
                        task.cancel()
                elif kind == "so":       # stream open
                    _, sid, md, peer, remaining = msg
                    stats["streams"] += 1
                    st = _DispatchStream(sid, out)
                    streams[sid] = st
                    st.task = asyncio.get_running_loop().create_task(
                        self._dispatch_stream(st, md, peer, remaining)
                    )
                    st.task.add_done_callback(
                        lambda _t, s=sid: streams.pop(s, None))
                elif kind == "sc":       # stream chunk
                    _, sid, body = msg
                    st = streams.get(sid)
                    if st is not None:
                        self._note_parse(stats, self._stream_path, body)
                        st.chunks.put_nowait(body)
                elif kind == "se":       # stream half-close
                    st = streams.get(msg[1])
                    if st is not None:
                        st.chunks.put_nowait(None)
                elif kind == "sx":       # stream cancelled client-side
                    st = streams.get(msg[1])
                    if st is not None and st.task is not None:
                        st.task.cancel()
                else:
                    raise ValueError(f"unknown ingest frame kind {kind!r}")
        except (ValueError, pickle.UnpicklingError, ConnectionResetError):
            log.exception("ingest shard connection torn down")
        finally:
            if stats is not None:
                stats["connected"] = False
            for task in list(tasks.values()):
                task.cancel()
            for st in list(streams.values()):
                if st.task is not None:
                    st.task.cancel()
            out.close()

    def _note_parse(self, stats: dict, path: str, body) -> None:
        if body[0] == "v":
            stats["parses"] += 1
        else:
            stats["fallbacks"] += 1

    # -- request materialization --------------------------------------------

    def _materialize(self, path: str, body, deser):
        """Body -> request object: pre-parsed native views ("v") rebuild
        with zero re-parse; raw bytes ("b") run through the SAME
        native-first deserializer the in-process listener uses."""
        from . import wire as wire_mod

        tag, payload = body
        if tag != "v":
            return deser(payload)
        kind, fields = payload
        if kind == 1:
            return wire_mod.NativeChallengeRequest(*fields)
        if kind == 2:
            return wire_mod.NativeBatchVerificationRequest(*fields)
        return wire_mod.NativeStreamVerifyRequest(*fields)

    async def _dispatch_unary(self, out: _FrameWriter, req_id: int,
                              path: str, md, peer, remaining, body) -> None:
        entry = self._unary.get(path)
        try:
            if entry is None:
                import grpc

                await out.send(("a", req_id, grpc.StatusCode.UNIMPLEMENTED,
                                f"unknown method {path}", ()))
                return
            deser, handler, serializer = entry
            request = self._materialize(path, body, deser)
            ctx = ShardContext(md, peer, remaining)
            response = await handler(request, ctx)
            await out.send(("r", req_id, serializer(response), ctx.trailers))
        except ShardAbort as exc:
            await out.send(("a", req_id, exc.code, exc.details, exc.trailers))
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # parity with grpc: unhandled -> UNKNOWN
            import grpc

            log.exception("ingest unary dispatch failed for %s", path)
            await out.send(("a", req_id, grpc.StatusCode.UNKNOWN,
                            f"Unhandled error: {exc}", ()))

    async def _dispatch_stream(self, st: "_DispatchStream",
                               md, peer, remaining) -> None:
        ctx = ShardContext(md, peer, remaining)
        out = st.out

        async def request_iterator():
            while True:
                body = await st.chunks.get()
                if body is None:
                    return
                request = self._materialize(self._stream_path, body,
                                            self._stream_deser)
                # consumed: grant the shard one more in-flight chunk
                await out.send(("scr", st.sid, 1))
                yield request

        try:
            handler = self.service.verify_proof_stream
            async for response in handler(request_iterator(), ctx):
                await out.send(("sm", st.sid, self._stream_ser(response)))
            await out.send(("sr", st.sid, ctx.trailers))
        except ShardAbort as exc:
            await out.send(("sa", st.sid, exc.code, exc.details,
                            exc.trailers))
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            import grpc

            log.exception("ingest stream dispatch failed")
            with contextlib.suppress(Exception):
                await out.send(("sa", st.sid, grpc.StatusCode.UNKNOWN,
                                f"Unhandled error: {exc}", ()))


class _DispatchStream:
    """Parent-side state of one proxied VerifyProofStream."""

    __slots__ = ("sid", "out", "chunks", "task")

    def __init__(self, sid: int, out: _FrameWriter):
        self.sid = sid
        self.out = out
        self.chunks: asyncio.Queue = asyncio.Queue()
        self.task: asyncio.Task | None = None


# ---------------------------------------------------------------------------
# shard-process side (spawned; must stay import-light — no jax, no state)
# ---------------------------------------------------------------------------


def run_shard(index: int, uds_path: str, options: dict) -> None:
    """Entry point of one ingest shard process (multiprocessing spawn)."""
    logging.basicConfig(
        level=os.environ.get("LOG_LEVEL", "INFO").upper(),
        format=f"%(asctime)s %(levelname)s ingest-{index}: %(message)s",
    )
    try:
        asyncio.run(_shard_amain(index, uds_path, options))
    except KeyboardInterrupt:
        pass


async def _shard_amain(index: int, uds_path: str, options: dict) -> None:
    import grpc

    from . import wire as wire_mod

    reader, writer = await asyncio.open_unix_connection(uds_path)
    out = _FrameWriter(writer)
    await out.send(("hello", index, os.getpid()))

    pending: dict[int, asyncio.Future] = {}
    stream_q: dict[int, asyncio.Queue] = {}
    credits: dict[int, asyncio.Semaphore] = {}
    seq = 0

    def next_id() -> int:
        nonlocal seq
        seq += 1
        return seq

    native = (
        options.get("wire", "native") == "native"
        and wire_mod.native_available()
    )

    def parse_body(path: str, data: bytes):
        """("v", (kind, fields)) when the native parser accepted, else
        ("b", raw) — the dispatch process then runs its own native-first
        deserializer, so a shard without a loadable .so changes nothing
        but where the parse happens."""
        name = path.rsplit("/", 1)[-1]
        kind = _WIRE_KINDS.get(name)
        if not native or kind is None:
            return ("b", data)
        if kind == 1:
            view = wire_mod._parse_challenge(data)
            if view is None:
                return ("b", data)
            return ("v", (1, (view.user_id,)))
        if kind == 2:
            view = wire_mod._parse_batch_verify(data)
            if view is None:
                return ("b", data)
            return ("v", (2, (view.user_ids, view.challenge_ids,
                              view.proofs, view.proofs_packed)))
        view = wire_mod._parse_stream_chunk(data)
        if view is None:
            return ("b", data)
        return ("v", (3, (view.ids, view.user_ids, view.challenge_ids,
                          view.proofs, view.proofs_packed,
                          view.mint_sessions)))

    async def reply_loop() -> None:
        """Dispatch-process responses -> waiting handler coroutines."""
        while True:
            payload = await read_frame(reader)
            if payload is None:
                break
            msg = pickle.loads(payload)
            kind = msg[0]
            if kind in ("r", "a"):
                fut = pending.pop(msg[1], None)
                if fut is not None and not fut.done():
                    fut.set_result(msg)
            elif kind in ("sm", "sr", "sa"):
                q = stream_q.get(msg[1])
                if q is not None:
                    q.put_nowait(msg)
            elif kind == "scr":
                sem = credits.get(msg[1])
                if sem is not None:
                    sem.release()
        # dispatch process gone: fail everything in flight and exit so
        # the supervisor (or systemd) decides what happens next
        for fut in pending.values():
            if not fut.done():
                fut.set_result(("a", 0, grpc.StatusCode.UNAVAILABLE,
                                "dispatch process unavailable", ()))
        for q in stream_q.values():
            q.put_nowait(("sa", 0, grpc.StatusCode.UNAVAILABLE,
                          "dispatch process unavailable", ()))
        raise SystemExit(1)

    def _forward_meta(context):
        md = tuple(
            (k, v) for k, v in (context.invocation_metadata() or ())
        )
        try:
            remaining = context.time_remaining()
        except Exception:
            remaining = None
        return md, context.peer(), remaining

    def unary_handler(path: str):
        async def handle(request_bytes: bytes, context):
            req_id = next_id()
            fut = asyncio.get_running_loop().create_future()
            pending[req_id] = fut
            md, peer, remaining = _forward_meta(context)
            try:
                await out.send(("u", req_id, path, md, peer, remaining,
                                parse_body(path, request_bytes)))
                msg = await fut
            except asyncio.CancelledError:
                pending.pop(req_id, None)
                with contextlib.suppress(Exception):
                    await out.send(("ux", req_id))
                raise
            if msg[0] == "r":
                _, _, resp, trailers = msg
                if trailers:
                    context.set_trailing_metadata(tuple(trailers))
                return resp
            _, _, code, details, trailers = msg
            try:
                await context.abort(code, details,
                                    trailing_metadata=tuple(trailers))
            except TypeError:
                await context.abort(code, details)

        return handle

    async def stream_handler(request_iterator, context):
        sid = next_id()
        q: asyncio.Queue = asyncio.Queue()
        stream_q[sid] = q
        sem = credits[sid] = asyncio.Semaphore(STREAM_CREDITS)
        md, peer, remaining = _forward_meta(context)
        await out.send(("so", sid, md, peer, remaining))

        async def pump() -> None:
            try:
                async for request_bytes in request_iterator:
                    await sem.acquire()  # dispatch-side queue stays bounded
                    await out.send(
                        ("sc", sid, parse_body(self_path, request_bytes)))
                await out.send(("se", sid))
            except asyncio.CancelledError:
                raise
            except Exception:
                with contextlib.suppress(Exception):
                    await out.send(("sx", sid))

        self_path = f"/{AUTH_SERVICE}/{STREAM_METHOD}"
        pump_task = asyncio.get_running_loop().create_task(pump())
        try:
            while True:
                msg = await q.get()
                if msg[0] == "sm":
                    yield msg[2]
                elif msg[0] == "sr":
                    if msg[2]:
                        context.set_trailing_metadata(tuple(msg[2]))
                    return
                else:  # sa
                    _, _, code, details, trailers = msg
                    try:
                        await context.abort(
                            code, details, trailing_metadata=tuple(trailers))
                    except TypeError:
                        await context.abort(code, details)
        finally:
            pump_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await pump_task
            stream_q.pop(sid, None)
            credits.pop(sid, None)
            with contextlib.suppress(Exception):
                await out.send(("sx", sid))

    identity = bytes  # request bytes in, response bytes out, untouched
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            unary_handler(f"/{AUTH_SERVICE}/{name}"),
            request_deserializer=identity,
            response_serializer=identity,
        )
        for name in UNARY_METHODS
    }
    handlers[STREAM_METHOD] = grpc.stream_stream_rpc_method_handler(
        stream_handler,
        request_deserializer=identity,
        response_serializer=identity,
    )
    health_handlers = {
        "Check": grpc.unary_unary_rpc_method_handler(
            unary_handler(f"/{HEALTH_SERVICE}/Check"),
            request_deserializer=identity,
            response_serializer=identity,
        )
    }

    server = grpc.aio.server(options=(("grpc.so_reuseport", 1),))
    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler(AUTH_SERVICE, handlers),
        grpc.method_handlers_generic_handler(HEALTH_SERVICE, health_handlers),
    ))
    addr = f"{options['host']}:{options['port']}"
    tls = options.get("tls")
    if tls is not None:
        bound = server.add_secure_port(
            addr, grpc.ssl_server_credentials([tls]))
    else:
        bound = server.add_insecure_port(addr)
    if bound == 0:
        log.error("ingest shard %d could not bind %s", index, addr)
        raise SystemExit(2)
    await server.start()
    log.info("ingest shard %d listening on %s (pid %d)",
             index, addr, os.getpid())
    reply = asyncio.get_running_loop().create_task(reply_loop())
    try:
        await reply
    finally:
        await server.stop(grace=None)
