"""Dedicated device-dispatch lane: one long-lived thread pair that owns
every backend call the serving path makes.

Before this module, the batcher paid ``asyncio.to_thread`` once per
device batch — a pool handoff whose scheduling latency lands between the
dispatch commit and worker pickup (the flight recorder's ``thread_hop``
span), and whose worker identity changes batch to batch, defeating any
thread-affine reuse (staging buffers, device queues).  The lane replaces
it with the persistent-worker discipline serving-oriented JAX stacks use
(PROFILE.md §7c, ROADMAP item 1):

- an **MPSC ingress queue** fed by the event loop (``submit``), drained
  FIFO by a persistent host-prep thread — ``thread_hop`` becomes one
  condition-variable wakeup on an already-running thread;
- **double-buffering**: the prep thread runs batch N+1's host phase
  (:meth:`~cpzk_tpu.protocol.batch.BatchVerifier.prepare_batch` —
  deferred screening, Fiat-Shamir challenges, RLC draws) while the
  device thread runs batch N's backend phase
  (:meth:`~cpzk_tpu.protocol.batch.BatchVerifier.run_prepared`), through
  a bounded staging buffer; the staging dwell is recorded as the
  ``device_wait`` stage, and under overlap the flight recorder's
  dispatch gap clamps toward 0 because the device thread never waits on
  host prep;
- results posted back to the submitting event loop via
  ``loop.call_soon_threadsafe`` on a per-batch future — the lane never
  touches asyncio state from its own threads.

Shutdown is drain-then-join: ``stop()`` refuses new work, the prep
thread finishes the ingress backlog, the device thread finishes the
staged backlog, and only then do the threads exit — every accepted
future resolves exactly once (test-pinned in
``tests/test_dispatch_lane.py``).  Backend exceptions are confined to
the batch that raised them: the exception is posted to that batch's
future and the lane threads keep serving (the failover/breaker machinery
lives INSIDE the backend wrapper, so a device loss degrades traffic to
the fallback exactly as it did on the thread-pool path).

``overlap=False`` (config ``tpu.pipeline_depth = 1``) collapses the pair
to a single thread that runs both phases back-to-back — strictly serial
dispatch, still without per-batch thread churn.
"""

from __future__ import annotations

import asyncio
import logging
import os
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ..core.rng import SecureRng
from ..errors import Error
from ..protocol.batch import BatchEntry, BatchVerifier, PreparedBatch

log = logging.getLogger("cpzk_tpu.server.dispatch")


class LaneStopped(RuntimeError):
    """The lane is stopping (or never started) and refuses new work; the
    batcher falls back to its inline verify path."""


@dataclass
class _LaneWork:
    """One batch moving through the lane."""

    entries: list[BatchEntry]
    stages: object                      # BatchStages | None
    loop: asyncio.AbstractEventLoop
    future: asyncio.Future
    bv: BatchVerifier | None = field(default=None, repr=False)
    prepared: PreparedBatch | None = field(default=None, repr=False)


def _run_instrumented(
    bv: BatchVerifier, prepared: PreparedBatch, stages
) -> list[Error | None]:
    """Backend phase with the optional env-gated instrumentation the
    worker-thread path always had: an xprof capture around the device
    dispatch (CPZK_XPROF_DIR) and the stage-decomposition stderr line
    (CPZK_BATCH_DEBUG=1)."""
    xprof = os.environ.get("CPZK_XPROF_DIR")
    if xprof:
        # JAX profiler (xprof) trace around the device dispatch — the
        # per-stage TraceAnnotations emitted by ``stages`` nest inside
        # this capture, so the xprof timeline carries the same
        # pad_and_pack/device_dispatch/unpack names as /tracez.
        import jax

        with jax.profiler.trace(xprof):
            with jax.profiler.TraceAnnotation("cpzk_batch_verify"):
                return bv.run_prepared(prepared, stages)
    if os.environ.get("CPZK_BATCH_DEBUG") == "1":
        t0 = time.perf_counter()
        out = bv.run_prepared(prepared, stages)
        print(f"[batch-debug] n={len(bv.entries)} "
              f"device_phase={time.perf_counter() - t0:.3f}s",
              file=sys.stderr, flush=True)
        return out
    return bv.run_prepared(prepared, stages)


class DispatchLane:
    """Persistent dispatch thread(s) behind
    :class:`~cpzk_tpu.server.batching.DynamicBatcher`.

    ``staging_slots`` bounds how many host-prepared batches may wait for
    the device thread (the double-buffer depth); the batcher's own
    ``pipeline_depth`` semaphore bounds total in-flight batches, so the
    lane's queues stay shallow in steady state.
    """

    def __init__(
        self,
        backend,
        rng: SecureRng | None = None,
        overlap: bool = True,
        staging_slots: int = 1,
        name: str = "cpzk-lane",
    ):
        self._backend = backend
        self._rng = rng or SecureRng()
        self._overlap = overlap
        self._slots = max(1, staging_slots)
        self._name = name
        self._cv = threading.Condition()
        self._ingress: deque[_LaneWork] = deque()
        self._staged: deque[_LaneWork] = deque()
        self._stopping = False
        self._prep_done = False
        self._started = False
        self._threads: list[threading.Thread] = []

    # -- lifecycle ----------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._started and not self._stopping

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        if self._overlap:
            self._threads = [
                threading.Thread(
                    target=self._prep_loop, name=f"{self._name}-prep",
                    daemon=True,
                ),
                threading.Thread(
                    target=self._device_loop, name=f"{self._name}-device",
                    daemon=True,
                ),
            ]
        else:
            self._threads = [
                threading.Thread(
                    target=self._serial_loop, name=f"{self._name}-serial",
                    daemon=True,
                ),
            ]
        for t in self._threads:
            t.start()

    async def stop(self) -> None:
        """Refuse new work, drain every accepted batch, join the threads.
        Every future handed out by :meth:`submit` is resolved before this
        returns — the leak-free shutdown contract."""
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        for t in self._threads:
            # join on a worker thread: the lane may still be verifying a
            # large in-flight batch and the event loop must keep serving
            await asyncio.to_thread(t.join)
        # defensive sweep: the drain loops resolve everything they pop,
        # so leftovers mean a lane thread died abnormally — never leak
        # the futures regardless
        with self._cv:
            leftovers = list(self._ingress) + list(self._staged)
            self._ingress.clear()
            self._staged.clear()
        for work in leftovers:  # pragma: no cover - requires thread death
            self._post(work, None, LaneStopped("dispatch lane exited"))

    # -- submission (event-loop side) ---------------------------------------

    def submit(self, entries: list[BatchEntry], stages) -> asyncio.Future:
        """Queue one prepared-entry batch; returns a future resolving to
        the per-entry results (or raising the dispatch exception).  Must
        be called from a running event loop; raises :class:`LaneStopped`
        once :meth:`stop` has begun."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        work = _LaneWork(
            entries=entries, stages=stages, loop=loop, future=fut,
        )
        with self._cv:
            if not self.running:
                raise LaneStopped("dispatch lane is not accepting work")
            self._ingress.append(work)
            self._cv.notify_all()
        return fut

    def depths(self) -> tuple[int, int]:
        """(ingress, staged) queue depths — introspection for tests and
        the admin REPL."""
        with self._cv:
            return len(self._ingress), len(self._staged)

    # -- shared verify seam --------------------------------------------------

    @staticmethod
    def verify_once(
        backend, rng: SecureRng, entries: list[BatchEntry], stages=None
    ) -> list[Error | None]:
        """Both phases back-to-back on the calling thread — the SAME
        code path the lane threads run, exposed for the stopped-batcher
        inline verify (``DynamicBatcher.submit_many`` during shutdown),
        so every serving path shares one dispatch seam and the flight
        record's stage-sum-vs-wall invariant holds everywhere."""
        bv = BatchVerifier(backend=backend, max_size=max(len(entries), 1))
        bv.entries.extend(entries)  # already validated at RPC ingress
        if stages is None:
            return _run_instrumented(bv, bv.prepare_batch(rng), None)
        stages.mark_worker_start()
        try:
            prepared = bv.prepare_batch(rng, stages)
            return _run_instrumented(bv, prepared, stages)
        finally:
            stages.mark_worker_end()

    # -- lane threads --------------------------------------------------------

    def _prepare(self, work: _LaneWork) -> bool:
        """Host phase on the prep thread; False when the batch already
        resolved (prep raised and the exception was posted)."""
        if work.stages is not None:
            work.stages.mark_worker_start()
        try:
            bv = BatchVerifier(
                backend=self._backend, max_size=max(len(work.entries), 1),
            )
            bv.entries.extend(work.entries)
            work.bv = bv
            work.prepared = bv.prepare_batch(self._rng, work.stages)
        except Exception as exc:
            self._post(work, None, exc)
            return False
        if work.stages is not None:
            work.stages.mark_staged()
        return True

    def _execute(self, work: _LaneWork) -> None:
        """Backend phase; posts results or the dispatch exception."""
        if work.stages is not None:
            work.stages.mark_device_start()
        try:
            results = _run_instrumented(work.bv, work.prepared, work.stages)
        except Exception as exc:
            # confined to this batch: the failover/breaker wrapper inside
            # the backend already routed what it could; the lane thread
            # itself survives for the next batch
            self._post(work, None, exc)
            return
        finally:
            if work.stages is not None:
                work.stages.mark_worker_end()
        self._post(work, results, None)

    def _pop_ingress(self) -> _LaneWork | None:
        """Next ingress item, blocking; None = stopping and fully drained."""
        with self._cv:
            while not self._ingress and not self._stopping:
                self._cv.wait()
            if not self._ingress:
                self._prep_done = True
                self._cv.notify_all()
                return None
            return self._ingress.popleft()

    def _prep_loop(self) -> None:
        while True:
            work = self._pop_ingress()
            if work is None:
                return
            if not self._prepare(work):
                continue
            with self._cv:
                # bounded staging: at most `slots` prepared batches wait
                # for the device thread (double-buffer backpressure).  No
                # stopping escape hatch — stop() drains, never drops.
                while len(self._staged) >= self._slots:
                    self._cv.wait()
                self._staged.append(work)
                self._cv.notify_all()

    def _device_loop(self) -> None:
        while True:
            with self._cv:
                while not self._staged and not self._prep_done:
                    self._cv.wait()
                if not self._staged:
                    return
                work = self._staged.popleft()
                self._cv.notify_all()  # staging slot freed
            self._execute(work)

    def _serial_loop(self) -> None:
        """pipeline_depth=1: both phases on one persistent thread."""
        while True:
            work = self._pop_ingress()
            if work is None:
                return
            if self._prepare(work):
                self._execute(work)

    # -- result posting ------------------------------------------------------

    def _post(self, work: _LaneWork, results, exc) -> None:
        def _resolve() -> None:
            fut = work.future
            if fut.done():
                return  # RPC side gave up (cancelled); nothing to deliver
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(results)

        try:
            work.loop.call_soon_threadsafe(_resolve)
        except RuntimeError:  # pragma: no cover - loop closed under us
            log.error(
                "dispatch lane could not post a batch result: the "
                "submitting event loop is closed (%d entries dropped)",
                len(work.entries),
            )
