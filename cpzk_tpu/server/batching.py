"""Dynamic batching: coalesce concurrent verification RPCs into
device-sized batches (BASELINE.md north-star config 5).

The reference verifies every ``VerifyProof`` inline on the request task
(``src/verifier/service.rs:321-405``) — fine for a CPU path, but a TPU
amortizes only over large batches.  ``DynamicBatcher`` is the TPU-native
serving piece: RPC handlers submit (params, statement, proof, context)
entries and await a future; a single dispatcher task drains the queue every
``window_ms`` (or immediately at ``max_batch``) and hands each batch to the
:class:`~cpzk_tpu.server.dispatch.DispatchLane` — a persistent host-prep +
device-dispatch thread pair (no per-batch ``asyncio.to_thread`` hop; batch
N+1's host prep overlaps batch N's device compute), which resolves the
futures with per-entry results.  Accept/reject semantics are exactly the
BatchVerifier ground truth, so batching is observationally identical to
inline verification — only latency (+window) and throughput change.

Deadline shedding (resilience subsystem): each entry may carry the
absolute monotonic deadline of the RPC that queued it; the dispatcher
drops already-expired entries *before* device dispatch, resolving their
futures with :class:`DeadlineExceeded` — a saturated queue stops burning
device time on answers nobody is waiting for.  ``shed_expired=False``
restores verify-everything behavior.

Gauges (VERDICT round-1 §metrics): ``tpu.queue.depth`` (queued +
claimed-by-in-flight-dispatches — cannot go stale at 0 under
pipelining), ``tpu.batch.fill_ratio``, ``tpu.batch.latency`` (histogram),
``tpu.batch.proofs`` / ``tpu.queue.shed`` / ``tpu.queue.expired``
(counters).

Tracing (observability subsystem): entries carry the submitting RPC's
trace id; each dispatch records a per-entry ``queue_wait`` span (and
histogram) plus batch-level ``pad_and_pack`` / ``device_dispatch`` /
``unpack`` stage spans via :class:`~cpzk_tpu.observability.BatchStages`,
with ``tpu.batch.host_time`` / ``tpu.batch.device_time`` histograms —
the latency-breakdown substrate docs/operations.md §Telemetry documents.

Flight recording: every dispatch additionally lands one
:class:`~cpzk_tpu.observability.flightrec.FlightRecord` — the widened
``thread_hop``/``device_wait``/``marshal``/``compile``/``execute``
split of where ``device_dispatch`` time went, padded-lane occupancy,
jit cache attribution, and the device dispatch gap — behind the admin
REPL's ``/flightrec`` and the SIGUSR2 JSON dump.
"""

from __future__ import annotations

import asyncio
import logging
import time

from ..core.rng import SecureRng
from ..errors import Error
from ..observability.tracing import BatchStages, get_tracer
from ..protocol.batch import BatchEntry, VerifierBackend
from ..protocol.gadgets import Parameters, Proof, Statement
from . import metrics
from .dispatch import DispatchLane, LaneStopped

log = logging.getLogger("cpzk_tpu.server.batching")


#: Max per-dispatch ``tpu.batch.queue_wait`` histogram observes; deeper
#: batches are stride-sampled (uniform, mean-unbiased — the admission
#: controller's overload signal reads the mean of this histogram).
_QUEUE_WAIT_SAMPLE = 128


class QueueFull(Exception):
    """Backpressure signal: the batcher queue is at capacity.  The RPC
    layer maps this to RESOURCE_EXHAUSTED (ADVICE r2: an unbounded queue
    grows without limit under sustained overload)."""


class DeadlineExceeded(Exception):
    """Deadline-shed signal: the entry's RPC deadline expired while it was
    queued, so it was dropped before device dispatch.  The RPC layer maps
    this to DEADLINE_EXCEEDED (usually moot — the client already gave up —
    but it keeps the status truthful for proxies and logs)."""


class _EntryGroup:
    """Shared result collector for one :meth:`DynamicBatcher.submit_group`
    chunk: ONE asyncio future for the whole chunk instead of one per
    entry.  Per-entry futures cost an ``ensure_future`` + ``call_soon``
    callback + context switch each in ``asyncio.wait`` — at stream depth
    that machinery alone was a measurable slice of every proof."""

    __slots__ = ("fut", "results", "remaining")

    def __init__(self, fut: asyncio.Future, n: int):
        self.fut = fut
        self.results: list = [None] * n
        self.remaining = n

    def note(self, index: int, value) -> None:
        if self.fut.done():
            return  # chunk abandoned (stream handler cancelled mid-wait)
        self.results[index] = value
        self.remaining -= 1
        if self.remaining == 0:
            self.fut.set_result(self.results)


class _GroupSlot:
    """Future-shaped view of one entry's slot in an :class:`_EntryGroup`
    — implements exactly the surface the dispatcher touches (``done`` /
    ``set_result`` / ``set_exception``), with exceptions SETTLED as
    values (the streaming per-entry-verdict contract)."""

    __slots__ = ("group", "index")

    def __init__(self, group: _EntryGroup, index: int):
        self.group = group
        self.index = index

    def done(self) -> bool:
        # the group future only completes when every slot resolved or the
        # submitter gave up — either way this slot needs no delivery
        return self.group.fut.done()

    def set_result(self, value) -> None:
        self.group.note(self.index, value)

    def set_exception(self, exc: BaseException) -> None:
        self.group.note(self.index, exc)


class DynamicBatcher:
    """Deadline-based request coalescing in front of a ``VerifierBackend``."""

    def __init__(
        self,
        backend: VerifierBackend | None,
        max_batch: int = 4096,
        window_ms: float = 5.0,
        max_queue: int | None = None,
        pipeline_depth: int = 2,
        shed_expired: bool = True,
        router=None,
    ):
        self.backend = backend
        # multi-chip serving plane: a prebuilt LaneRouter replaces the
        # single dispatch lane — every settled batch is PLACED on one of
        # N per-device lanes (or the big-batch mesh lane) instead of fed
        # to one chip.  None (the [tpu] lanes = 1 default) keeps the
        # single-lane path STRUCTURALLY unchanged: no router bookkeeping
        # on the hot path of single-device hosts.
        self.router = router
        self.max_batch = max_batch
        self.shed_expired = shed_expired
        # shed load once more than a few device batches are waiting; the
        # dispatcher drains max_batch per pass, so 4x is ~4 windows of grace
        self.max_queue = max_queue if max_queue is not None else 4 * max_batch
        self.window = window_ms / 1000.0
        # host-pipeline overlap (SURVEY §2.3 PP analog): up to
        # pipeline_depth batches in flight, so batch k+1's host stage
        # (challenge hashing, limb marshalling — GIL-releasing native and
        # numpy work) overlaps batch k's device compute.  Depth 1 restores
        # strictly serial dispatch.
        self.pipeline_depth = max(1, pipeline_depth)
        # the persistent dispatch lane (created per start()): one host-prep
        # thread + one device thread replacing the per-batch to_thread hop;
        # depth 1 collapses it to a single strictly-serial lane thread
        self._lane: DispatchLane | None = None
        self._inflight: asyncio.Semaphore | None = None
        # entries claimed by in-flight dispatches but not yet resolved;
        # counted into both backpressure and the depth gauge so pipelining
        # can't hide a device's worth of queued work (satellite fix: the
        # gauge used to go stale at 0 the moment the queue drained)
        self._inflight_entries = 0
        self._dispatches: set[asyncio.Task] = set()
        self._queue: list[tuple[BatchEntry, asyncio.Future]] = []
        self._wakeup: asyncio.Event = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._stopping = False
        self._rng = SecureRng()
        # drain-rate EWMA (entries resolved per second): the admission
        # controller sizes cpzk-retry-after-ms pushback from it
        self._drained_at: float | None = None
        self._drain_rate = 0.0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._task is not None and not self._task.done():
            return  # already running (serve() starts the batcher it is given)
        if self.router is not None:
            self.router.start()
        else:
            self._lane = DispatchLane(
                self.backend,
                rng=self._rng,
                overlap=self.pipeline_depth > 1,
                staging_slots=max(1, self.pipeline_depth - 1),
            )
            self._lane.start()
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        """Drain the queue and all in-flight dispatches, then stop —
        including the dispatch lane, which drains its accepted batches
        and resolves every pending future before its threads exit."""
        self._stopping = True
        self._wakeup.set()
        if self._task is not None:
            await self._task
            self._task = None
        if self._dispatches:
            await asyncio.gather(*tuple(self._dispatches), return_exceptions=True)
        if self._lane is not None:
            await self._lane.stop()
        if self.router is not None:
            await self.router.stop()

    # -- submission --------------------------------------------------------

    async def submit(
        self,
        params: Parameters,
        statement: Statement,
        proof: Proof,
        context: bytes | None,
        deadline: float | None = None,
        trace_id: str | None = None,
    ) -> Error | None:
        """Queue one proof; resolves to ``None`` (ok) or the ``Error``.
        ``deadline`` is an absolute ``time.monotonic()`` point (the RPC
        deadline); past it the entry is shed instead of verified and the
        await raises :class:`DeadlineExceeded`.  ``trace_id`` ties the
        entry's stage spans (queue_wait, pad_and_pack, device_dispatch,
        unpack) to the submitting RPC's trace."""
        entry = BatchEntry(
            params, statement, proof, context,
            deadline=deadline, trace_id=trace_id,
        )
        return (await self.submit_many([entry]))[0]

    async def submit_many(
        self, entries: list[BatchEntry], settled: bool = False
    ) -> list[Error | None]:
        """Queue a whole RPC's entries in one enqueue: one capacity check,
        one wakeup, and futures created without a coroutine per item —
        the per-item scheduling cost is the serving layer's, not the
        device's, so batch RPCs bypass it.  All-or-nothing on
        backpressure: either every entry is queued or ``QueueFull`` is
        raised before any is (no orphaned siblings to drain).  Entries may
        still be split across device batches at ``max_batch`` boundaries
        or coalesced with concurrent RPCs — per-entry results are awaited
        together and returned in order.

        ``settled=True`` (the streaming path) returns per-entry
        EXCEPTIONS as values instead of raising the first one: entries
        shed by the deadline policy come back as their
        :class:`DeadlineExceeded` while their batch siblings still carry
        real verdicts — the per-entry NOT-verdict contract a stream needs
        (an exception raised for one entry of a unary batch RPC aborts
        the whole RPC anyway, so the unary path keeps raising)."""
        if not entries:
            return []
        now = time.monotonic()
        for entry in entries:
            entry.enqueued_at = now
        if self._stopping or self._task is None or self._task.done():
            # shutdown window (stop() ran but the listener is still up) or
            # batcher never started: verify inline with identical semantics
            # through the SAME dispatch seam the lane threads run
            # (DispatchLane.verify_once), so the flight record still lands
            # with the full stage decomposition — thread_hop here is the
            # one-off to_thread handoff this fallback path actually pays
            stages = self._stages_for(entries)
            t0 = time.monotonic()
            stages.mark_submit()
            try:
                results = await asyncio.to_thread(
                    DispatchLane.verify_once,
                    self.backend, self._rng, entries, stages,
                )
            except Exception as exc:
                if not settled:
                    raise
                return [exc] * len(entries)  # type: ignore[list-item]
            stages.finalize(time.monotonic() - t0)
            return results
        # backpressure over the whole pipeline: queued entries PLUS entries
        # already claimed by in-flight dispatches — otherwise a deep
        # pipeline accepts up to pipeline_depth*max_batch extra work the
        # instant the queue drains, defeating the cap
        if len(self._queue) + self._inflight_entries + len(entries) > self.max_queue:
            metrics.counter("tpu.queue.shed").inc()
            raise QueueFull(
                f"verification queue at capacity ({self.max_queue} entries)"
            )
        loop = asyncio.get_running_loop()
        futs = [loop.create_future() for _ in entries]
        self._queue.extend(zip(entries, futs, strict=True))
        self._set_depth_gauge()
        self._wakeup.set()
        # Futures resolve to an Error VALUE for a per-entry verification
        # failure and to a raised exception only for dispatch blowups —
        # gather(return_exceptions=True) would conflate the two, and plain
        # gather would leave sibling exceptions unretrieved (log flood).
        # wait + explicit .exception() keeps the distinction and marks
        # every sibling's exception retrieved before the first propagates.
        try:
            await asyncio.wait(futs)
        except asyncio.CancelledError:
            # RPC cancelled while queued: cancel our futures so a later
            # dispatch failure doesn't set never-retrieved exceptions on
            # them (_dispatch skips done futures)
            for fut in futs:
                fut.cancel()
            raise
        first_exc: BaseException | None = None
        results: list[Error | None] = []
        for fut in futs:
            exc = fut.exception()
            if exc is not None:
                first_exc = first_exc or exc
                results.append(exc if settled else None)  # type: ignore[arg-type]
            else:
                results.append(fut.result())
        if first_exc is not None and not settled:
            raise first_exc
        return results

    async def submit_group(self, entries: list[BatchEntry]) -> list:
        """The streaming enqueue: one chunk, ONE future.  Same queueing,
        coalescing, shedding, and backpressure semantics as
        :meth:`submit_many` with ``settled=True`` (per-entry exceptions
        come back as values), but the n-futures-plus-``asyncio.wait``
        machinery is replaced by an :class:`_EntryGroup` the dispatcher
        fills in place — the difference is pure per-entry event-loop
        overhead, which is exactly what a deep stream amortizes away."""
        if not entries:
            return []
        now = time.monotonic()
        for entry in entries:
            entry.enqueued_at = now
        if self._stopping or self._task is None or self._task.done():
            stages = self._stages_for(entries)
            t0 = time.monotonic()
            stages.mark_submit()
            try:
                results = await asyncio.to_thread(
                    DispatchLane.verify_once,
                    self.backend, self._rng, entries, stages,
                )
            except Exception as exc:
                return [exc] * len(entries)
            stages.finalize(time.monotonic() - t0)
            return results
        if len(self._queue) + self._inflight_entries + len(entries) > self.max_queue:
            metrics.counter("tpu.queue.shed").inc()
            raise QueueFull(
                f"verification queue at capacity ({self.max_queue} entries)"
            )
        loop = asyncio.get_running_loop()
        group = _EntryGroup(loop.create_future(), len(entries))
        self._queue.extend(  # type: ignore[arg-type]  # future-shaped slots
            (entry, _GroupSlot(group, i)) for i, entry in enumerate(entries)
        )
        self._set_depth_gauge()
        self._wakeup.set()
        return await group.fut

    # -- dispatcher --------------------------------------------------------

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            await self._wakeup.wait()
            self._wakeup.clear()
            if not self._queue:
                if self._stopping:
                    return
                continue
            # deadline window: let concurrent requests pile in, but dispatch
            # immediately once a full device batch is queued (the wakeup
            # event interrupts the wait) or when draining for shutdown
            deadline = loop.time() + self.window
            while len(self._queue) < self.max_batch and not self._stopping:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    break
                try:
                    await asyncio.wait_for(self._wakeup.wait(), remaining)
                    self._wakeup.clear()
                except asyncio.TimeoutError:
                    break

            if self._inflight is None:
                self._inflight = asyncio.Semaphore(self.pipeline_depth)
            while self._queue:
                # shed entries whose RPC deadline already passed — nobody
                # is waiting, so device time on them is pure waste
                self._drop_expired()
                take = self._queue[: self.max_batch]
                if not take:
                    break
                del self._queue[: len(take)]
                self._inflight_entries += len(take)
                self._set_depth_gauge()
                # bounded pipeline: block only when pipeline_depth batches
                # are already in flight; otherwise batch k+1's host prep
                # overlaps batch k's device compute on another thread
                await self._inflight.acquire()
                task = asyncio.get_running_loop().create_task(
                    self._dispatch_release(take)
                )
                self._dispatches.add(task)
                task.add_done_callback(self._dispatches.discard)

            if self._stopping and not self._queue:
                return

    async def _dispatch_release(self, take) -> None:
        try:
            await self._dispatch(take)
        finally:
            assert self._inflight is not None
            self._inflight.release()
            # recompute after the drain: the gauge reflects queued +
            # in-flight work, so it cannot read 0 while a device batch is
            # still resolving (satellite fix)
            self._inflight_entries -= len(take)
            self._set_depth_gauge()
            self._note_drain(len(take))

    # -- load signals (admission subsystem seam) ---------------------------

    def load_snapshot(self) -> tuple[int, int]:
        """(entries queued + claimed in flight, queue capacity) — the
        utilization signal the admission controller adapts on."""
        return len(self._queue) + self._inflight_entries, self.max_queue

    def drain_rate(self) -> float:
        """EWMA of entries resolved per second (0.0 until the first two
        dispatches have completed)."""
        return self._drain_rate

    def _note_drain(self, n: int) -> None:
        now = time.monotonic()
        if self._drained_at is not None:
            dt = now - self._drained_at
            if dt > 0:
                inst = n / dt
                self._drain_rate = (
                    inst if self._drain_rate == 0.0
                    else 0.8 * self._drain_rate + 0.2 * inst
                )
        self._drained_at = now

    def _set_depth_gauge(self) -> None:
        metrics.gauge("tpu.queue.depth").set(
            len(self._queue) + self._inflight_entries
        )

    def _split_expired(
        self, items: list[tuple[BatchEntry, asyncio.Future]]
    ) -> tuple[list[tuple[BatchEntry, asyncio.Future]], list[asyncio.Future]]:
        """(live, expired-futures) partition of ``items`` at now.  Entries
        whose future is already done (RPC cancelled while queued — e.g.
        the client's deadline fired first) are dropped on the floor here
        too: nobody can observe their result, so verifying them would be
        the same waste as verifying an expired entry.  They count into
        ``tpu.queue.abandoned`` rather than ``tpu.queue.expired`` so the
        two shed paths stay distinguishable on a dashboard."""
        if not self.shed_expired:
            return items, []
        now = time.monotonic()
        live, expired, abandoned = [], [], 0
        for entry, fut in items:
            if fut.done():
                abandoned += 1
            elif entry.deadline is not None and now >= entry.deadline:
                expired.append(fut)
            else:
                live.append((entry, fut))
        if abandoned:
            metrics.counter("tpu.queue.abandoned").inc(abandoned)
        return live, expired

    def _resolve_expired(self, futs: list[asyncio.Future]) -> None:
        if not futs:
            return
        metrics.counter("tpu.queue.expired").inc(len(futs))
        for fut in futs:
            fut.set_exception(
                DeadlineExceeded("RPC deadline expired before dispatch")
            )

    def _drop_expired(self) -> None:
        live, expired = self._split_expired(self._queue)
        if len(live) != len(self._queue):  # expired OR abandoned were cut
            self._queue[:] = live
            self._set_depth_gauge()
        self._resolve_expired(expired)

    def _backend_label(self) -> str:
        """Which compute plane this batch lands on, for the ``backend``
        label of ``tpu.batch.device_time`` ("fallback" while a failover
        wrapper is degraded)."""
        backend = self.backend
        if backend is None:
            return "cpu"
        if hasattr(backend, "degraded"):
            return "fallback" if backend.degraded else "primary"
        name = type(backend).__name__.removesuffix("Backend").lower()
        return name or "custom"

    def _stages_for(
        self, entries: list[BatchEntry], queue_wait_s: float = 0.0
    ) -> BatchStages:
        return BatchStages(
            get_tracer(),
            [e.trace_id for e in entries],
            batch_size=len(entries),
            backend_label=self._backend_label(),
            queue_wait_s=queue_wait_s,
        )

    def _note_queue_wait(self, entries: list[BatchEntry]) -> float:
        """queue_wait span + histogram, measured from enqueue to the
        moment the batch is committed to dispatch; returns the mean wait
        (the flight record's ``queue_wait_s``).

        Spans are grouped per trace: entries sharing a trace id (a batch
        RPC's items, a stream chunk) get ONE ``queue_wait`` span carrying
        their mean wait and entry count — per-entry spans on a shared
        trace are redundant for display and quadratic for memory on deep
        streams.  Entries with distinct traces keep their exact
        per-entry span.  Histogram observes are stride-sampled above
        ``_QUEUE_WAIT_SAMPLE`` entries per dispatch (uniform stride, so
        the mean the admission controller reads stays unbiased) — at
        device-quantum batch sizes, per-entry observes were a
        milliseconds-scale slice of every dispatch."""
        now = time.monotonic()
        tracer = get_tracer()
        hist = metrics.histogram("tpu.batch.queue_wait")
        total = 0.0
        seen = 0
        by_trace: dict[str, tuple[float, int, float]] = {}
        waits: list[float] = []
        for entry in entries:
            if entry.enqueued_at is None:
                continue
            wait = max(0.0, now - entry.enqueued_at)
            total += wait
            seen += 1
            waits.append(wait)
            tid = entry.trace_id
            if tid:
                acc = by_trace.get(tid)
                if acc is None:
                    by_trace[tid] = (wait, 1, entry.enqueued_at)
                else:
                    by_trace[tid] = (
                        acc[0] + wait, acc[1] + 1, min(acc[2], entry.enqueued_at)
                    )
        if len(waits) <= _QUEUE_WAIT_SAMPLE:
            for wait in waits:
                hist.observe(wait)
        else:
            stride = len(waits) / _QUEUE_WAIT_SAMPLE
            for k in range(_QUEUE_WAIT_SAMPLE):
                hist.observe(waits[int(k * stride)])
        for tid, (t_sum, count, first) in by_trace.items():
            if count == 1:
                tracer.add_span(tid, "queue_wait", first, t_sum)
            else:
                tracer.add_span(
                    tid, "queue_wait", first, t_sum / count, entries=count
                )
        return total / seen if seen else 0.0

    async def _dispatch(self, take: list[tuple[BatchEntry, asyncio.Future]]) -> None:
        # entries can also expire between the drain-loop slice and this
        # dispatch actually running (pipeline backpressure waits on the
        # in-flight semaphore in between) — shed them here too, right
        # before device work is committed
        take, expired = self._split_expired(take)
        self._resolve_expired(expired)
        if not take:
            return
        entries = [e for e, _ in take]
        futs = [f for _, f in take]
        metrics.gauge("tpu.batch.fill_ratio").set(len(entries) / self.max_batch)
        metrics.counter("tpu.batch.proofs").inc(len(entries))
        mean_wait = self._note_queue_wait(entries)
        stages = self._stages_for(entries, queue_wait_s=mean_wait)
        t0 = time.monotonic()  # same clock as the stage spans, so the
        stages.mark_submit()   # stage-sum-vs-wall invariant is exact
        try:
            results = await self._lane_verify(entries, stages)
        except Exception as exc:  # backend blew up past all failovers
            log.exception("batch dispatch failed")
            for fut in futs:
                if not fut.done():
                    fut.set_exception(exc)
            return
        wall = time.monotonic() - t0
        metrics.histogram("tpu.batch.latency").observe(wall)
        # flight record: the widened stage breakdown, padded-shape
        # occupancy, jit attribution, and dispatch gap for this batch
        stages.finalize(wall)
        for fut, res in zip(futs, results, strict=True):
            if not fut.done():
                fut.set_result(res)

    async def _lane_verify(
        self, entries: list[BatchEntry], stages: BatchStages | None
    ) -> list[Error | None]:
        """Route one committed batch through the lane router (multi-chip
        plane) or the single dispatch lane; falls back to a worker thread
        running the identical seam when the lane is already draining (a
        dispatch committed in the same loop tick as stop())."""
        router = self.router
        if router is not None and router.running:
            try:
                return await router.submit(entries, stages)
            except LaneStopped:
                pass  # raced stop(); the fallback below still verifies
        lane = self._lane
        if lane is not None and lane.running:
            try:
                return await lane.submit(entries, stages)
            except LaneStopped:
                pass  # raced stop(); the fallback below still verifies
        return await asyncio.to_thread(
            DispatchLane.verify_once, self.backend, self._rng, entries, stages,
        )
