"""Server daemon + admin REPL (reference ``src/bin/server.rs`` twin).

Flags (env-overridable like the clap definitions at server.rs:20-48), config
load + validation, background cleanup task under a panic-restarting
supervisor, optional Prometheus exporter, gRPC health, a colored admin REPL
(/status /persist /users /sessions /challenges /cleanup /help /quit), and
graceful shutdown: health flips to NOT_SERVING, 2 s drain, the listener
stops, background tasks are awaited, and the final snapshot lands
(server.rs:379-427).  Boot goes through :func:`load_state`: crash recovery
(snapshot + WAL replay) when ``[durability]`` is enabled, quarantine-safe
snapshot restore otherwise.

Run: ``python -m cpzk_tpu.server --host 127.0.0.1 --port 50051``
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import gc
import logging
import os
import signal
import sys
import time

from . import metrics
from ..errors import UnsupportedFormat
from .config import RateLimiter, ServerConfig
from .state import ServerState

# sweep/checkpoint cadence; CPZK_CLEANUP_INTERVAL_S shortens it so a
# bounded-duration soak run still observes checkpoints and sweeps
CLEANUP_INTERVAL_SECONDS = float(os.environ.get("CPZK_CLEANUP_INTERVAL_S", 60))
SUPERVISOR_BACKOFF_SECONDS = 5
DRAIN_SECONDS = 2

log = logging.getLogger("cpzk_tpu.server")


def _c(color: str, text: str) -> str:
    codes = {"green": "32", "red": "31", "yellow": "33", "cyan": "36", "white": "37"}
    if not sys.stdout.isatty():
        return text
    return f"\x1b[{codes[color]}m{text}\x1b[0m"


def parse_args(argv=None) -> argparse.Namespace:
    """CLI flags are the TOP config layer: every flag defaults to None and
    only overrides the resolved config when explicitly provided — env vars
    (SERVER_HOST, SERVER_RATE_LIMIT_REQUESTS_PER_MINUTE, ...) and .env are
    handled by ``ServerConfig.from_env`` so precedence stays
    defaults < TOML < .env < env < CLI (the reference never reconciles
    these layers — SURVEY.md §3.3)."""
    p = argparse.ArgumentParser(prog="cpzk-server", description="Chaum-Pedersen auth server")
    p.add_argument("-H", "--host", default=None)
    p.add_argument("-p", "--port", type=int, default=None)
    p.add_argument("--metrics", action="store_true", default=None,
                   help="enable the Prometheus exporter")
    p.add_argument("--metrics-port", type=int, default=None)
    p.add_argument("--rate-limit", type=int, default=None,
                   help="requests per minute")
    p.add_argument("--rate-burst", type=int, default=None)
    p.add_argument("--backend", choices=("cpu", "tpu"), default=None,
                   help="verifier backend: cpu (inline host verify) or tpu "
                        "(JAX data plane + dynamic batching + CPU failover)")
    p.add_argument("--batch-max", type=int, default=None,
                   help="dynamic-batcher device batch target (tpu backend)")
    p.add_argument("--batch-window-ms", type=float, default=None,
                   help="dynamic-batcher queue deadline in ms (tpu backend)")
    p.add_argument("--no-repl", action="store_true", help="run headless (no admin REPL)")
    p.add_argument("--state-file", default=None,
                   help="opt-in checkpoint/resume: restore users+sessions "
                        "from this JSON snapshot at boot (when it exists) "
                        "and write it on graceful shutdown and every "
                        "cleanup sweep. Default: in-memory only "
                        "(reference parity)")
    return p.parse_args(argv)


def build_backend(config):
    """(backend, batcher) for the resolved config: the TPU data plane behind
    a CPU failover and a dynamic batching queue, or (None, None) for the
    reference-parity inline CPU path.  With ``[tpu] prewarm_quanta`` set,
    the verify kernels for those batch sizes are AOT-compiled HERE — before
    the listener binds and health reports ready — so the first serving
    dispatch at a warmed shape never pays an XLA trace.

    ``[tpu] lanes != 1`` builds the multi-chip serving plane instead: one
    per-device ``DispatchLane`` per local device behind a deadline-aware
    :class:`~cpzk_tpu.server.router.LaneRouter` with a per-lane breaker
    (one sick chip degrades only its lane), per-device AOT prewarm, and —
    with ``mesh_threshold`` set — a big-batch mesh lane riding the
    sharded kernels (docs/operations.md §"Multi-chip serving")."""
    if config.tpu.backend != "tpu":
        return None, None
    import jax

    from ..ops.backend import TpuBackend, enable_donation, prewarm_executables
    from ..parallel import resolve_lane_devices
    from ..protocol.batch import CpuBackend, FailoverBackend
    from .batching import DynamicBatcher

    # serving rebuilds every kernel input per batch, so donated buffers
    # are safe here (and let XLA reuse device memory across batches);
    # XLA CPU ignores donation and warns per call, so gate it off there
    enable_donation(jax.default_backend() != "cpu")

    quanta = config.tpu.parsed_prewarm_quanta()
    recovery_after_s = (
        None if config.tpu.recovery_after_s == -1
        else config.tpu.recovery_after_s
    )
    lane_devices = resolve_lane_devices(config.tpu.lanes)
    if lane_devices is not None:
        from .router import LaneRouter

        lane_backends = [TpuBackend(device=d) for d in lane_devices]
        if quanta:
            t0 = time.monotonic()
            warmed = prewarm_executables(quanta, devices=lane_devices)
            log.info(
                "prewarmed %d verify executables for batch quanta %s "
                "across %d devices in %.1fs", len(warmed), quanta,
                len(lane_devices), time.monotonic() - t0,
            )
        mesh_backend = None
        if config.tpu.mesh_threshold > 0:
            mesh_backend = TpuBackend(mesh_devices=len(lane_devices))
        router = LaneRouter(
            lane_backends,
            devices=lane_devices,
            overlap=config.tpu.pipeline_depth > 1,
            staging_slots=max(1, config.tpu.pipeline_depth - 1),
            recovery_after_s=recovery_after_s,
            mesh_backend=mesh_backend,
            mesh_threshold=config.tpu.mesh_threshold,
        )
        # the resolved topology, surfaced once at boot: lane count +
        # device list + mesh crossover (and the tpu.lanes gauge for
        # dashboards that can't read logs)
        metrics.gauge("tpu.lanes").set(len(lane_devices))
        log.info(
            "serving plane: %d per-device dispatch lanes over %s (of %d "
            "local / %d visible devices), mesh path %s",
            len(lane_devices),
            ", ".join(str(d) for d in lane_devices),
            jax.local_device_count(), jax.device_count(),
            f"at >= {config.tpu.mesh_threshold} entries"
            if config.tpu.mesh_threshold > 0 else "off",
        )
        batcher = DynamicBatcher(
            lane_backends[0],
            max_batch=config.tpu.batch_max,
            window_ms=config.tpu.batch_window_ms,
            pipeline_depth=config.tpu.pipeline_depth,
            shed_expired=config.tpu.shed_expired,
            router=router,
        )
        return lane_backends[0], batcher

    # mesh_devices semantics: 0 = shard over all visible devices (default),
    # k = first k devices; TpuBackend skips the mesh when only 1 is visible.
    # recovery_after_s = -1 disables the breaker's self-healing (degrade
    # until an operator reset), anything else is the probe cooldown.
    backend = FailoverBackend(
        TpuBackend(mesh_devices=config.tpu.mesh_devices),
        CpuBackend(),
        recovery_after_s=recovery_after_s,
        probe_batch_max=config.tpu.probe_batch_max,
    )
    if quanta:
        t0 = time.monotonic()
        warmed = prewarm_executables(quanta)
        log.info(
            "prewarmed %d verify executables for batch quanta %s in %.1fs "
            "(%s)", len(warmed), quanta, time.monotonic() - t0,
            ", ".join(warmed) or "all cached",
        )
    metrics.gauge("tpu.lanes").set(1)
    log.info(
        "serving plane: single dispatch lane (%d local / %d visible "
        "devices; mesh_devices=%d for in-batch sharding)",
        jax.local_device_count(), jax.device_count(),
        config.tpu.mesh_devices,
    )
    batcher = DynamicBatcher(
        backend,
        max_batch=config.tpu.batch_max,
        window_ms=config.tpu.batch_window_ms,
        pipeline_depth=config.tpu.pipeline_depth,
        shed_expired=config.tpu.shed_expired,
    )
    return backend, batcher


async def cleanup_supervisor(
    state: ServerState,
    stop: asyncio.Event,
    state_file: str | None = None,
    durability=None,
    replica=None,
) -> None:
    """Periodic expiry sweeps under a restart-on-crash supervisor
    (server.rs:168-192); with --state-file, each sweep also checkpoints —
    through the :class:`~cpzk_tpu.durability.DurabilityManager` (snapshot
    + WAL fsync/compaction) when durability is enabled.  An unpromoted
    replication standby only checkpoints: a local expiry sweep would
    journal records into the standby's WAL and fork its sequence numbers
    away from the primary's stream (expired entries are inert anyway —
    validation rejects them lazily and the primary's own sweep records
    replay the removals).  Full sweeps resume once promoted."""

    async def sweep_loop():
        while not stop.is_set():
            try:
                await asyncio.wait_for(stop.wait(), timeout=CLEANUP_INTERVAL_SECONDS)
                return
            except asyncio.TimeoutError:
                pass
            if replica is None or replica.role == "primary":
                nc = await state.cleanup_expired_challenges()
                ns = await state.cleanup_expired_sessions()
                if nc or ns:
                    log.info("cleanup: %d challenges, %d sessions expired", nc, ns)
            if durability is not None:
                await durability.checkpoint()
            elif state_file:
                await state.snapshot(state_file)
            # freeze the surviving object graph out of the cyclic
            # collector's gen-2 scan: at millions of registered users an
            # automatic collection traverses every UserData/SessionData
            # and stalls the event loop for ~a second.  The state graph
            # is acyclic (refcounting frees removed entries regardless),
            # so freezing after each checkpoint keeps the scanned set to
            # recent allocations only.
            gc.freeze()

    while not stop.is_set():
        try:
            await sweep_loop()
            return
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("cleanup task crashed; restarting in %ss", SUPERVISOR_BACKOFF_SECONDS)
            try:
                await asyncio.wait_for(stop.wait(), timeout=SUPERVISOR_BACKOFF_SECONDS)
            except asyncio.TimeoutError:
                pass


HELP = """Available commands:
  /status      (/st)  server status summary (incl. backend breaker state)
  /overload    (/ov)  admission status: level, tiers, clients, pushback
  /tracez [N]  (/tz)  last N completed request traces w/ stage breakdown
  /flightrec [N] (/fr) last N device batches: occupancy, dispatch gap,
                      thread_hop/marshal/compile/execute split, jit hits
  /profile S [DIR]    capture S seconds of jax.profiler (xprof) trace
  /persist     (/wal) durability status: WAL size, fsync age, covered seq
  /audit       (/au)  proof-log status: path, bytes, seq, pending appends
  /replication (/repl) replication status: role, epoch, lag, lease
  /promote            promote this standby to primary (operator failover)
  /handover           coordinated primary→standby handover (zero-loss,
                      bounded write blackout; primary side only)
  /fleet [reload] (/fl) partition-map status; `reload` re-reads the map
                      file and adopts a strictly newer version (splits)
  /controller  (/ctl) fleet controller: mode, cooldowns, last decisions
  /users       (/u)   registered user count
  /sessions    (/s)   active session count
  /challenges  (/c)   pending challenge count
  /cleanup     (/gc)  run an expiry sweep now
  /reset       (/rearm) re-arm the TPU failover breaker
  /help        (/h)   this help
  /quit        (/q)   graceful shutdown"""


async def handle_command(
    cmd: str, state: ServerState, backend=None, durability=None,
    admission=None, replication=None, audit_log=None, fleet=None,
    controller=None,
) -> tuple[str, bool]:
    """(output, should_quit) for one REPL line (server.rs:50-90,261-359).
    ``backend`` is the serving FailoverBackend (None on the inline CPU
    path) — /status surfaces its breaker state, /reset re-arms it;
    ``durability`` is the DurabilityManager behind /persist (None when
    durability is disabled); ``admission`` is the AdmissionController
    behind /overload (None when admission is disabled); ``replication``
    is the SegmentShipper (primary) or StandbyReplica (standby) behind
    /replication and /promote (None when replication is disabled);
    ``audit_log`` is the ProofLogWriter behind /audit (None when the
    audit trail is disabled); ``fleet`` is the FleetRouter behind /fleet
    (None when fleet routing is disabled)."""
    cmd = cmd.strip()
    if not cmd:
        return "", False
    if not cmd.startswith("/"):
        return "Commands must start with '/'. Type /help for available commands.", False
    word = cmd.split()[0].lower()
    if word in ("/status", "/st"):
        u, s, c = (
            await state.user_count(),
            await state.session_count(),
            await state.challenge_count(),
        )
        line = f"users={u} sessions={s} challenges={c}"
        if backend is not None and hasattr(backend, "breaker"):
            line += (
                f" backend={backend.breaker.state.value}"
                f" degraded_for={backend.breaker.degraded_seconds:.1f}s"
                f" expired_shed={int(metrics.read('tpu.queue.expired'))}"
            )
        return line, False
    if word in ("/overload", "/ov"):
        if admission is None:
            return (
                "admission control disabled (set [admission] enabled = true "
                "to get per-client fairness + priority shedding)",
                False,
            )
        s = admission.snapshot()
        tiers = "+".join(s["admitted_tiers"]) or "none"
        return (
            f"level={s['level']:.2f}/3 admitting={tiers}"
            f" clients={s['clients']}/{s['max_clients']}"
            f" (evicted={s['evictions']})"
            f" queue={s['queue_depth']}/{s['queue_capacity']}"
            f" drain={s['drain_rate']:.1f}/s"
            f" util={s['utilization']:.2f}"
            f" queue_wait={s['queue_wait_ms']:.1f}ms"
            f" retry_after={s['retry_after_ms']:.0f}ms"
            f" admitted={int(s['admitted'])}"
            f" shed{{client={int(s['shed_per_client'])}"
            f" priority={int(s['shed_priority'])}"
            f" global={int(s['shed_global'])}}}",
            False,
        )
    if word in ("/tracez", "/traces", "/tz"):
        from ..observability import format_tracez, get_tracer

        parts = cmd.split()
        try:
            limit = int(parts[1]) if len(parts) > 1 else 20
        except ValueError:
            return f"usage: /tracez [N] — not a number: {parts[1]}", False
        # same serializer as the ops plane's HTTP /tracez (one schema)
        return format_tracez(get_tracer().payload(), limit=max(1, limit)), False
    if word in ("/flightrec", "/fr"):
        from ..observability import format_flightrec, get_flight_recorder

        parts = cmd.split()
        try:
            limit = int(parts[1]) if len(parts) > 1 else 20
        except ValueError:
            return f"usage: /flightrec [N] — not a number: {parts[1]}", False
        # same serializer as the HTTP /flightrec and the SIGUSR2 dump
        return format_flightrec(
            get_flight_recorder().payload(), limit=max(1, limit)
        ), False
    if word in ("/profile", "/prof"):
        from ..observability import flightrec as flightrec_mod

        parts = cmd.split()
        if len(parts) < 2:
            return "usage: /profile <seconds> [dir]", False
        try:
            seconds = float(parts[1])
        except ValueError:
            return f"usage: /profile <seconds> [dir] — not a number: {parts[1]}", False
        if not 0 < seconds <= 600:
            return "profile duration must be in (0, 600] seconds", False
        logdir = parts[2] if len(parts) > 2 else (
            f"/tmp/cpzk-xprof-{int(time.time())}"
        )
        if not flightrec_mod.start_profile(logdir):
            return (
                f"a profile capture is already running "
                f"(into {flightrec_mod.profile_active()}); wait for it",
                False,
            )
        try:
            await asyncio.sleep(seconds)
        finally:
            flightrec_mod.stop_profile()
        return (
            f"xprof capture ({seconds:g}s) written to {logdir} — inspect "
            f"with: tensorboard --logdir {logdir} (Profile tab, Trace "
            f"Viewer; the cpzk.* annotations match /tracez stage names)",
            False,
        )
    if word in ("/persist", "/wal"):
        if durability is None or durability.wal is None:
            return (
                "durability disabled (set [durability] enabled = true and a "
                "state_file to get a write-ahead log)",
                False,
            )
        s = durability.status()
        age = s["snapshot_age_s"]
        return (
            f"wal={s['wal_path']} bytes={s['wal_bytes']} seq={s['wal_seq']}"
            f" covered_seq={s['covered_seq']} pending={s['pending_appends']}"
            f" fsync={s['fsync_policy']}"
            f" last_fsync_age={s['last_fsync_age_s']:.1f}s"
            f" snapshot_age={'n/a' if age is None else f'{age:.1f}s'}",
            False,
        )
    if word in ("/audit", "/au"):
        if audit_log is None:
            return (
                "audit trail disabled (set [audit] enabled = true and a "
                "log_path to record verified proofs for offline replay)",
                False,
            )
        s = audit_log.status()
        return (
            f"log={s['path']} bytes={s['bytes']} seq={s['seq']}"
            f" this_boot={s['records_this_boot']}"
            f" pending={s['pending_appends']} fsync={s['fsync_policy']}"
            f" — replay with: python -m cpzk_tpu.audit run --log"
            f" {s['path']} --report <out.json>",
            False,
        )
    if word in ("/replication", "/repl"):
        if replication is None:
            return (
                "replication disabled (set [replication] enabled = true on "
                "a durability-enabled pair to get a warm standby)",
                False,
            )
        s = replication.status()
        if s["role"] == "primary":
            return (
                f"role=primary epoch={s['epoch']} mode={s['mode']}"
                f" peer={s['peer']} wal_seq={s['wal_seq']}"
                f" acked_seq={s['acked_seq']} lag={s['lag_records']}"
                f" segments_shipped={s['segments_shipped']}"
                f" fenced={s['fenced']} gap_stalled={s['gap_stalled']}",
                False,
            )
        lease = s["lease_remaining_s"]
        return (
            f"role={s['role']} epoch={s['epoch']}"
            f" applied_seq={s['applied_seq']} lag={s['lag_records']}"
            f" segments={s['segments_received']}"
            f" (rejected={s['segments_rejected']} fenced={s['fenced']})"
            f" records={s['records_applied']}"
            f" (skipped={s['records_skipped']})"
            f" lease={'unarmed' if lease is None else f'{lease:.2f}s'}",
            False,
        )
    if word in ("/fleet", "/fl"):
        if fleet is None:
            return (
                "fleet routing disabled (set [fleet] enabled = true with a "
                "map_path to join an N-partition fleet)",
                False,
            )
        parts = cmd.split()
        if len(parts) > 1 and parts[1].lower() == "reload":
            try:
                changed = fleet.reload()
            except (OSError, ValueError) as e:
                return f"map reload failed: {e}", False
            if not changed:
                return (
                    f"map unchanged (still v{fleet.map.version} "
                    f"{fleet.map.short_digest()})",
                    False,
                )
        s = fleet.status()
        return (
            f"partition={s['partition']}/{s['partitions']}"
            f" map=v{s['map_version']} digest={s['map_digest']}"
            f" address={s['address']}"
            f" owned={s['owned_span_fraction']:.1%} of keyspace"
            f" redirects={s['redirects']}",
            False,
        )
    if word in ("/controller", "/ctl"):
        if controller is None:
            return (
                "fleet controller disabled (set [controller] enabled = true "
                "to close the signal->actuator loop; dry_run = true to "
                "watch decisions without acting)",
                False,
            )
        s = controller.status()
        lines = [
            f"mode={'DRY-RUN' if s['dry_run'] else 'LIVE'}"
            f" ticks={s['ticks']}"
            f" acting={s['acting']}"
            f" drained_lanes={','.join(s['drained_lanes']) or 'none'}"
            + (
                " cooldowns=" + " ".join(
                    f"{k}:{v:.0f}s" for k, v in s["cooldowns_s"].items()
                ) if s["cooldowns_s"] else ""
            )
        ]
        for row in list(s["decisions"])[-5:]:
            outcome = (
                "FIRED" if row["fired"]
                else f"veto:{row['veto']}" if row["veto"]
                else "dry-run"
            )
            lines.append(
                f"  {row['action']} {row['target']} [{outcome}] "
                f"{row['reason']}"
            )
        if len(lines) == 1:
            lines.append("  (no decisions yet)")
        return "\n".join(lines), False
    if word == "/promote":
        if replication is None or not hasattr(replication, "promote"):
            return (
                "nothing to promote (this node is not a replication "
                "standby)",
                False,
            )
        report = await replication.promote(reason="operator")
        if not report["promoted"]:
            return f"not promoted: {report['message']}", False
        return (
            f"PROMOTED to primary: epoch={report['epoch']}"
            f" applied_seq={report['applied_seq']}"
            f" tail_replayed={report['replayed_tail']}"
            f" torn_bytes={report['truncated_bytes']} — this node now "
            "accepts auth traffic; fence the old primary before reviving it",
            False,
        )
    if word == "/handover":
        if replication is None or not hasattr(replication, "run_handover"):
            return (
                "nothing to hand over (this node is not a replication "
                "primary)",
                False,
            )
        try:
            report = await replication.run_handover(reason="operator")
        except Exception as exc:  # noqa: BLE001 — surface, don't kill REPL
            return (
                f"handover ABORTED: {exc} — pair unchanged, lease "
                "failover still covers a real primary death",
                False,
            )
        return (
            f"HANDOVER complete in {report['duration_s'] * 1000.0:.0f}ms: "
            f"standby promoted at epoch={report['epoch']} "
            f"fence_seq={report['fence_seq']} — this node now redirects "
            "writes to the new primary; drain and restart it",
            False,
        )
    if word in ("/reset", "/rearm"):
        if backend is None or not hasattr(backend, "breaker"):
            return "no failover backend to reset (inline CPU path)", False
        backend.reset()
        return "breaker re-armed: traffic back on the primary backend", False
    if word in ("/users", "/u"):
        return f"registered users: {await state.user_count()}", False
    if word in ("/sessions", "/s"):
        return f"active sessions: {await state.session_count()}", False
    if word in ("/challenges", "/c"):
        return f"pending challenges: {await state.challenge_count()}", False
    if word in ("/cleanup", "/gc"):
        nc = await state.cleanup_expired_challenges()
        ns = await state.cleanup_expired_sessions()
        return f"cleanup done: {nc} challenges, {ns} sessions removed", False
    if word in ("/help", "/h", "/?"):
        return HELP, False
    if word in ("/quit", "/exit", "/q"):
        return "shutting down...", True
    return f"Unknown command: {word}. Type /help for available commands.", False


async def load_state(config: ServerConfig):
    """(state, durability manager | None) for the resolved config.

    With ``[durability] enabled``: full crash recovery — snapshot load with
    corrupt-file quarantine, WAL torn-tail truncation + suffix replay, then
    a fresh covering snapshot so the next boot replays nothing.  Without
    it: the plain snapshot restore, where a corrupt snapshot quarantines
    with a loud ERROR and the server boots empty instead of crash-looping
    on every restart."""
    state = ServerState(
        shards=config.replication.shards,
        max_users=config.server.max_users,
        max_challenges=config.server.max_challenges,
        max_sessions=config.server.max_sessions,
    )
    if config.durability.enabled:
        from ..durability import DurabilityManager

        durability = DurabilityManager(state, config.durability, config.state_file)
        report = await durability.recover()
        log.info(
            "durability: %d users / %d sessions from snapshot, %d WAL records "
            "replayed (%d skipped) up to seq %d",
            report.users, report.sessions, report.replayed, report.skipped,
            report.next_seq,
        )
        # fold the replayed suffix into a fresh covering snapshot now:
        # bounds the next boot's replay and arms compaction
        await durability.checkpoint()
        # a freshly-recovered million-user graph goes straight into the
        # collector's frozen set (see cleanup_supervisor for why)
        gc.collect()
        gc.freeze()
        return state, durability
    if config.state_file and os.path.exists(config.state_file):
        try:
            nu, ns = await state.restore(config.state_file)
            log.info("restored state snapshot: %d users, %d sessions", nu, ns)
        except asyncio.CancelledError:
            raise
        except UnsupportedFormat:
            # newer-format snapshot: not corrupt, the binary is old —
            # refuse to boot rather than quarantining live data
            raise
        except Exception as e:
            from ..durability.recovery import quarantine_file

            dst = quarantine_file(config.state_file, int(time.time()))
            log.error(
                "ERROR: corrupt state snapshot %s (%s); quarantined to %s and "
                "booting with empty state instead of crash-looping",
                config.state_file, e, dst,
            )
    return state, None


def resolve_config(args) -> ServerConfig:
    """defaults < TOML < .env < SERVER_* env < explicitly-provided CLI flags
    (the reference leaves CLI/figment unreconciled — SURVEY.md §3.3)."""
    config = ServerConfig.from_env()
    if args.host is not None:
        config.host = args.host
    if args.port is not None:
        config.port = args.port
    if args.rate_limit is not None:
        config.rate_limit.requests_per_minute = args.rate_limit
    if args.rate_burst is not None:
        config.rate_limit.burst = args.rate_burst
    if args.metrics is not None:
        config.metrics.enabled = args.metrics
    if args.metrics_port is not None:
        config.metrics.port = args.metrics_port
    if args.backend is not None:
        config.tpu.backend = args.backend
    if args.batch_max is not None:
        config.tpu.batch_max = args.batch_max
    if args.batch_window_ms is not None:
        config.tpu.batch_window_ms = args.batch_window_ms
    if args.state_file is not None:
        config.state_file = args.state_file
    config.validate()
    return config


async def amain(args) -> None:
    # resolve config first so .env-provided RUST_LOG/LOG_LEVEL reach logging
    config = resolve_config(args)

    logging.basicConfig(
        level=os.environ.get("RUST_LOG", os.environ.get("LOG_LEVEL", "INFO")).upper(),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )

    # observability: trace ring size, slow-request threshold, histogram
    # buckets, and the (opt-in) JSON log formatter — before any RPC runs
    from ..observability import configure as configure_observability

    configure_observability(config.observability)
    if config.observability.json_logs:
        log.info("structured JSON logging enabled")

    state, durability = await load_state(config)
    limiter = config.rate_limit.build_limiter()
    stop = asyncio.Event()

    metrics_fallback_needed = False
    if config.metrics.enabled:
        if metrics.start_exporter(config.metrics.host, config.metrics.port):
            log.info("metrics exporter on %s:%d", config.metrics.host, config.metrics.port)
        else:
            # satellite fix: this used to return False silently, leaving a
            # configured metrics port with no listener and no log line —
            # now the ops plane serves the facade's own text exposition on
            # that same port, and says so
            metrics_fallback_needed = True
            log.warning(
                "prometheus_client is not installed: the metrics exporter "
                "cannot start; serving the metrics facade's own text "
                "exposition at http://%s:%d/metrics via the ops plane "
                "instead (identical family set)",
                config.metrics.host, config.metrics.port,
            )

    tls = None
    if config.tls.enabled:
        def _read_tls(key_path: str, cert_path: str) -> tuple[bytes, bytes]:
            with open(key_path, "rb") as kf, open(cert_path, "rb") as cf:
                return kf.read(), cf.read()

        tls = await asyncio.to_thread(
            _read_tls, config.tls.key_path, config.tls.cert_path
        )

    from .service import serve

    backend, batcher = build_backend(config)
    if backend is not None:
        log.info(
            "TPU backend enabled (batch_max=%d window=%.1fms, CPU failover armed)",
            config.tpu.batch_max, config.tpu.batch_window_ms,
        )

    admission = None
    if config.admission.enabled:
        from ..admission import AdmissionController

        admission = AdmissionController(config.admission, batcher=batcher)
        log.info(
            "admission control enabled (per_client_rpm=%d, max_clients=%d)",
            config.admission.per_client_rpm, config.admission.max_clients,
        )

    audit_log = None
    if config.audit.enabled:
        from ..audit import ProofLogWriter

        audit_log = ProofLogWriter(
            config.audit.log_path,
            fsync=config.audit.fsync,
            fsync_interval_ms=config.audit.fsync_interval_ms,
            segment_bytes=config.audit.segment_bytes,
        )
        log.info(
            "audit trail enabled: proof log at %s (fsync=%s, seq=%d, "
            "segment_bytes=%d)",
            config.audit.log_path, config.audit.fsync, audit_log.seq,
            config.audit.segment_bytes,
        )

    shipper = None
    replica = None
    if config.replication.enabled:
        from ..replication import SegmentShipper, StandbyReplica

        if config.replication.role == "standby":
            replica = StandbyReplica(
                state, durability, config.replication,
                audit_path=config.audit.log_path or None,
            )
            log.info(
                "replication standby: epoch=%d applied_seq=%d (auth RPCs "
                "refused until promotion; lease %gms, auto_promote=%s)",
                replica.epoch, replica.applied_seq,
                config.replication.lease_ms, config.replication.auto_promote,
            )
        else:
            # sealed proof-log segments ride the same shipping loop as
            # WAL segments, so the audit trail survives machine death too
            shipper = SegmentShipper(
                state, durability, config.replication, audit_log=audit_log
            )
            durability.attach_shipper(shipper)
            if config.replication.mode == "sync":
                state.attach_replication_barrier(shipper.wait_replicated)
            log.info(
                "replication primary: epoch=%d mode=%s -> %s (segment "
                "%d bytes, renew %gms)",
                shipper.epoch, config.replication.mode,
                config.replication.peer, config.replication.segment_bytes,
                config.replication.renew_interval_ms,
            )

    fleet_router = None
    if config.fleet.enabled:
        from ..fleet import FleetRouter, PartitionMap

        pmap = PartitionMap.load(config.fleet.map_path)
        idx = config.fleet.partition
        if idx < 0:
            advertise = config.fleet.advertise or config.addr()
            idx = pmap.index_of_address(advertise)
        fleet_router = FleetRouter(
            pmap, idx, map_path=config.fleet.map_path
        )
        me = pmap.partitions[idx]
        log.info(
            "fleet routing enabled: partition %d/%d (map v%d %s, owns "
            "%.1f%% of the keyspace as %s)",
            idx, len(pmap.partitions), pmap.version, pmap.short_digest(),
            100.0 * me.span() / (1 << 32), me.address,
        )

    # started after the replication block: an unpromoted standby's sweep
    # must checkpoint-only (see cleanup_supervisor)
    cleanup_task = asyncio.create_task(
        cleanup_supervisor(
            state, stop, config.state_file or None, durability, replica
        )
    )

    # ops plane + SLO engine: the remote introspection surface, started
    # BEFORE the gRPC listener so a recovering/standby box is observable
    # before (and whether or not) it takes traffic
    from ..observability.opsplane import OpsPlane, OpsSources
    from ..observability.slo import SloEngine

    slo_engine = SloEngine(config.slo)

    async def slo_ticker() -> None:
        interval = config.slo.tick_interval_ms / 1000.0
        while not stop.is_set():
            try:
                await asyncio.wait_for(stop.wait(), timeout=interval)
                return
            except asyncio.TimeoutError:
                pass
            try:
                slo_engine.tick()
            except Exception:
                log.exception("SLO tick failed; continuing")

    slo_task = asyncio.create_task(slo_ticker())

    if fleet_router is not None:
        # per-partition SLO attribution: the /slo payload (and /statusz
        # rollup) names this partition so fleet dashboards can join
        slo_engine.partition = str(fleet_router.self_index)

    ops_sources = OpsSources(
        state=state,
        batcher=batcher,
        backend=backend,
        admission=admission,
        replication=shipper or replica,
        audit_log=audit_log,
        durability=durability,
        slo=slo_engine,
        fleet=fleet_router,
        config_fingerprint=config.fingerprint(),
        role="standby" if replica is not None else "server",
    )
    ops_plane = None
    if config.opsplane.enabled:
        ops_plane = OpsPlane(
            ops_sources, host=config.opsplane.host, port=config.opsplane.port
        )
        bound = await ops_plane.start()
        log.info(
            "ops plane on http://%s:%d (/metrics /statusz /tracez "
            "/flightrec /healthz /slo)", config.opsplane.host, bound,
        )
    metrics_fallback_plane = None
    if metrics_fallback_needed:
        metrics_fallback_plane = OpsPlane(
            ops_sources, host=config.metrics.host, port=config.metrics.port
        )
        await metrics_fallback_plane.start()

    # sharded ingest ([server] ingest_shards > 1): the dispatch process
    # starts PORTLESS and N SO_REUSEPORT listener processes own the
    # public address, feeding it over the CRC-framed unix-socket seam;
    # ingest_shards = 1 binds in-process — today's path, structurally
    # unchanged (no supervisor is ever constructed)
    shard_ingest = config.server.ingest_shards > 1
    server, port = await serve(
        state, limiter, host=config.host, port=config.port,
        backend=backend, batcher=batcher, tls=tls, admission=admission,
        # a primary exposes the ReplicationService too (the shipper's
        # handler serves the Handover RPC; ship/status answer refusals)
        replica=replica or shipper, audit_log=audit_log,
        stream_window=config.tpu.stream_window,
        stream_entry_deadline_ms=config.tpu.stream_entry_deadline_ms,
        fleet=fleet_router, wire=config.server.wire,
        listen=not shard_ingest,
    )
    ingest = None
    if shard_ingest:
        from .ingest import IngestSupervisor

        ingest = IngestSupervisor(
            server.auth_service, server.health,
            shards=config.server.ingest_shards,
            host=config.host, port=config.port,
            wire=config.server.wire, tls=tls,
        )
        await ingest.start()
        port = config.port
        ops_sources.ingest = ingest
    # late attachments: serve() built these (health gate, stream registry)
    ops_sources.health = server.health
    ops_sources.service = server.auth_service

    # fleet controller ([controller] enabled): the self-driving loop over
    # the planes built above — started after serve() so its first tick
    # already sees the lane router and ingest shards, dry-run by default
    controller = None
    controller_task = None
    if config.controller.enabled:
        from ..fleet.controller import FleetController

        controller = FleetController(
            config.controller,
            state=state,
            router=getattr(batcher, "router", None),
            admission=admission,
            slo=slo_engine,
            fleet=fleet_router,
            durability=durability,
            replica=replica,
            epoch_file=config.replication.epoch_file
            or ((config.state_file + ".epoch") if config.state_file else ""),
            segment_bytes=config.replication.segment_bytes,
        )
        ops_sources.controller = controller

        async def controller_ticker() -> None:
            interval = config.controller.tick_interval_ms / 1000.0
            while not stop.is_set():
                try:
                    await asyncio.wait_for(stop.wait(), timeout=interval)
                    return
                except asyncio.TimeoutError:
                    pass
                try:
                    await controller.tick()
                except Exception:
                    log.exception("controller tick failed; continuing")

        controller_task = asyncio.create_task(controller_ticker())
        log.info(
            "fleet controller %s: tick %gms, act after %d hot ticks, "
            "clear after %d",
            "DRY-RUN (decisions only)" if config.controller.dry_run
            else "LIVE", config.controller.tick_interval_ms,
            config.controller.act_ticks, config.controller.clear_ticks,
        )
    if shipper is not None:
        shipper.start()
    if replica is not None:
        replica.start()
    from .wire import native_available

    log.info(
        "wire path: %s (native parser %savailable)", config.server.wire,
        "" if native_available() else "NOT ",
    )
    print(_c("green", f"AuthService listening on {config.host}:{port}"
             + (f" ({config.server.ingest_shards} ingest shards)"
                if shard_ingest else "")))

    loop = asyncio.get_running_loop()
    # SIGTERM is the planned-operations signal: on a primary with a live
    # standby it runs a coordinated handover before the drain (below).
    # SIGINT stays a plain stop — ^C in a terminal should not fail over.
    term_requested = False

    def _on_term() -> None:
        nonlocal term_requested
        term_requested = True
        stop.set()

    loop.add_signal_handler(signal.SIGINT, stop.set)
    loop.add_signal_handler(signal.SIGTERM, _on_term)

    def dump_flightrec() -> None:
        """SIGUSR2: dump the flight-recorder ring as JSON — the live-
        incident snapshot (``kill -USR2 <pid>``), no REPL needed."""
        from ..observability import get_flight_recorder

        path = os.environ.get(
            "CPZK_FLIGHTREC_DUMP", f"/tmp/cpzk-flightrec-{os.getpid()}.json"
        )
        try:
            get_flight_recorder().dump(path)
            log.info("flight recorder dumped to %s", path)
        except OSError:
            log.exception("flight recorder dump to %s failed", path)

    with contextlib.suppress(NotImplementedError, ValueError, AttributeError):
        # absent on platforms without SIGUSR2 (windows) — REPL still works
        loop.add_signal_handler(signal.SIGUSR2, dump_flightrec)

    async def repl():
        print(_c("cyan", "Admin REPL ready. Type /help for commands."))
        while not stop.is_set():
            try:
                line = await asyncio.to_thread(input, "> ")
            except (EOFError, KeyboardInterrupt):
                stop.set()
                return
            out, quit_ = await handle_command(
                line, state, backend, durability, admission,
                shipper or replica, audit_log, fleet_router,
                controller,
            )
            if out:
                print(_c("white", out))
            if quit_:
                stop.set()
                return

    repl_task = None
    if not args.no_repl and sys.stdin.isatty():
        repl_task = asyncio.create_task(repl())

    await stop.wait()

    # planned operations (ISSUE 18): SIGTERM on a primary with a standby
    # hands ownership over BEFORE the drain — write blackout is one ship
    # RTT + promotion instead of a lease_ms failover window, with zero
    # acked-write loss structurally.  Any failure falls back to the plain
    # drain, loudly: the standby then takes over via ordinary lease expiry.
    if (
        term_requested
        and shipper is not None
        and config.replication.handover_on_term
        and not shipper.fenced
    ):
        print(_c("yellow", "SIGTERM: attempting coordinated handover..."))
        try:
            report = await shipper.run_handover(reason="sigterm")
            print(_c(
                "green",
                f"handover complete: standby promoted at epoch "
                f"{report['epoch']} in {report['duration_s'] * 1000.0:.0f}ms",
            ))
        except Exception:
            log.exception(
                "coordinated handover FAILED; falling back to plain drain "
                "(no/stale standby?) — the standby takes over via lease "
                "expiry instead"
            )

    # graceful shutdown: not-serving -> drain -> stop -> final snapshot
    # (server.rs:379-427); background tasks are cancelled AND awaited so
    # no in-flight sweep races the final snapshot and no "Task was
    # destroyed but it is pending" warnings leak
    print(_c("yellow", "shutdown: flipping health to NOT_SERVING, draining..."))
    server.health.serving = False
    await asyncio.sleep(DRAIN_SECONDS)
    if ingest is not None:
        await ingest.stop()  # listener shards down before the batcher drain
    if batcher is not None:
        await batcher.stop()  # drain queued verifications before the listener
    if audit_log is not None:
        # after the batcher drain: the last verdicts' records are appended
        await asyncio.to_thread(audit_log.close)
        log.info("audit trail closed at seq %d", audit_log.seq)
    if shipper is not None:
        await shipper.stop()  # one final flush tick toward the standby
    if replica is not None:
        await replica.stop()
    await server.stop(grace=5)
    # the ops plane outlives the gRPC listener (it watched the drain);
    # stop it after so the last /statusz of a shutdown is observable
    if ops_plane is not None:
        await ops_plane.stop()
    if metrics_fallback_plane is not None:
        await metrics_fallback_plane.stop()
    if controller_task is not None:
        controller_task.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await controller_task
    slo_task.cancel()
    with contextlib.suppress(asyncio.CancelledError):
        await slo_task
    cleanup_task.cancel()
    with contextlib.suppress(asyncio.CancelledError):
        await cleanup_task
    if durability is not None:
        await durability.close()  # final snapshot + truncate the covered WAL
        log.info(
            "durability: final snapshot written to %s, WAL truncated",
            config.state_file,
        )
    elif config.state_file:
        await state.snapshot(config.state_file)
        log.info("state snapshot written to %s", config.state_file)
    if repl_task is not None:
        repl_task.cancel()
        # the REPL may be blocked in a to_thread(input) that only returns
        # on the next keypress — bound the wait instead of hanging exit
        with contextlib.suppress(asyncio.CancelledError, asyncio.TimeoutError):
            await asyncio.wait_for(repl_task, timeout=1.0)
    print(_c("green", "bye"))


def main() -> None:
    asyncio.run(amain(parse_args()))


if __name__ == "__main__":
    main()
