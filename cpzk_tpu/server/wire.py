"""Native zero-copy wire path for the three hot request messages.

The serving plane's last CPU tax (ROADMAP item 4, PROFILE.md §7c) is the
transport: request bytes -> protobuf message objects -> per-field Python
materialization -> per-proof joins back into packed buffers for the
native parse and the device marshal.  This module closes the loop the
way the reference stack does (tonic decodes proto in native code
straight off the socket): the C++ scanner in ``native/wire.cpp`` indexes
the request's fields in one pass over the socket bytes, the proof wires
are gathered natively into ONE contiguous per-thread staging buffer
(``proofs_packed``), and that buffer flows to
``Proof.from_bytes_batch(packed=...)`` and the dispatch lane's prep
thread without ever being re-joined from per-entry Python objects.

Deserializer contract (the gRPC ``request_deserializer`` seam):

- the native parser accepts only messages it is bit-for-bit sure the
  Python protobuf runtime decodes identically (known fields, valid
  UTF-8, well-formed varints/lengths) — ANYTHING else falls back to
  ``<pb2 class>.FromString`` unconditionally, so a missing ``.so``
  (``CPZK_NO_NATIVE_BUILD=1``), an unknown message shape, or adversarial
  bytes all behave exactly like the Python path, error messages
  included;
- an accepted message yields a ``Native*Request`` view whose attribute
  surface (``user_ids``/``challenge_ids``/``proofs``/``ids``/
  ``mint_sessions``/``user_id``) is list/str/bytes-identical to the
  protobuf message, pinned by ``tests/test_wire.py`` and held on
  arbitrary bytes by ``fuzz/fuzz_wire_parse.py``.

Telemetry: ``transport.parse.native{rpc}`` / ``transport.parse.fallback
{rpc}`` count the two paths, ``transport.parse.duration`` times the
native parse, ``transport.parse.bytes`` totals the bytes it handled, and
each handler attaches a ``wire_parse`` span to its trace so /tracez
shows the parse cost next to the other stages.
"""

from __future__ import annotations

import re
import time

from ..core import _native
from ..observability import get_tracer
from . import metrics

__all__ = [
    "NativeChallengeRequest",
    "NativeBatchVerificationRequest",
    "NativeStreamVerifyRequest",
    "WIRE_MODES",
    "make_deserializer",
    "native_available",
    "note_wire_parse",
]

#: Valid values of the ``[server] wire`` knob.
WIRE_MODES = ("native", "python")

#: The one wire size a valid proof has (gadgets.PROOF_WIRE_SIZE; kept as
#: a local constant so this module stays import-light on the hot path).
_PROOF_WIRE_SIZE = 109


def native_available() -> bool:
    """Whether the native wire parser is loadable on this host."""
    return _native.wire_lib() is not None


# -- bulk materialization helpers --------------------------------------------
#
# The protobuf runtime (upb) materializes repeated fields in C at
# ~0.16 us/entry; a naive per-entry Python slice loop costs ~0.9 us.  The
# helpers below keep materialization in C for the canonical shapes: one
# native gather into a contiguous blob, then one fixed-stride re.findall
# (uniform-length fields: every 109-byte proof, every tagged challenge
# id) or one whole-blob utf-8 decode + str slicing for user ids.

_STRIDE_RE: dict[int, re.Pattern] = {}


def _stride_split(packed: bytes, stride: int) -> list[bytes]:
    pat = _STRIDE_RE.get(stride)
    if pat is None:
        pat = _STRIDE_RE[stride] = re.compile(
            (".{%d}" % stride).encode(), re.S
        )
    return pat.findall(packed)


def _lens_list(lens, n: int) -> list[int]:
    return lens[:n] if n else []


def _gather_bytes(data: bytes, offs, lens, n: int, lens_l: list[int]):
    """(items, packed_or_None): one native gather + stride split when the
    lengths are uniform, else per-entry slices (rare: hand-built or
    adversarial requests)."""
    if n == 0:
        return [], b""
    total = sum(lens_l)
    uniform = lens_l[0] if total == lens_l[0] * n else 0
    if uniform > 0:
        packed = _native.wire_gather(data, offs, lens, n, total)
        return _stride_split(packed, uniform), packed
    return [bytes(data[o:o + l]) for o, l in zip(offs[:n], lens_l)], None


def _gather_strs(data: bytes, offs, lens, n: int) -> list[str]:
    if n == 0:
        return []
    lens_l = _lens_list(lens, n)
    blob = _native.wire_gather(data, offs, lens, n, sum(lens_l))
    text = blob.decode("utf-8")  # per-field UTF-8 already validated in C
    if blob.isascii():  # byte offsets == char offsets: slice one str
        out = []
        pos = 0
        for ln in lens_l:
            out.append(text[pos:pos + ln])
            pos += ln
        return out
    return [str(data[o:o + l], "utf-8") for o, l in zip(offs[:n], lens_l)]


# -- request views ------------------------------------------------------------


class NativeChallengeRequest:
    """``auth.ChallengeRequest`` decoded by the native parser."""

    __slots__ = ("user_id", "_parse_s")

    def __init__(self, user_id: str, parse_s: float = 0.0):
        self.user_id = user_id
        self._parse_s = parse_s


class NativeBatchVerificationRequest:
    """``auth.BatchVerificationRequest`` decoded by the native parser.

    ``proofs_packed`` is the zero-copy payoff: when every proof wire has
    the canonical 109-byte size, the proofs were gathered natively into
    ONE contiguous buffer straight off the socket bytes —
    ``Proof.from_bytes_batch(packed=...)`` validates it in a single
    native pass with no Python re-join."""

    __slots__ = ("user_ids", "challenge_ids", "proofs", "proofs_packed",
                 "_parse_s")

    def __init__(self, user_ids, challenge_ids, proofs, proofs_packed,
                 parse_s: float = 0.0):
        self.user_ids = user_ids
        self.challenge_ids = challenge_ids
        self.proofs = proofs
        self.proofs_packed = proofs_packed
        self._parse_s = parse_s

    def packed_proofs(self, count: int):
        """The packed proof buffer when it covers exactly the first
        ``count`` == all proofs at canonical size, else None (callers
        that screened a subset fall back to the join path)."""
        packed = self.proofs_packed
        if packed is not None and count == len(self.proofs):
            return packed
        return None


class NativeStreamVerifyRequest:
    """One ``auth.StreamVerifyRequest`` chunk decoded by the native
    parser (same packed-proofs contract as the batch view)."""

    __slots__ = ("ids", "user_ids", "challenge_ids", "proofs",
                 "proofs_packed", "mint_sessions", "_parse_s")

    def __init__(self, ids, user_ids, challenge_ids, proofs, proofs_packed,
                 mint_sessions: bool, parse_s: float = 0.0):
        self.ids = ids
        self.user_ids = user_ids
        self.challenge_ids = challenge_ids
        self.proofs = proofs
        self.proofs_packed = proofs_packed
        self.mint_sessions = mint_sessions
        self._parse_s = parse_s

    def packed_proofs(self, count: int):
        packed = self.proofs_packed
        if packed is not None and count == len(self.proofs):
            return packed
        return None


# -- parsers ------------------------------------------------------------------


def _parse_challenge(data: bytes):
    idx = _native.wire_index(_native.WIRE_CHALLENGE, data)
    if idx is None:
        return None
    counts, offs, lens, _vals, _mint = idx
    n = counts[0]
    if n == 0:
        return NativeChallengeRequest("")  # absent field: proto3 default
    o, ln = offs[0][n - 1], lens[0][n - 1]  # last occurrence wins
    return NativeChallengeRequest(str(data[o:o + ln], "utf-8"))


def _parse_batch_verify(data: bytes):
    idx = _native.wire_index(_native.WIRE_BATCH_VERIFY, data)
    if idx is None:
        return None
    counts, offs, lens, _vals, _mint = idx
    user_ids = _gather_strs(data, offs[0], lens[0], counts[0])
    cids, _ = _gather_bytes(
        data, offs[1], lens[1], counts[1], _lens_list(lens[1], counts[1])
    )
    plens = _lens_list(lens[2], counts[2])
    proofs, packed = _gather_bytes(data, offs[2], lens[2], counts[2], plens)
    if packed is not None and (not plens or plens[0] != _PROOF_WIRE_SIZE):
        packed = None  # uniform but not proof-sized: no fast-parse claim
    return NativeBatchVerificationRequest(user_ids, cids, proofs, packed)


def _parse_stream_chunk(data: bytes):
    idx = _native.wire_index(_native.WIRE_STREAM_CHUNK, data)
    if idx is None:
        return None
    counts, offs, lens, vals, mint = idx
    ids = vals[:counts[3]] if counts[3] else []
    user_ids = _gather_strs(data, offs[0], lens[0], counts[0])
    cids, _ = _gather_bytes(
        data, offs[1], lens[1], counts[1], _lens_list(lens[1], counts[1])
    )
    plens = _lens_list(lens[2], counts[2])
    proofs, packed = _gather_bytes(data, offs[2], lens[2], counts[2], plens)
    if packed is not None and (not plens or plens[0] != _PROOF_WIRE_SIZE):
        packed = None
    return NativeStreamVerifyRequest(ids, user_ids, cids, proofs, packed, mint)


_PARSERS = {
    "CreateChallenge": _parse_challenge,
    "VerifyProofBatch": _parse_batch_verify,
    "VerifyProofStream": _parse_stream_chunk,
}


def make_deserializer(rpc: str, pb2_cls):
    """Native-first request deserializer for one of the three hot RPCs:
    tries the native parser, falls back to ``pb2_cls.FromString`` for
    anything outside its recognized subset (including EVERY malformed
    input, so rejection semantics are the protobuf runtime's own).
    Returns None for RPCs without a native parser — the caller keeps
    the plain ``FromString``."""
    parser = _PARSERS.get(rpc)
    if parser is None:
        return None
    native_ctr = metrics.counter(
        "transport.parse.native", labelnames=("rpc",)
    ).labels(rpc=rpc)
    fallback_ctr = metrics.counter(
        "transport.parse.fallback", labelnames=("rpc",)
    ).labels(rpc=rpc)
    bytes_ctr = metrics.counter("transport.parse.bytes")
    duration = metrics.histogram("transport.parse.duration")

    def deserialize(data: bytes):
        t0 = time.perf_counter()
        view = parser(data)
        if view is None:
            fallback_ctr.inc()
            return pb2_cls.FromString(data)
        dt = time.perf_counter() - t0
        view._parse_s = dt
        native_ctr.inc()
        bytes_ctr.inc(len(data))
        duration.observe(dt)
        return view

    return deserialize


def note_wire_parse(request, trace_id: str | None) -> None:
    """Attach the native parse cost as a ``wire_parse`` span on the
    RPC's trace (no-op for protobuf-parsed requests): /tracez then shows
    the transport decode next to queue/device stages."""
    parse_s = getattr(request, "_parse_s", 0.0)
    if not parse_s or not trace_id:
        return
    now = time.monotonic()
    get_tracer().add_span(
        trace_id, "wire_parse", now - parse_s, parse_s, path="native"
    )
