"""Multi-chip serving plane: per-device dispatch lanes behind a
deadline-aware router.

Everything the serving stack built through PR 10 — batcher, dispatch
lane, stream, flight recorder — drives ONE device.  The 8-device mesh
passes every sharded path offline (``MULTICHIP_r05.json``), and
PROFILE.md §1 puts the verify ceiling at 50–300k proofs/s *per chip*:
the 1M proofs/s north star needs all eight.  This module graduates the
mesh into the serving path:

- **N per-device lanes**: one :class:`~cpzk_tpu.server.dispatch
  .DispatchLane` per local device, each holding its own backend handle
  pinned to its chip (``TpuBackend(device=...)`` — staging transfers via
  ``jax.device_put``-targeted ``wires_to_device``, jit/AOT executables
  compiled per device, per-thread staging buffers falling out of the
  lane's persistent device thread).  Eight chips, eight independent
  batch streams, no collective anywhere on the hot path.

- **Deadline-aware placement**: each settled batch goes to the lane with
  the shortest *predicted completion* — pending entries over the lane's
  drain-rate EWMA (a cold lane borrows the fleet's mean rate) — so a
  slow or backlogged chip sheds new work to its siblings instead of
  growing its queue.  Ties break round-robin.

- **Per-lane breaker**: PR 1's :class:`~cpzk_tpu.resilience.breaker
  .CircuitBreaker` wrapped per device.  A backend raise opens only that
  lane's breaker; the router skips OPEN lanes, so one sick chip degrades
  the fleet by exactly one lane while the other seven serve.  After the
  cooldown the breaker goes HALF_OPEN and the next batch routes to the
  sick lane as its *probe*: success re-closes (lane re-admitted),
  failure re-opens.  With every breaker OPEN the router routes anyway
  (least-loaded) — refusing all work is strictly worse than trying.

- **Mesh path for big batches**: at or above ``mesh_threshold`` entries
  (a *measured* ``[tpu]`` knob, default off) a batch routes to a
  dedicated mesh lane whose backend shards it over all lane devices via
  the existing ``sharded_*`` kernels under one ``batch_mesh()`` — the
  quantum where one ICI reduction beats N independent programs is
  silicon-specific, so the crossover ships as a knob, not a guess.

The single-lane configuration (``[tpu] lanes = 1``, the default) never
constructs a router: :class:`~cpzk_tpu.server.batching.DynamicBatcher`
keeps its direct lane exactly as PR 7 shipped it, so single-device hosts
pay zero new hot-path cost (pinned by the CPU e2e perf gate).

Offline hosts (the bulk audit pipeline) attach via
:meth:`LaneRouter.start_in_thread` + :meth:`LaneRouter.verify_blocking`,
which fans each quantum across every routable lane from a daemon-thread
event loop — the first consumer that can saturate all lanes.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from dataclasses import dataclass

from ..core.rng import SecureRng
from ..resilience.breaker import (
    ROUTE_FALLBACK,
    ROUTE_PRIMARY,
    ROUTE_PROBE,
    BreakerState,
    CircuitBreaker,
)
from . import metrics
from .dispatch import DispatchLane, LaneStopped

log = logging.getLogger("cpzk_tpu.server.router")

#: Lane label of the mesh path in metrics / flight records / statusz.
MESH_LANE = "mesh"


@dataclass
class _LaneSlot:
    """One routable lane: its dispatch lane, breaker, and load signals."""

    lane: DispatchLane
    breaker: CircuitBreaker
    device: object | None = None
    label: str = "0"
    pending: int = 0          # entries submitted, not yet settled
    dispatches: int = 0
    errors: int = 0
    drain_rate: float = 0.0   # entries/s EWMA
    drained_at: float | None = None
    probes: int = 0
    stages_lane: int | str = 0
    drained: bool = False     # administratively out of rotation (the
                              # fleet controller's brownout actuator);
                              # distinct from the breaker, which keeps
                              # probing a drained lane's sick backend

    def note_drain(self, n: int, now: float) -> None:
        if self.drained_at is not None:
            dt = now - self.drained_at
            if dt > 0:
                inst = n / dt
                self.drain_rate = (
                    inst if self.drain_rate == 0.0
                    else 0.8 * self.drain_rate + 0.2 * inst
                )
        self.drained_at = now


class LaneRouter:
    """Deadline-aware placement over N per-device dispatch lanes (see
    module docstring).

    ``backends`` is one verifier backend per lane, each already pinned
    to its device; ``devices`` is the matching device list (None entries
    allowed — CPU lane emulation).  ``mesh_backend`` (optional) serves
    batches of ``mesh_threshold``+ entries through the sharded kernels.
    """

    def __init__(
        self,
        backends: list,
        devices: list | None = None,
        rng: SecureRng | None = None,
        overlap: bool = True,
        staging_slots: int = 1,
        recovery_after_s: float | None = 30.0,
        mesh_backend=None,
        mesh_threshold: int = 0,
        clock=time.monotonic,
    ):
        if not backends:
            raise ValueError("LaneRouter needs at least one lane backend")
        if devices is not None and len(devices) != len(backends):
            raise ValueError(
                f"{len(backends)} lane backends but {len(devices)} devices"
            )
        self._rng = rng or SecureRng()
        self._clock = clock
        self._lock = threading.Lock()
        self._rr = 0  # tie-break rotation
        self._slots: list[_LaneSlot] = []
        for i, backend in enumerate(backends):
            device = devices[i] if devices is not None else None
            self._slots.append(self._make_slot(
                backend, str(i), i, device,
                overlap=overlap, staging_slots=staging_slots,
                recovery_after_s=recovery_after_s,
            ))
        self._mesh_slot: _LaneSlot | None = None
        self._mesh_threshold = max(0, mesh_threshold)
        if mesh_backend is not None and self._mesh_threshold > 0:
            self._mesh_slot = self._make_slot(
                mesh_backend, MESH_LANE, MESH_LANE, None,
                overlap=overlap, staging_slots=staging_slots,
                recovery_after_s=recovery_after_s,
            )
        self._started = False
        self._stopping = False
        self._thread: threading.Thread | None = None
        self._thread_loop: asyncio.AbstractEventLoop | None = None

    def _make_slot(
        self, backend, label: str, stages_lane, device,
        overlap: bool, staging_slots: int, recovery_after_s: float | None,
    ) -> _LaneSlot:
        slot = _LaneSlot(
            lane=DispatchLane(
                backend, rng=self._rng, overlap=overlap,
                staging_slots=staging_slots, name=f"cpzk-lane{label}",
            ),
            breaker=CircuitBreaker(
                recovery_after_s=recovery_after_s, clock=self._clock,
                on_transition=self._transition_hook(label),
            ),
            device=device,
            label=label,
            stages_lane=stages_lane,
        )
        return slot

    def _transition_hook(self, label: str):
        def hook(old: BreakerState, new: BreakerState) -> None:
            level = logging.WARNING if new is BreakerState.OPEN else logging.INFO
            log.log(
                level, "lane %s breaker %s -> %s%s", label, old.value,
                new.value,
                " (lane skipped until probe succeeds)"
                if new is BreakerState.OPEN else "",
            )
            try:
                from ..observability import get_tracer

                get_tracer().record_event(
                    "lane_breaker", lane=label, old=old.value, new=new.value,
                )
            except Exception:  # pragma: no cover - observability optional
                pass

        return hook

    # -- lifecycle -----------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._started and not self._stopping

    @property
    def lane_count(self) -> int:
        return len(self._slots)

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for slot in self._all_slots():
            slot.lane.start()
        metrics.gauge("tpu.lanes").set(len(self._slots))

    async def stop(self) -> None:
        """Drain-then-join every lane: each lane resolves every accepted
        future exactly once (the DispatchLane shutdown contract, fanned
        out over N lanes)."""
        self._stopping = True
        await asyncio.gather(*[s.lane.stop() for s in self._all_slots()])

    def _all_slots(self) -> list[_LaneSlot]:
        slots = list(self._slots)
        if self._mesh_slot is not None:
            slots.append(self._mesh_slot)
        return slots

    # -- placement -----------------------------------------------------------

    def _predicted_s(self, slot: _LaneSlot, n: int, mean_rate: float) -> float:
        """Predicted completion (seconds) of n more entries on this lane:
        queue depth over drain rate.  A lane that has never drained
        borrows the fleet's mean rate so cold lanes still fill."""
        rate = slot.drain_rate if slot.drain_rate > 0 else mean_rate
        backlog = slot.pending + n
        return backlog / rate if rate > 0 else float(backlog)

    def _pick(self, n: int) -> tuple[_LaneSlot, bool]:
        """(slot, is_probe) for one batch.  Mesh routing happens in
        :meth:`submit` before this runs; here only the per-device lanes
        compete."""
        with self._lock:
            # a drained lane takes no PRIMARY placement but still gets its
            # HALF_OPEN probes — recovery must stay provable while the
            # fleet controller holds the lane out of rotation, or it could
            # never earn re-admission.
            routable: list[_LaneSlot] = []
            probe: _LaneSlot | None = None
            for slot in self._slots:
                route = slot.breaker.acquire()
                if route == ROUTE_PRIMARY:
                    if not slot.drained:
                        routable.append(slot)
                elif route == ROUTE_PROBE and probe is None:
                    probe = slot  # this batch becomes the lane's probe
            if probe is not None:
                probe.probes += 1
                return probe, True
            # all OPEN (or all drained): route anyway — refusing every
            # batch is strictly worse than trying the sick pool
            pool = (
                routable
                or [s for s in self._slots if not s.drained]
                or self._slots
            )
            if not routable:
                metrics.counter("tpu.lane.all_open").inc()
            rates = [s.drain_rate for s in pool if s.drain_rate > 0]
            mean_rate = sum(rates) / len(rates) if rates else 0.0
            self._rr += 1
            best = min(
                range(len(pool)),
                key=lambda k: (
                    self._predicted_s(pool[k], n, mean_rate),
                    (k + self._rr) % len(pool),
                ),
            )
            return pool[best], False

    # -- submission (event-loop side) ----------------------------------------

    def submit(self, entries: list, stages) -> asyncio.Future:
        """Route one settled batch to a lane; returns the lane's future.
        Raises :class:`LaneStopped` once :meth:`stop` has begun (the
        batcher falls back to its inline seam, same as the single-lane
        path)."""
        if not self.running:
            raise LaneStopped("lane router is not accepting work")
        slot: _LaneSlot | None = None
        probe = False
        if (
            self._mesh_slot is not None
            and len(entries) >= self._mesh_threshold
        ):
            # big-batch mesh path: one sharded program over all chips.
            # The acquire doubles as the mesh breaker's routing decision:
            # after a mesh blow-up, big batches fall back to per-device
            # placement until a HALF_OPEN probe batch succeeds.
            route = self._mesh_slot.breaker.acquire()
            if route != ROUTE_FALLBACK:
                slot = self._mesh_slot
                probe = route == ROUTE_PROBE
                if probe:
                    with self._lock:
                        slot.probes += 1
        if slot is None:
            slot, probe = self._pick(len(entries))
        if stages is not None:
            stages.lane = slot.stages_lane
        n = len(entries)
        with self._lock:
            slot.pending += n
            slot.dispatches += 1
        try:
            fut = slot.lane.submit(entries, stages)
        except LaneStopped:
            with self._lock:
                slot.pending = max(0, slot.pending - n)
                slot.dispatches -= 1
            if probe:
                slot.breaker.release_probe()
            raise
        metrics.counter(
            "tpu.lane.dispatches", labelnames=("lane",)
        ).labels(lane=slot.label).inc()
        metrics.gauge(
            "tpu.lane.depth", labelnames=("lane",)
        ).labels(lane=slot.label).set(slot.pending)
        fut.add_done_callback(
            lambda f, s=slot, k=n, p=probe: self._settled(s, k, p, f)
        )
        return fut

    def _settled(self, slot: _LaneSlot, n: int, probe: bool, fut) -> None:
        now = self._clock()
        if fut.cancelled():
            exc: BaseException | None = None
            outcome_known = False
        else:
            exc = fut.exception()
            outcome_known = True
        with self._lock:
            slot.pending = max(0, slot.pending - n)
            if outcome_known and exc is None:
                slot.note_drain(n, now)
            if exc is not None:
                slot.errors += 1
            pending = slot.pending
        metrics.gauge(
            "tpu.lane.depth", labelnames=("lane",)
        ).labels(lane=slot.label).set(pending)
        if not outcome_known:
            # cancelled future: nobody observed the verify — hand an
            # unused probe back so the NEXT batch probes immediately
            if probe:
                slot.breaker.release_probe()
            return
        if exc is not None:
            metrics.counter(
                "tpu.lane.errors", labelnames=("lane",)
            ).labels(lane=slot.label).inc()
            if probe:
                slot.breaker.probe_failed()
            else:
                if slot.breaker.record_failure():
                    log.warning(
                        "lane %s backend raised (%s): breaker OPEN, "
                        "routing around it", slot.label, exc,
                    )
        elif probe:
            slot.breaker.probe_succeeded()
            log.info("lane %s probe succeeded: lane re-admitted", slot.label)

    # -- introspection -------------------------------------------------------

    def status(self) -> dict:
        """The ``/statusz`` lanes block: one row per lane plus the mesh
        lane when configured."""
        with self._lock:
            rows = [self._slot_row(s) for s in self._slots]
            mesh = (
                self._slot_row(self._mesh_slot)
                if self._mesh_slot is not None else None
            )
        return {
            "lanes": rows,
            "mesh": mesh,
            "mesh_threshold": self._mesh_threshold,
        }

    def _slot_row(self, slot: _LaneSlot) -> dict:
        ingress, staged = slot.lane.depths()
        return {
            "lane": slot.label,
            "device": str(slot.device) if slot.device is not None else None,
            "breaker": slot.breaker.state.value,
            "dispatches": slot.dispatches,
            "errors": slot.errors,
            "probes": slot.probes,
            "pending_entries": slot.pending,
            "queued_batches": ingress + staged,
            "drain_rate_per_s": round(slot.drain_rate, 3),
            "drained": slot.drained,
        }

    # -- administrative drain (fleet controller actuator) --------------------

    def lane_states(self) -> list[dict]:
        """Per-device lane signal rows for the fleet controller: label,
        breaker state, drained flag, pending depth.  Mesh lane excluded —
        the controller rebalances the per-device pool only."""
        with self._lock:
            return [
                {
                    "lane": s.label,
                    "breaker": s.breaker.state.value,
                    "drained": s.drained,
                    "pending": s.pending,
                }
                for s in self._slots
            ]

    def drain_lane(self, label: str) -> bool:
        """Take one per-device lane out of placement rotation (its pending
        work still settles; new batches rebalance across siblings).  True
        when the flag flipped, False for unknown labels or no-ops."""
        return self._set_drained(label, True)

    def readmit_lane(self, label: str) -> bool:
        """Put a drained lane back in rotation.  The breaker still rules:
        a re-admitted lane whose backend is sick re-opens on its own."""
        return self._set_drained(label, False)

    def _set_drained(self, label: str, drained: bool) -> bool:
        with self._lock:
            for slot in self._slots:
                if slot.label == label and slot.drained != drained:
                    slot.drained = drained
                    break
            else:
                return False
        metrics.gauge(
            "tpu.lane.drained", labelnames=("lane",)
        ).labels(lane=label).set(1.0 if drained else 0.0)
        log.warning(
            "lane %s %s rotation", label,
            "drained from" if drained else "re-admitted to",
        )
        return True

    def breakers(self) -> list[CircuitBreaker]:
        """Per-lane breakers, lane order (REPL /reset re-arms them all)."""
        return [s.breaker for s in self._all_slots()]

    def reset(self) -> None:
        for breaker in self.breakers():
            breaker.reset()

    # -- offline (synchronous-host) attachment -------------------------------

    def start_in_thread(self) -> None:
        """Run the router's event loop on a daemon thread — the
        attachment point for synchronous hosts (the bulk audit
        pipeline), mirroring ``OpsPlane.start_in_thread``."""
        if self._thread is not None:
            return
        ready = threading.Event()

        def run() -> None:
            loop = asyncio.new_event_loop()
            self._thread_loop = loop
            loop.call_soon(self.start)
            loop.call_soon(ready.set)
            loop.run_forever()

        self._thread = threading.Thread(
            target=run, name="cpzk-lane-router", daemon=True
        )
        self._thread.start()
        ready.wait(timeout=10.0)

    def stop_thread(self) -> None:
        """Drain every lane and stop a :meth:`start_in_thread` loop."""
        loop = self._thread_loop
        if loop is None:
            return
        done = asyncio.run_coroutine_threadsafe(self.stop(), loop)
        done.result(timeout=600.0)
        loop.call_soon_threadsafe(loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        self._thread = None
        self._thread_loop = None

    def verify_blocking(self, entries: list) -> list:
        """Fan one quantum across every lane and return per-entry results
        in entry order — the synchronous bulk seam the audit pipeline
        replays through (placement, breakers, and per-lane metrics all
        engaged, exactly like serving traffic).  Entries split into
        ``lane_count`` contiguous slices so every chip gets one program;
        slicing never changes accept/reject semantics (the combined
        check's verify_each fallback is per-row ground truth)."""
        if self._thread_loop is None:
            raise RuntimeError(
                "verify_blocking needs start_in_thread() first"
            )
        if not entries:
            return []
        per = -(-len(entries) // len(self._slots))
        slices = [
            entries[lo: lo + per] for lo in range(0, len(entries), per)
        ]

        async def fan() -> list:
            futs = [self.submit(s, None) for s in slices]
            parts = await asyncio.gather(*futs)
            return [r for part in parts for r in part]

        return asyncio.run_coroutine_threadsafe(
            fan(), self._thread_loop
        ).result()
