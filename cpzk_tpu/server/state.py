"""In-memory server state: users, single-use challenges, sessions.

Reference parity (``src/verifier/state.rs``): same TTLs (challenge 300 s
with a 2x-age clock-skew guard, session 3600 s), per-user caps (3
challenges, 5 sessions), global caps (10k users / 50k challenges / 100k
sessions), consume-once challenge semantics, and cleanup sweeps.

Design deviation (deliberate): ONE ``asyncio.Lock`` guards all five maps.
The reference takes five ``RwLock``s in inconsistent order between
``create_challenge`` and ``consume_challenge`` (``state.rs:165-167`` vs
``:205-206``) — a deadlock hazard under contention flagged in SURVEY.md §5;
a single lock removes the hazard and is not a throughput bottleneck next to
group operations.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field

from ..errors import InvalidParams
from ..protocol.gadgets import Statement

CHALLENGE_EXPIRY_SECONDS = 300
MAX_CHALLENGES_PER_USER = 3
SESSION_EXPIRY_SECONDS = 3600
MAX_SESSIONS_PER_USER = 5

MAX_TOTAL_USERS = 10_000
MAX_TOTAL_CHALLENGES = 50_000
MAX_TOTAL_SESSIONS = 100_000


def _now() -> int:
    return int(time.time())


@dataclass
class UserData:
    user_id: str
    statement: Statement
    registered_at: int


@dataclass
class ChallengeData:
    challenge_id: bytes
    user_id: str
    created_at: int = field(default_factory=_now)
    expires_at: int = 0

    def __post_init__(self) -> None:
        if not self.expires_at:
            self.expires_at = self.created_at + CHALLENGE_EXPIRY_SECONDS

    def is_expired(self) -> bool:
        """TTL check with the reference's 2x-age clock-skew guard
        (state.rs:101-111)."""
        now = _now()
        age = max(0, now - self.created_at)
        return now >= self.expires_at or age >= 2 * CHALLENGE_EXPIRY_SECONDS


@dataclass
class SessionData:
    token: str
    user_id: str
    created_at: int = field(default_factory=_now)
    expires_at: int = 0

    def __post_init__(self) -> None:
        if not self.expires_at:
            self.expires_at = self.created_at + SESSION_EXPIRY_SECONDS

    def is_expired(self) -> bool:
        return _now() >= self.expires_at


class ServerState:
    """All server registries behind one lock (see module docstring)."""

    def __init__(self) -> None:
        self._lock = asyncio.Lock()
        self._users: dict[str, UserData] = {}
        self._challenges: dict[bytes, ChallengeData] = {}
        self._user_challenges: dict[str, list[bytes]] = {}
        self._sessions: dict[str, SessionData] = {}
        self._user_sessions: dict[str, list[str]] = {}

    # --- users (state.rs:136-161) ---

    async def register_user(self, user_data: UserData) -> None:
        async with self._lock:
            if len(self._users) >= MAX_TOTAL_USERS:
                raise InvalidParams(
                    f"Server has reached maximum user capacity ({MAX_TOTAL_USERS})"
                )
            if user_data.user_id in self._users:
                raise InvalidParams(f"User '{user_data.user_id}' already registered")
            self._users[user_data.user_id] = user_data

    async def get_user(self, user_id: str) -> UserData | None:
        async with self._lock:
            return self._users.get(user_id)

    # --- challenges (state.rs:164-249) ---

    async def create_challenge(self, user_id: str, challenge_id: bytes) -> int:
        async with self._lock:
            if len(self._challenges) >= MAX_TOTAL_CHALLENGES:
                raise InvalidParams(
                    f"Server has reached maximum challenge capacity ({MAX_TOTAL_CHALLENGES})"
                )
            if user_id not in self._users:
                raise InvalidParams(f"User '{user_id}' not found")
            per_user = self._user_challenges.setdefault(user_id, [])
            if len(per_user) >= MAX_CHALLENGES_PER_USER:
                raise InvalidParams(f"Too many active challenges for user '{user_id}'")
            data = ChallengeData(challenge_id=challenge_id, user_id=user_id)
            per_user.append(challenge_id)
            self._challenges[challenge_id] = data
            return data.expires_at

    async def get_challenge(self, challenge_id: bytes) -> ChallengeData | None:
        async with self._lock:
            return self._challenges.get(challenge_id)

    async def consume_challenge(self, challenge_id: bytes) -> ChallengeData:
        """Single-use removal; expired challenges are removed AND rejected."""
        async with self._lock:
            data = self._challenges.get(challenge_id)
            if data is None:
                raise InvalidParams("Invalid or expired challenge")
            del self._challenges[challenge_id]
            per_user = self._user_challenges.get(data.user_id)
            if per_user is not None and challenge_id in per_user:
                per_user.remove(challenge_id)
            if data.is_expired():
                raise InvalidParams("Invalid or expired challenge")
            return data

    async def cleanup_expired_challenges(self) -> int:
        async with self._lock:
            expired = [cid for cid, d in self._challenges.items() if d.is_expired()]
            for cid in expired:
                data = self._challenges.pop(cid)
                per_user = self._user_challenges.get(data.user_id)
                if per_user is not None and cid in per_user:
                    per_user.remove(cid)
            return len(expired)

    # --- sessions (state.rs:252-327) ---

    async def create_session(self, token: str, user_id: str) -> None:
        async with self._lock:
            if len(self._sessions) >= MAX_TOTAL_SESSIONS:
                raise InvalidParams(
                    f"Server has reached maximum session capacity ({MAX_TOTAL_SESSIONS})"
                )
            per_user = self._user_sessions.setdefault(user_id, [])
            if len(per_user) >= MAX_SESSIONS_PER_USER:
                raise InvalidParams(
                    f"User '{user_id}' has reached maximum session limit ({MAX_SESSIONS_PER_USER})"
                )
            self._sessions[token] = SessionData(token=token, user_id=user_id)
            per_user.append(token)

    async def validate_session(self, token: str) -> str:
        async with self._lock:
            data = self._sessions.get(token)
            if data is None:
                raise InvalidParams("Invalid session token")
            if data.is_expired():
                raise InvalidParams("Session expired")
            return data.user_id

    async def revoke_session(self, token: str) -> None:
        async with self._lock:
            data = self._sessions.pop(token, None)
            if data is None:
                raise InvalidParams("Session not found")
            per_user = self._user_sessions.get(data.user_id)
            if per_user is not None and token in per_user:
                per_user.remove(token)

    async def cleanup_expired_sessions(self) -> int:
        async with self._lock:
            expired = [t for t, d in self._sessions.items() if d.is_expired()]
            for t in expired:
                data = self._sessions.pop(t)
                per_user = self._user_sessions.get(data.user_id)
                if per_user is not None and t in per_user:
                    per_user.remove(t)
            return len(expired)

    # --- counts (state.rs:330-342) ---

    async def user_count(self) -> int:
        async with self._lock:
            return len(self._users)

    async def session_count(self) -> int:
        async with self._lock:
            return len(self._sessions)

    async def challenge_count(self) -> int:
        async with self._lock:
            return len(self._challenges)
