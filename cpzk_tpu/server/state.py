"""In-memory server state: users, single-use challenges, sessions.

Reference parity (``src/verifier/state.rs``): same TTLs (challenge 300 s
with a 2x-age clock-skew guard, session 3600 s), per-user caps (3
challenges, 5 sessions), global caps (10k users / 50k challenges / 100k
sessions), consume-once challenge semantics, and cleanup sweeps.

Design deviation (deliberate): the registries are split into
``NUM_STATE_SHARDS`` independently-locked shards keyed by a stable hash
of the owning ``user_id``.  The reference takes five ``RwLock``s in
inconsistent order between ``create_challenge`` and ``consume_challenge``
(``state.rs:165-167`` vs ``:205-206``) — a deadlock hazard under
contention flagged in SURVEY.md §5; here everything about one user
(registration, challenges, per-user lists, sessions) lives behind ONE
shard lock, so no operation ever holds two locks and distinct users stop
serializing on a single global lock (the per-RPC contention the pre-shard
design paid — ISSUE 8).

Routing without a scan: challenge ids carry their owning user's shard
index in byte 0 and session tokens carry it in the first two hex chars
(stamped by :meth:`ServerState.tag_challenge_id` /
:meth:`ServerState.tag_session_token` at mint time), so ``VerifyProof``
and ``validate_session`` land directly on the shard that issued them.
Untagged ids (tests, snapshots written before sharding) fall back to a
bounded scan over the shard dicts — correctness never depends on the tag.

Lock discipline (mechanically enforced by cpzk-lint LOCK-001): every
mutation of a shard's maps happens lexically inside ``async with
shard.lock`` for that same shard, and every ``_journal_append`` happens
under the mutating shard's lock — which pins WAL order to in-memory
application order per shard (cross-shard interleaving on the single
event loop is itself the application order).  Global capacity caps read
maintained counters (every map mutation routes through the
``_*_insert``/``_*_remove`` funnels): the event loop cannot interleave
another coroutine into a synchronous block, so the check-then-insert
under one shard lock stays exact — at O(1) per check instead of the
O(shards) sum the bulk paths used to pay per entry.

Expiry is indexed by per-shard time-wheels (coarse buckets keyed on the
effective expiry instant, maintained at mint/revoke/consume), so a
cleanup sweep does O(expired) work instead of scanning every live entry,
with lock holds bounded at ``SWEEP_CHUNK`` entries — the two O(total-
state) cliffs the million-user soak (ISSUE 14) exposed.  Snapshots cut
and serialize one shard at a time with event-loop yields in between; see
:meth:`ServerState.snapshot` for why the early WAL watermark is safe.
"""

from __future__ import annotations

import asyncio
import time
import zlib
from collections.abc import MutableMapping
from dataclasses import dataclass, field

from ..errors import InvalidParams, UnsupportedFormat, WrongPartition
from ..protocol.gadgets import Statement
from . import metrics

CHALLENGE_EXPIRY_SECONDS = 300
MAX_CHALLENGES_PER_USER = 3
SESSION_EXPIRY_SECONDS = 3600
MAX_SESSIONS_PER_USER = 5

MAX_TOTAL_USERS = 10_000
MAX_TOTAL_CHALLENGES = 50_000
MAX_TOTAL_SESSIONS = 100_000

MAX_USER_ID_LEN = 256

#: Expiry time-wheel bucket width.  Each shard indexes its sessions and
#: challenges by ``effective_expiry // granularity`` so a cleanup sweep
#: visits only the buckets that are due — O(expired) work per tick
#: instead of a full scan of every live entry (the O(live) cliff the
#: million-user soak exposed).  Coarse on purpose: a bucket is a hint
#: set, membership is re-checked against the map under the shard lock.
EXPIRY_WHEEL_GRANULARITY_S = 60

#: Max entries examined per shard-lock hold during a sweep: bounds the
#: event-loop stall of one lock acquisition even when millions of
#: entries expire at once (the sweep yields between chunks).
SWEEP_CHUNK = 4096

#: Default shard count.  Shard indexes are embedded in challenge ids
#: (byte 0) and session tokens (first two hex chars), so the count is
#: capped at 256 and must agree across a replicated pair — a promoted
#: standby routes by tags the primary stamped ([replication] shards).
NUM_STATE_SHARDS = 16
MAX_STATE_SHARDS = 256


def _valid_user_id_chars(user_id: str) -> bool:
    return all(c.isalnum() or c in "_-." for c in user_id)


def user_id_error(user_id: str) -> str | None:
    """Registration-time user-id rules (service.rs:37-56 twin): non-empty,
    <=256 chars, ``[A-Za-z0-9._-]`` only.  Shared by the gRPC service and
    the snapshot-restore trust boundary so the two can never drift."""
    if not user_id:
        return "User ID cannot be empty"
    if len(user_id) > MAX_USER_ID_LEN:
        return "User ID too long"
    if not _valid_user_id_chars(user_id):
        return "User ID contains invalid characters"
    return None


def _now() -> int:
    return int(time.time())


@dataclass
class UserData:
    user_id: str
    statement: Statement
    registered_at: int


@dataclass
class ChallengeData:
    challenge_id: bytes
    user_id: str
    created_at: int = field(default_factory=_now)
    expires_at: int = 0

    def __post_init__(self) -> None:
        if not self.expires_at:
            self.expires_at = self.created_at + CHALLENGE_EXPIRY_SECONDS

    def is_expired(self, now: int | None = None) -> bool:
        """TTL check with the reference's 2x-age clock-skew guard
        (state.rs:101-111)."""
        if now is None:
            now = _now()
        age = max(0, now - self.created_at)
        return now >= self.expires_at or age >= 2 * CHALLENGE_EXPIRY_SECONDS


@dataclass
class SessionData:
    token: str
    user_id: str
    created_at: int = field(default_factory=_now)
    expires_at: int = 0

    def __post_init__(self) -> None:
        if not self.expires_at:
            self.expires_at = self.created_at + SESSION_EXPIRY_SECONDS

    def is_expired(self, now: int | None = None) -> bool:
        """Same 2x-age clock-skew guard as :meth:`ChallengeData.is_expired`
        (state.rs:101-111): a wall clock stepping backward after mint must
        not silently extend a bearer token's lifetime past twice its TTL."""
        if now is None:
            now = _now()
        age = max(0, now - self.created_at)
        return now >= self.expires_at or age >= 2 * SESSION_EXPIRY_SECONDS


#: Every Nth shard-lock acquisition is timed into the
#: ``state.shard.lock_wait`` histogram (uniform stride, so the mean an
#: operator reads is unbiased; per-acquire timing on the serving path
#: would cost two clock reads per state op for a signal that only
#: matters in aggregate).
_LOCK_WAIT_STRIDE = 16


class _SampledLock(asyncio.Lock):
    """An ``asyncio.Lock`` that stride-samples acquisition wait into the
    cross-plane ``state.shard.lock_wait`` histogram — the shard-contention
    signal the ops plane's ``/statusz`` surfaces.  Drop-in: every
    ``async with shard.lock`` site stays untouched."""

    def __init__(self) -> None:
        super().__init__()
        self._acquires = 0

    async def acquire(self) -> bool:
        self._acquires += 1
        if self._acquires % _LOCK_WAIT_STRIDE:
            return await super().acquire()
        t0 = time.monotonic()
        result = await super().acquire()
        metrics.histogram("state.shard.lock_wait").observe(
            time.monotonic() - t0
        )
        return result


def _session_wheel_key(data: SessionData) -> int:
    """Wheel bucket for a session: its *effective* expiry instant — the
    earlier of ``expires_at`` and the 2x-age clock-skew guard — so an
    entry is expired exactly when ``now`` reaches its bucket's span."""
    eff = min(data.expires_at, data.created_at + 2 * SESSION_EXPIRY_SECONDS)
    return eff // EXPIRY_WHEEL_GRANULARITY_S


def _challenge_wheel_key(data: ChallengeData) -> int:
    eff = min(data.expires_at, data.created_at + 2 * CHALLENGE_EXPIRY_SECONDS)
    return eff // EXPIRY_WHEEL_GRANULARITY_S


class StateShard:
    """One lock + the five registries it guards, for one hash slice of the
    user keyspace.  Everything about a user — registration, challenges,
    sessions, and the per-user index lists — lives in exactly one shard,
    so no state operation ever needs two locks."""

    __slots__ = (
        "lock", "_users", "_challenges", "_user_challenges",
        "_sessions", "_user_sessions", "_session_wheel", "_challenge_wheel",
    )

    def __init__(self) -> None:
        self.lock = _SampledLock()
        self._users: dict[str, UserData] = {}
        self._challenges: dict[bytes, ChallengeData] = {}
        self._user_challenges: dict[str, list[bytes]] = {}
        self._sessions: dict[str, SessionData] = {}
        self._user_sessions: dict[str, list[str]] = {}
        # expiry time-wheels: effective-expiry bucket -> member keys.
        # Hint indexes maintained at mint/revoke/consume so a sweep
        # visits only due buckets; the maps above stay the truth.
        self._session_wheel: dict[int, set[str]] = {}
        self._challenge_wheel: dict[int, set[bytes]] = {}


class _ShardedView(MutableMapping):
    """A merged mutable view over one registry across all shards.

    Test/inspection seam only — the RPC paths go straight at the shards.
    Writes route by the owning user (taken from the key for the
    user-keyed maps, from the value's ``user_id`` for sessions and
    challenges); reads try the tag-routed shard first and fall back to a
    scan, so untagged fixture keys behave exactly as the single-map
    design did."""

    __slots__ = ("_state", "_attr", "_kind")

    def __init__(self, state: "ServerState", attr: str, kind: str):
        self._state = state
        self._attr = attr
        self._kind = kind  # "user" | "session" | "challenge"

    def _maps(self):
        return [getattr(s, self._attr) for s in self._state._shards]

    def _shard_for_key(self, key) -> "StateShard":
        st = self._state
        if self._kind == "user":
            return st._shard_for_user(key)
        if self._kind == "session":
            idx = st._locate_session(key)
        else:
            idx = st._locate_challenge(key)
        if idx is None:
            raise KeyError(key)
        return st._shards[idx]

    def _map_for_key(self, key):
        return getattr(self._shard_for_key(key), self._attr)

    def __getitem__(self, key):
        return self._map_for_key(key)[key]

    def __setitem__(self, key, value) -> None:
        # writes route through the mutation funnels so the maintained
        # counters and expiry wheels stay exact even for fixture writes
        st = self._state
        if self._kind == "user":
            shard = st._shard_for_user(key)
            if self._attr == "_users" and key not in shard._users:
                st._n_users += 1
            getattr(shard, self._attr)[key] = value
            return
        owner = getattr(value, "user_id", None)
        shard = (
            st._shard_for_user(owner)
            if owner is not None
            else st._shards[0]
        )
        if self._kind == "session" and getattr(value, "token", None) == key:
            st._session_insert(shard, value)
        elif (
            self._kind == "challenge"
            and getattr(value, "challenge_id", None) == key
        ):
            st._challenge_insert(shard, value)
        else:  # key-mismatched fixture write: raw set, count new keys
            m = getattr(shard, self._attr)
            if key not in m:
                if self._kind == "session":
                    st._n_sessions += 1
                else:
                    st._n_challenges += 1
            m[key] = value

    def __delitem__(self, key) -> None:
        st = self._state
        shard = self._shard_for_key(key)
        m = getattr(shard, self._attr)
        if key not in m:
            raise KeyError(key)
        if self._attr == "_sessions":
            st._session_remove(shard, key)
        elif self._attr == "_challenges":
            st._challenge_remove(shard, key)
        elif self._attr == "_users":
            st._user_remove(shard, key)
        else:
            del m[key]

    def __iter__(self):
        for m in self._maps():
            yield from m

    def __len__(self) -> int:
        return sum(len(m) for m in self._maps())

    def __contains__(self, key) -> bool:
        # membership must check the key, not only that a shard resolves:
        # the user-keyed maps route ANY key to some shard, so the old
        # resolve-only check answered True for every user id
        try:
            m = self._map_for_key(key)
        except KeyError:
            return False
        return key in m


class ServerState:
    """All server registries behind per-shard locks (see module docstring)."""

    def __init__(
        self,
        shards: int = NUM_STATE_SHARDS,
        max_users: int = MAX_TOTAL_USERS,
        max_challenges: int = MAX_TOTAL_CHALLENGES,
        max_sessions: int = MAX_TOTAL_SESSIONS,
    ) -> None:
        if not 1 <= shards <= MAX_STATE_SHARDS:
            raise ValueError(
                f"shards must be in [1, {MAX_STATE_SHARDS}], got {shards}"
            )
        if min(max_users, max_challenges, max_sessions) < 1:
            raise ValueError("capacity caps must be >= 1")
        self.num_shards = shards
        self._shards = [StateShard() for _ in range(shards)]
        # global capacity caps: the reference constants by default,
        # raised via [server] max_* for million-user deployments
        self.max_users = max_users
        self.max_challenges = max_challenges
        self.max_sessions = max_sessions
        # maintained global counts, updated by the _*_insert/_*_remove
        # funnels below: O(1) cap checks instead of an O(shards) sum per
        # entry inside the shard lock (ISSUE 14 satellite) — semantics
        # unchanged because every map mutation routes through the funnels
        self._n_users = 0
        self._n_challenges = 0
        self._n_sessions = 0
        # sweep introspection: kind -> (examined, removed, duration_s) of
        # the last expiry sweep (the operation-counting spy tests and the
        # soak harness read this; the metrics carry the same numbers)
        self.last_sweep_stats: dict[str, tuple[int, int, float]] = {}
        # longest synchronous per-shard snapshot cut this process has
        # paid, milliseconds (the acceptance number of the streaming
        # snapshot: the event loop never stalls longer than one cut)
        self.snapshot_max_pause_ms = 0.0
        # longest whole-sweep wall time, milliseconds (soak acceptance)
        self.sweep_max_ms = 0.0
        # serializes whole snapshot() calls: overlapping writers (cleanup
        # sweep vs shutdown) must rename in document-build order, or an
        # older doc can land over a newer one with _persist_dirty false
        self._snapshot_lock = asyncio.Lock()
        # set on any change to persisted data (users/sessions); lets the
        # periodic snapshot skip writes on an idle server
        self._persist_dirty = True
        # durability journal hook (WriteAheadLog | None): when attached,
        # every acknowledged mutation to persisted data is appended —
        # under the mutating shard's lock, so WAL order always equals
        # application order — and fsynced (per policy) before the RPC
        # returns
        self.journal = None
        # synchronous-replication barrier (async callable(seq) | None):
        # when attached by a sync-mode SegmentShipper, acknowledged
        # mutations additionally wait until the warm standby has applied
        # the journal up to their sequence number (zero-loss failover)
        self.repl_barrier = None
        # write-time ownership fence (callable(user_id) -> str | None,
        # attached where a fleet router exists): re-verifies partition
        # ownership INSIDE the shard lock, in the same synchronous
        # section as the mutation itself.  The entry-point ownership
        # check alone cannot fence multi-await handlers — VerifyProof
        # awaits the batcher between its check and create_session,
        # register awaits the shard lock — and a live split's map flip
        # can land inside any of those awaits.  Because the split's
        # export -> flip runs with no await and this check-then-mutate
        # is equally synchronous, event-loop non-interleaving totally
        # orders the two: a fenced mutation either precedes the export
        # (and ships with it) or follows the flip (and is rejected with
        # the redirect message — never acknowledged, never stranded).
        self.owner_fence = None
        # WAL sequence number the last-restored snapshot covered
        self.restored_wal_seq = 0
        # (seq, byte offset) of the journal at the last snapshot write:
        # the compaction watermark — everything before it is covered
        self.snapshot_covered_seq = 0
        self.snapshot_covered_offset = 0

    # --- shard routing ----------------------------------------------------

    def _shard_index(self, user_id: str) -> int:
        """Stable user->shard hash (crc32: identical across processes, so
        a promoted standby routes the tags the primary stamped)."""
        return zlib.crc32(user_id.encode()) % self.num_shards

    def _shard_for_user(self, user_id: str) -> StateShard:
        return self._shards[self._shard_index(user_id)]

    def tag_challenge_id(self, user_id: str, challenge_id: bytes) -> bytes:
        """Stamp the owning user's shard index into byte 0 of a freshly
        minted challenge id, so ``consume_challenge`` lands on the issuing
        shard without a scan (31 of the 32 random bytes remain)."""
        return bytes([self._shard_index(user_id)]) + challenge_id[1:]

    def tag_session_token(self, user_id: str, token: str) -> str:
        """Stamp the owning user's shard index into the first two hex
        chars of a freshly minted session token (same routing contract as
        :meth:`tag_challenge_id`)."""
        return f"{self._shard_index(user_id):02x}" + token[2:]

    def _locate_challenge(self, challenge_id: bytes) -> int | None:
        """Shard index holding ``challenge_id``: the tag byte when it
        routes to a hit, else a bounded scan (untagged test/legacy ids);
        ``None`` when no shard holds it.  Synchronous — callers re-check
        under the shard lock before mutating."""
        if challenge_id:
            idx = challenge_id[0]
            if idx < self.num_shards and challenge_id in self._shards[idx]._challenges:
                return idx
        for i, shard in enumerate(self._shards):
            if challenge_id in shard._challenges:
                return i
        return None

    def _locate_session(self, token: str) -> int | None:
        """Shard index holding ``token`` (tag-routed, scan fallback)."""
        if len(token) >= 2:
            try:
                idx = int(token[:2], 16)
            except ValueError:
                idx = -1
            if 0 <= idx < self.num_shards and token in self._shards[idx]._sessions:
                return idx
        for i, shard in enumerate(self._shards):
            if token in shard._sessions:
                return i
        return None

    # --- mutation funnels (counter + wheel + per-user-list upkeep) --------
    #
    # EVERY mutation of a shard's registries goes through one of these six
    # methods (RPC paths, replay, restore, drop_users, and the _ShardedView
    # test seam alike).  That single funnel is what lets the global counts
    # be maintained integers instead of O(shards) sums, keeps the expiry
    # wheels consistent with the maps, and fixes the per-user-list churn
    # leak in one place: a remove that empties a user's session/challenge
    # list also deletes the dict entry, so the per-user index dicts no
    # longer grow with every user that ever held a session (ISSUE 14).
    # Direct registry writes outside these six methods are a FUNNEL-001
    # finding; lock discipline is the CALLER's obligation (LOCK-001 at
    # the call sites — parameter-rooted mutations carry no waivers here).

    def _user_insert(self, shard: StateShard, data: UserData) -> None:
        if data.user_id not in shard._users:
            self._n_users += 1
        shard._users[data.user_id] = data

    def _user_remove(self, shard: StateShard, user_id: str) -> UserData | None:
        data = shard._users.pop(user_id, None)
        if data is not None:
            self._n_users -= 1
        return data

    def _session_insert(self, shard: StateShard, data: SessionData) -> None:
        old = shard._sessions.get(data.token)
        if old is None:
            self._n_sessions += 1
        else:  # replace (test seam): drop the old wheel entry first
            self._wheel_discard(
                shard._session_wheel, _session_wheel_key(old), data.token
            )
        shard._sessions[data.token] = data
        shard._session_wheel.setdefault(
            _session_wheel_key(data), set()
        ).add(data.token)

    def _session_remove(self, shard: StateShard, token: str) -> SessionData | None:
        data = shard._sessions.pop(token, None)
        if data is None:
            return None
        self._n_sessions -= 1
        self._wheel_discard(
            shard._session_wheel, _session_wheel_key(data), token
        )
        per_user = shard._user_sessions.get(data.user_id)
        if per_user is not None:
            if token in per_user:
                per_user.remove(token)
            if not per_user:  # churn-leak fix: delete-on-empty
                del shard._user_sessions[data.user_id]
        return data

    def _challenge_insert(self, shard: StateShard, data: ChallengeData) -> None:
        old = shard._challenges.get(data.challenge_id)
        if old is None:
            self._n_challenges += 1
        else:
            self._wheel_discard(
                shard._challenge_wheel, _challenge_wheel_key(old),
                data.challenge_id,
            )
        shard._challenges[data.challenge_id] = data
        shard._challenge_wheel.setdefault(
            _challenge_wheel_key(data), set()
        ).add(data.challenge_id)

    def _challenge_remove(
        self, shard: StateShard, challenge_id: bytes
    ) -> ChallengeData | None:
        data = shard._challenges.pop(challenge_id, None)
        if data is None:
            return None
        self._n_challenges -= 1
        self._wheel_discard(
            shard._challenge_wheel, _challenge_wheel_key(data), challenge_id
        )
        per_user = shard._user_challenges.get(data.user_id)
        if per_user is not None:
            if challenge_id in per_user:
                per_user.remove(challenge_id)
            if not per_user:  # churn-leak fix: delete-on-empty
                del shard._user_challenges[data.user_id]
        return data

    @staticmethod
    def _wheel_discard(wheel: dict[int, set], key: int, member) -> None:
        bucket = wheel.get(key)
        if bucket is not None:
            bucket.discard(member)
            if not bucket:
                del wheel[key]

    # --- global counts (maintained integers; see the funnels above) -------

    def _total_users(self) -> int:
        return self._n_users

    def _total_challenges(self) -> int:
        return self._n_challenges

    def _total_sessions(self) -> int:
        return self._n_sessions

    # --- per-shard introspection (ops plane /statusz + /metrics) ----------

    def shard_stats(self) -> list[dict]:
        """Per-shard registry sizes, shard-index order.  Synchronous dict
        ``len()`` reads — a consistent-enough cut for an operator surface,
        with zero lock traffic on the serving path."""
        return [
            {
                "shard": i,
                "users": len(s._users),
                "sessions": len(s._sessions),
                "challenges": len(s._challenges),
            }
            for i, s in enumerate(self._shards)
        ]

    def export_shard_gauges(self) -> None:
        """Refresh the per-shard ``state.shard.size{shard,kind}`` gauges
        (pull-style: called by the ops plane right before an exposition
        render rather than on every mutation — per-mutation gauge writes
        would tax the serving path for a scrape-time number)."""
        gauge = metrics.gauge("state.shard.size", labelnames=("shard", "kind"))
        for row in self.shard_stats():
            idx = str(row["shard"])
            gauge.labels(shard=idx, kind="users").set(row["users"])
            gauge.labels(shard=idx, kind="sessions").set(row["sessions"])
            gauge.labels(shard=idx, kind="challenges").set(row["challenges"])

    # --- merged views (test/inspection seam; RPC paths use shards) --------

    @property
    def _users(self) -> _ShardedView:
        return _ShardedView(self, "_users", "user")

    @property
    def _sessions(self) -> _ShardedView:
        return _ShardedView(self, "_sessions", "session")

    @property
    def _challenges(self) -> _ShardedView:
        return _ShardedView(self, "_challenges", "challenge")

    @property
    def _user_sessions(self) -> _ShardedView:
        return _ShardedView(self, "_user_sessions", "user")

    @property
    def _user_challenges(self) -> _ShardedView:
        return _ShardedView(self, "_user_challenges", "user")

    # --- owned-key subset iteration (fleet split: cpzk_tpu/fleet/) --------

    def export_user_records(self, predicate) -> list[dict]:
        """Journal-style records (``type`` set, no ``seq``) for every user
        matched by ``predicate(user_id)`` — the owned-key subset a fleet
        split ships to the new partition.  Per user: the registration,
        then live challenges, then live sessions, in the order the replay
        validators require (a session/challenge record is rejected unless
        its user is already registered).

        One synchronous pass in a deterministic order (shard index, then
        sorted user id): the event loop cannot interleave a mutating
        handler, so the export is a consistent cut — the same guarantee
        :meth:`snapshot` leans on — and two exports of the same state are
        byte-identical, which keeps a resumed split's segment stream
        stable."""
        from ..core.ristretto import Ristretto255

        eb = Ristretto255.element_to_bytes
        out: list[dict] = []
        for shard in self._shards:
            for uid in sorted(shard._users):
                if not predicate(uid):
                    continue
                user = shard._users[uid]
                out.append({
                    "type": "register_user",
                    "user_id": uid,
                    "y1": eb(user.statement.y1).hex(),
                    "y2": eb(user.statement.y2).hex(),
                    "registered_at": user.registered_at,
                })
                for cid in shard._user_challenges.get(uid, ()):
                    ch = shard._challenges.get(cid)
                    if ch is None or ch.is_expired():
                        continue
                    out.append({
                        "type": "create_challenge",
                        "challenge_id": cid.hex(),
                        "user_id": uid,
                        "created_at": ch.created_at,
                        "expires_at": ch.expires_at,
                    })
                for token in shard._user_sessions.get(uid, ()):
                    s = shard._sessions.get(token)
                    if s is None or s.is_expired():
                        continue
                    out.append({
                        "type": "create_session",
                        "token": token,
                        "user_id": uid,
                        "created_at": s.created_at,
                        "expires_at": s.expires_at,
                    })
        return out

    # cpzk-lint: disable=LOCK-001 -- split drain runs single-threaded on offline partition files, like replay_journal_record
    def drop_users(self, predicate) -> tuple[int, int, int]:
        """Remove every user matched by ``predicate(user_id)`` together
        with their challenges, sessions, and per-user lists — the drain
        stage of a fleet split, after the moved subset is durable on the
        new partition and the map has flipped.  Single-threaded offline
        use only (the split tool operates on a stopped partition's
        files); returns ``(users, challenges, sessions)`` removed."""
        n_users = n_chal = n_sess = 0
        for shard in self._shards:
            doomed = [uid for uid in shard._users if predicate(uid)]
            for uid in doomed:
                self._user_remove(shard, uid)
                n_users += 1
                for cid in list(shard._user_challenges.get(uid, ())):
                    if self._challenge_remove(shard, cid) is not None:
                        n_chal += 1
                for token in list(shard._user_sessions.get(uid, ())):
                    if self._session_remove(shard, token) is not None:
                        n_sess += 1
                shard._user_challenges.pop(uid, None)
                shard._user_sessions.pop(uid, None)
        if n_users or n_chal or n_sess:
            self._persist_dirty = True
        return n_users, n_chal, n_sess

    # --- durability journal (cpzk_tpu/durability/) ---

    def attach_journal(self, wal) -> None:
        """Install the write-ahead log as this state's journal hook (done
        once by ``DurabilityManager.recover`` before serving starts)."""
        self.journal = wal

    def attach_owner_fence(self, fence) -> None:
        """Install the write-time partition-ownership fence: a SYNCHRONOUS
        ``callable(user_id) -> str | None`` returning the wrong-partition
        redirect message when this daemon no longer owns ``user_id``
        under the live fleet map, else ``None``.  Checked inside the
        shard lock immediately before every acknowledged user-keyed
        mutation (see the ``owner_fence`` constructor comment for why
        the entry-point check alone cannot fence multi-await handlers
        across a live split's map flip).  Reads and challenge consumes
        stay unfenced on purpose: removing a stale copy the split
        already exported cannot lose an acknowledged write, and leaving
        the consume unfenced lets an in-flight login retry at the new
        owner with its challenge intact there."""
        self.owner_fence = fence

    def _fence(self, user_id: str) -> None:
        """Raise :class:`WrongPartition` when the fence rejects
        ``user_id``.  Callers hold the mutating shard's lock; the raise
        precedes the insert/remove funnel AND the journal append, so a
        fenced mutation leaves no trace in memory or in the WAL."""
        fence = self.owner_fence
        if fence is None:
            return
        msg = fence(user_id)
        if msg is not None:
            raise WrongPartition(msg)

    def attach_replication_barrier(self, barrier) -> None:
        """Install a sync-replication barrier: an async callable awaited
        with the journal's sequence number after fsync and before the
        mutation is acknowledged (``SegmentShipper.wait_replicated`` in
        ``mode = "sync"``)."""
        self.repl_barrier = barrier

    # cpzk-lint: disable=LOCK-001 -- append funnel: every caller holds the mutating shard's lock (docstring contract)
    def _journal_append(self, rtype: str, payload: dict) -> None:
        """Append one record — callers hold the mutating shard's ``lock``,
        which pins WAL order to in-memory application order."""
        if self.journal is not None:
            self.journal.append(rtype, payload)

    async def _journal_sync(self) -> None:
        """Make appended records durable per the WAL's fsync policy; called
        AFTER the shard lock is released (fsync flushes every earlier
        append too, so interleaved mutations stay individually durable)
        and BEFORE the mutation is acknowledged to the caller.  With a
        sync-replication barrier attached, the acknowledgement further
        waits for the warm standby to apply up to this sequence number."""
        wal = self.journal
        if wal is not None and wal.needs_sync():
            await asyncio.to_thread(wal.sync)
        barrier = self.repl_barrier
        if barrier is not None and wal is not None:
            await barrier(wal.seq)

    # cpzk-lint: disable=LOCK-001 -- boot-time replay runs single-threaded before serving starts
    def replay_journal_record(self, rec: dict) -> str | None:
        """Boot-time (and standby-side) replay of one WAL record through
        the same trust-boundary validators as :meth:`restore` — a tampered
        log cannot smuggle in what the live RPC would reject.
        Single-threaded (recovery runs before serving starts; the standby
        applies segments before it is promoted to serve), so no lock.
        Returns None when applied, else the skip reason; never raises on
        malformed input (the fuzz harness holds this as an invariant)."""
        from ..core.ristretto import Ristretto255

        try:
            rtype = rec.get("type")
            if rtype == "register_user":
                uid = str(rec["user_id"])
                msg = user_id_error(uid)
                if msg is not None:
                    return msg
                shard = self._shard_for_user(uid)
                if uid in shard._users:
                    return "already registered"
                if self._total_users() >= self.max_users:
                    return "user capacity cap"
                y1 = Ristretto255.element_from_bytes(bytes.fromhex(rec["y1"]))
                y2 = Ristretto255.element_from_bytes(bytes.fromhex(rec["y2"]))
                if Ristretto255.is_identity(y1) or Ristretto255.is_identity(y2):
                    return "identity statement element"
                self._user_insert(shard, UserData(
                    user_id=uid,
                    statement=Statement(y1, y2),
                    registered_at=int(rec["registered_at"]),
                ))
                self._persist_dirty = True
                return None
            if rtype == "create_session":
                token, uid = str(rec["token"]), str(rec["user_id"])
                created, expires = int(rec["created_at"]), int(rec["expires_at"])
                if expires <= created or expires - created > SESSION_EXPIRY_SECONDS:
                    return "invalid session expiry"
                shard = self._shard_for_user(uid)
                if uid not in shard._users:
                    return "unregistered user"
                if self._locate_session(token) is not None:
                    return "duplicate session token"
                if self._total_sessions() >= self.max_sessions:
                    return "session capacity cap"
                data = SessionData(
                    token=token, user_id=uid, created_at=created, expires_at=expires
                )
                if data.is_expired():
                    return None  # same silent drop as restore()
                if len(shard._user_sessions.get(uid, ())) >= MAX_SESSIONS_PER_USER:
                    return "per-user session cap"
                self._session_insert(shard, data)
                shard._user_sessions.setdefault(uid, []).append(token)
                self._persist_dirty = True
                return None
            if rtype == "revoke_session":
                token = str(rec["token"])
                idx = self._locate_session(token)
                if idx is None:
                    return "session not found"
                self._session_remove(self._shards[idx], token)
                self._persist_dirty = True
                return None
            if rtype == "expire_sessions":
                now = int(rec["now"])
                for shard in self._shards:
                    for t in [
                        t for t, d in shard._sessions.items() if d.is_expired(now)
                    ]:
                        self._session_remove(shard, t)
                self._persist_dirty = True
                return None
            if rtype == "create_challenge":
                cid, uid = bytes.fromhex(rec["challenge_id"]), str(rec["user_id"])
                created, expires = int(rec["created_at"]), int(rec["expires_at"])
                if (
                    expires <= created
                    or expires - created > CHALLENGE_EXPIRY_SECONDS
                ):
                    return "invalid challenge expiry"
                shard = self._shard_for_user(uid)
                if uid not in shard._users:
                    return "unregistered user"
                if self._locate_challenge(cid) is not None:
                    return "duplicate challenge id"
                if self._total_challenges() >= self.max_challenges:
                    return "challenge capacity cap"
                data = ChallengeData(
                    challenge_id=cid, user_id=uid,
                    created_at=created, expires_at=expires,
                )
                if data.is_expired():
                    return None  # stale in-flight login: drop silently
                if len(shard._user_challenges.get(uid, ())) >= MAX_CHALLENGES_PER_USER:
                    return "per-user challenge cap"
                self._challenge_insert(shard, data)
                shard._user_challenges.setdefault(uid, []).append(cid)
                return None
            if rtype == "consume_challenge":
                cid = bytes.fromhex(rec["challenge_id"])
                idx = self._locate_challenge(cid)
                if idx is None:
                    return "challenge not found"
                self._challenge_remove(self._shards[idx], cid)
                return None
            return f"unknown record type {rtype!r}"
        except Exception as e:  # malformed fields are a rejection, not a crash
            return f"malformed record: {e!r}"

    # --- users (state.rs:136-161) ---

    async def register_user(self, user_data: UserData) -> None:
        shard = self._shard_for_user(user_data.user_id)
        async with shard.lock:
            # fence BEFORE the duplicate check: post-flip the source may
            # still hold the user's stale copy, and "already registered"
            # from a non-owner would mask the redirect
            self._fence(user_data.user_id)
            if self._total_users() >= self.max_users:
                raise InvalidParams(
                    f"Server has reached maximum user capacity ({self.max_users})"
                )
            if user_data.user_id in shard._users:
                raise InvalidParams(f"User '{user_data.user_id}' already registered")
            self._user_insert(shard, user_data)
            self._persist_dirty = True
            if self.journal is not None:
                from ..core.ristretto import Ristretto255

                eb = Ristretto255.element_to_bytes
                self._journal_append(
                    "register_user",
                    {
                        "user_id": user_data.user_id,
                        "y1": eb(user_data.statement.y1).hex(),
                        "y2": eb(user_data.statement.y2).hex(),
                        "registered_at": user_data.registered_at,
                    },
                )
        await self._journal_sync()

    async def get_user(self, user_id: str) -> UserData | None:
        return (await self.get_users([user_id]))[0]

    async def get_users(self, user_ids: list[str]) -> list[UserData | None]:
        out: dict[int, UserData | None] = {}
        by_shard: dict[int, list[tuple[int, str]]] = {}
        for i, uid in enumerate(user_ids):
            by_shard.setdefault(self._shard_index(uid), []).append((i, uid))
        for idx in sorted(by_shard):
            shard = self._shards[idx]
            async with shard.lock:
                for i, uid in by_shard[idx]:
                    out[i] = shard._users.get(uid)
        return [out[i] for i in range(len(user_ids))]

    # --- challenges (state.rs:164-249) ---

    async def create_challenge(self, user_id: str, challenge_id: bytes) -> int:
        shard = self._shard_for_user(user_id)
        async with shard.lock:
            self._fence(user_id)
            if self._total_challenges() >= self.max_challenges:
                raise InvalidParams(
                    f"Server has reached maximum challenge capacity ({self.max_challenges})"
                )
            if user_id not in shard._users:
                raise InvalidParams(f"User '{user_id}' not found")
            if len(shard._user_challenges.get(user_id, ())) >= MAX_CHALLENGES_PER_USER:
                raise InvalidParams(f"Too many active challenges for user '{user_id}'")
            data = ChallengeData(challenge_id=challenge_id, user_id=user_id)
            shard._user_challenges.setdefault(user_id, []).append(challenge_id)
            self._challenge_insert(shard, data)
            # journaled so a crash-reboot (and a promoted standby) does not
            # strand every in-flight login (ISSUE 8 satellite) — replayed
            # through the same validators as the other record types
            self._journal_append(
                "create_challenge",
                {
                    "challenge_id": challenge_id.hex(),
                    "user_id": user_id,
                    "created_at": data.created_at,
                    "expires_at": data.expires_at,
                },
            )
        await self._journal_sync()
        return data.expires_at

    async def get_challenge(self, challenge_id: bytes) -> ChallengeData | None:
        idx = self._locate_challenge(challenge_id)
        if idx is None:
            return None
        shard = self._shards[idx]
        async with shard.lock:
            return shard._challenges.get(challenge_id)

    async def consume_challenge(self, challenge_id: bytes) -> ChallengeData:
        """Single-use removal; expired challenges are removed AND rejected.
        Thin wrapper over the bulk form so the two can never desync."""
        data = (await self.consume_challenges([challenge_id]))[0]
        if data is None:
            raise InvalidParams("Invalid or expired challenge")
        return data

    # cpzk-lint: disable=FENCE-001 -- consume stays unfenced on purpose (PR 16/18): burning a stale copy the split already exported cannot lose an acked write, and an unfenced consume lets an in-flight login retry at the new owner with its challenge intact there (the serving layer redirects BEFORE consuming)
    async def consume_challenges(self, ids: list[bytes]) -> list[ChallengeData | None]:
        """Bulk consume-once, one lock acquisition per touched shard (the
        batch RPC's hot path: n sequential ``consume_challenge`` awaits
        cost n event-loop round-trips).  Per-id semantics identical to
        :meth:`consume_challenge`, with ``None`` standing in for the
        invalid/expired rejection; duplicate ids in one batch behave as
        they would sequentially (first wins — duplicates always route to
        the same shard)."""
        out: dict[int, ChallengeData | None] = {}
        by_shard: dict[int, list[tuple[int, bytes]]] = {}
        for i, cid in enumerate(ids):
            idx = self._locate_challenge(cid)
            if idx is None:
                out[i] = None
            else:
                by_shard.setdefault(idx, []).append((i, cid))
        journaled = False
        now = int(time.time())  # one clock read for the whole batch
        for idx in sorted(by_shard):
            shard = self._shards[idx]
            async with shard.lock:
                for i, cid in by_shard[idx]:
                    # re-check under the lock: located synchronously above,
                    # and a duplicate earlier in this batch may have won
                    data = shard._challenges.get(cid)
                    if data is None:
                        out[i] = None
                        continue
                    self._challenge_remove(shard, cid)
                    if self.journal is not None:
                        # payload built only when a journal exists: the
                        # hex + dict per id is measurable at stream depth
                        self._journal_append(
                            "consume_challenge", {"challenge_id": cid.hex()}
                        )
                        journaled = True
                    out[i] = None if data.is_expired(now=now) else data
        if journaled:
            await self._journal_sync()
        return [out[i] for i in range(len(ids))]

    async def cleanup_expired_challenges(self) -> int:
        # no journal record: expiry is deterministic from the timestamps a
        # create_challenge record carries, so replay drops them on its own
        return await self._sweep_expired("challenges")

    # cpzk-lint: disable=FENCE-001 -- expiry GC removes only entries past their validity: a post-flip sweep of a moved user's expired entry is a no-op the split drain performs anyway, so ownership never gates garbage collection
    async def _sweep_expired(self, kind: str) -> int:
        """One expiry sweep over the time-wheels: visit only the buckets
        whose span is due, re-check each member against the map under the
        shard lock, remove what is expired — O(expired) work instead of
        the pre-wheel full scan of every live entry.  Lock holds are
        bounded at ``SWEEP_CHUNK`` entries with an event-loop yield
        between chunks, so a million simultaneous expiries never stall
        serving for the whole sweep.  Journal semantics unchanged: one
        ``expire_sessions {now}`` record per shard that removed something,
        with the single timestamp captured before any removal — replay
        still produces exactly the removed set (interleaved mints carry
        later timestamps and are never expired at ``now``; interleaved
        revokes journal their own records)."""
        is_sessions = kind == "sessions"
        now = _now()
        due = now // EXPIRY_WHEEL_GRANULARITY_S
        t0 = time.monotonic()
        removed = examined = 0
        journaled = False
        for shard in self._shards:
            wheel = (
                shard._session_wheel if is_sessions
                else shard._challenge_wheel
            )
            registry = shard._sessions if is_sessions else shard._challenges
            async with shard.lock:
                pending: list = []
                for k in [k for k in wheel if k <= due]:
                    pending.extend(wheel.pop(k))
            if not pending:
                continue
            shard_removed = 0
            survivors: list = []
            for lo in range(0, len(pending), SWEEP_CHUNK):
                async with shard.lock:
                    for key in pending[lo:lo + SWEEP_CHUNK]:
                        examined += 1
                        data = registry.get(key)
                        if data is None:
                            continue  # consumed/revoked since: stale hint
                        if data.is_expired(now):
                            if is_sessions:
                                self._session_remove(shard, key)
                            else:
                                self._challenge_remove(shard, key)
                            shard_removed += 1
                        else:
                            survivors.append(key)
                await asyncio.sleep(0)  # bounded hold: yield between chunks
            async with shard.lock:
                # the partially-due bucket's survivors go back on the wheel
                for key in survivors:
                    data = registry.get(key)
                    if data is None:
                        continue
                    wk = (
                        _session_wheel_key(data) if is_sessions
                        else _challenge_wheel_key(data)
                    )
                    wheel.setdefault(wk, set()).add(key)
                if shard_removed and is_sessions:
                    self._persist_dirty = True
                    # one record per shard that expired something: replay
                    # applies the sweep globally, so repeats are no-ops
                    self._journal_append("expire_sessions", {"now": now})
                    journaled = True
            removed += shard_removed
        if journaled:
            await self._journal_sync()
        duration = time.monotonic() - t0
        self.last_sweep_stats[kind] = (examined, removed, duration)
        self.sweep_max_ms = max(self.sweep_max_ms, duration * 1000.0)
        metrics.gauge("state.sweep.max_ms").set(self.sweep_max_ms)
        metrics.histogram(
            "state.sweep.duration", labelnames=("kind",)
        ).labels(kind=kind).observe(duration)
        metrics.counter(
            "state.sweep.examined", labelnames=("kind",)
        ).labels(kind=kind).inc(examined)
        return removed

    # --- sessions (state.rs:252-327) ---

    async def create_session(self, token: str, user_id: str) -> None:
        """Thin wrapper over the bulk form so the two can never desync."""
        msg = (await self.create_sessions([(token, user_id)]))[0]
        if msg is not None:
            # distinguish the fence rejection so the serving layer can
            # answer a redirect instead of INTERNAL: ownership moves are
            # monotone within one flip, so re-asking the fence here is
            # race-free (still rejected <=> the entry failed the fence)
            fence = self.owner_fence
            if fence is not None and fence(user_id) is not None:
                raise WrongPartition(msg)
            raise InvalidParams(msg)

    async def create_sessions(self, pairs: list[tuple[str, str]]) -> list[str | None]:
        """Bulk session mint, one lock acquisition per touched shard:
        per-(token, user_id) result is ``None`` on success or the same
        error message :meth:`create_session` would raise.  Caps are
        enforced exactly as a sequential loop would within each shard;
        across shards the loop runs in shard-index order (the global cap
        stays exact — counts are synchronous sums)."""
        out: dict[int, str | None] = {}
        by_shard: dict[int, list[tuple[int, str, str]]] = {}
        for i, (token, user_id) in enumerate(pairs):
            by_shard.setdefault(self._shard_index(user_id), []).append(
                (i, token, user_id)
            )
        journaled = False
        for idx in sorted(by_shard):
            shard = self._shards[idx]
            async with shard.lock:
                for i, token, user_id in by_shard[idx]:
                    fence = self.owner_fence
                    if fence is not None:
                        fmsg = fence(user_id)
                        if fmsg is not None:
                            out[i] = fmsg
                            continue
                    if self._total_sessions() >= self.max_sessions:
                        out[i] = (
                            f"Server has reached maximum session capacity ({self.max_sessions})"
                        )
                        continue
                    if len(shard._user_sessions.get(user_id, ())) >= MAX_SESSIONS_PER_USER:
                        out[i] = (
                            f"User '{user_id}' has reached maximum session limit ({MAX_SESSIONS_PER_USER})"
                        )
                        continue
                    data = SessionData(token=token, user_id=user_id)
                    self._session_insert(shard, data)
                    shard._user_sessions.setdefault(user_id, []).append(token)
                    self._persist_dirty = True
                    self._journal_append(
                        "create_session",
                        {
                            "token": data.token,
                            "user_id": data.user_id,
                            "created_at": data.created_at,
                            "expires_at": data.expires_at,
                        },
                    )
                    journaled = True
                    out[i] = None
        if journaled:
            await self._journal_sync()
        return [out[i] for i in range(len(pairs))]

    async def validate_session(self, token: str) -> str:
        idx = self._locate_session(token)
        if idx is None:
            raise InvalidParams("Invalid session token")
        shard = self._shards[idx]
        async with shard.lock:
            data = shard._sessions.get(token)
            if data is None:
                raise InvalidParams("Invalid session token")
            if data.is_expired():
                raise InvalidParams("Session expired")
            return data.user_id

    async def revoke_session(self, token: str) -> None:
        idx = self._locate_session(token)
        if idx is None:
            raise InvalidParams("Session not found")
        shard = self._shards[idx]
        async with shard.lock:
            existing = shard._sessions.get(token)
            if existing is None:
                raise InvalidParams("Session not found")
            # fenced like every acked mutation: revoking only the stale
            # copy post-flip would ack a revoke the new owner never saw
            self._fence(existing.user_id)
            data = self._session_remove(shard, token)
            if data is None:
                raise InvalidParams("Session not found")
            self._persist_dirty = True
            self._journal_append("revoke_session", {"token": token})
        await self._journal_sync()

    async def cleanup_expired_sessions(self) -> int:
        return await self._sweep_expired("sessions")

    # --- counts (state.rs:330-342) ---

    async def user_count(self) -> int:
        return self._total_users()

    async def session_count(self) -> int:
        return self._total_sessions()

    async def challenge_count(self) -> int:
        return self._total_challenges()

    # --- snapshot / restore (checkpoint-resume, SURVEY.md §5) -------------
    #
    # The reference has no persistence: a restart loses everything
    # (state.rs holds only in-memory maps).  In-memory remains this
    # framework's default for parity; snapshots are OPT-IN new capability
    # (--state-file).  Scope: users and sessions — challenges are 300-second
    # single-use nonces, and persisting them in the long-lived snapshot
    # would extend their attack window across restarts for no operational
    # benefit; in-flight logins instead survive through their journaled
    # create/consume WAL records, which recovery replays regardless of the
    # snapshot's covered sequence number (bounded by WAL compaction — see
    # docs/operations.md).  Format: versioned JSON, public data only
    # (statements are public by protocol design; session tokens are bearer
    # secrets, so the file must be protected like a session store — written
    # 0600).  With a durability journal attached, each snapshot also
    # records the WAL sequence number it covers ("wal_seq"), so boot-time
    # recovery replays only the log suffix beyond it (cpzk_tpu/durability/).

    SNAPSHOT_VERSION = 1

    async def snapshot(self, path: str) -> bool:
        """Write users + live sessions to ``path`` (JSON); returns whether
        a write happened (skipped when nothing changed since the last
        snapshot).

        **Streaming per-shard cut** (ISSUE 14): the WAL watermark
        (``journal.seq``, ``journal.size``) is captured in ONE synchronous
        block FIRST, then the shards are cut one at a time — each cut is a
        synchronous C-speed copy of that shard's item references — with an
        event-loop yield between shards, and ALL serialization + fsync +
        atomic rename happen on a worker thread over the captured
        references (UserData/SessionData are immutable once minted, so the
        writer thread reads them race-free).  The event loop therefore
        never stalls longer than one shard's pointer copy, instead of the
        multi-second whole-document build the monolithic cut paid at 1M
        users.  Mutations that land between the early watermark and a
        later shard's cut may appear in the document even though
        ``wal_seq`` predates them — safe by replay idempotency: recovery
        replays the WAL suffix past ``wal_seq`` through the
        ``replay_journal_record`` validators, where a duplicated create is
        skipped and a revoke/consume of an absent entry is a no-op, so
        restore + suffix-replay converges to the acknowledged state.  The
        on-disk format is byte-identical to the monolithic writer's
        ``json.dump`` output (pinned by test).  Whole calls serialize on a
        snapshot lock so overlapping writers (cleanup sweep vs shutdown)
        rename in document-build order."""
        import asyncio as _asyncio
        import json
        import os

        from ..core.ristretto import Ristretto255

        eb = Ristretto255.element_to_bytes
        async with self._snapshot_lock:
            if not self._persist_dirty:
                return False
            covered: tuple[int, int] | None = None
            wal_seq: int | None = None
            if self.journal is not None:
                # the watermark comes FIRST, before any shard is cut: a
                # mutation after this point is either absent from the
                # document (replayed from the suffix) or present in it
                # (suffix replay skips the duplicate) — both converge
                wal_seq = self.journal.seq
                covered = (self.journal.seq, self.journal.size)
            self._persist_dirty = False
            now = _now()
            cuts: list[tuple[list, list]] = []
            max_pause_ms = 0.0
            # pause the cyclic collector for the cut loop: the burst of
            # list allocations otherwise triggers a gen-2 collection that
            # traverses EVERY live user/session object inside the timed
            # block (~900ms at 1M users, measured) — the cut itself is a
            # C-level reference copy (~5ms/shard at that scale)
            import gc

            gc_was_enabled = gc.isenabled()
            gc.disable()
            try:
                for shard in self._shards:
                    t0 = time.monotonic()
                    # one shard's consistent cut: list() is a synchronous
                    # reference copy, no serialization on the event loop
                    cuts.append((
                        list(shard._users.items()),
                        list(shard._sessions.values()),
                    ))
                    pause_ms = (time.monotonic() - t0) * 1000.0
                    max_pause_ms = max(max_pause_ms, pause_ms)
                    metrics.histogram("state.snapshot.pause_ms").observe(
                        pause_ms
                    )
                    await _asyncio.sleep(0)  # yield between shard cuts
            finally:
                if gc_was_enabled:
                    gc.enable()
            self.snapshot_max_pause_ms = max(
                self.snapshot_max_pause_ms, max_pause_ms
            )
            metrics.gauge("state.snapshot.max_pause_ms").set(
                self.snapshot_max_pause_ms
            )

            def write() -> None:
                # unique tmp name so a racing writer can never rename a
                # torn document; a distinctive prefix lets us sweep debris
                # a hard crash (SIGKILL between mkstemp and rename) left
                # behind — those files hold live bearer tokens
                import tempfile

                d = os.path.dirname(os.path.abspath(path)) or "."
                prefix = "." + os.path.basename(path) + ".tmp."
                for stale in os.listdir(d):
                    if stale.startswith(prefix):
                        try:
                            os.unlink(os.path.join(d, stale))
                        except OSError:
                            pass
                # mkstemp creates 0600 — the bearer-token protection requirement
                fd, tmp = tempfile.mkstemp(prefix=prefix, dir=d)
                dumps = json.dumps
                try:
                    with os.fdopen(fd, "w") as f:
                        # streamed shard by shard, byte-identical to
                        # json.dump of the equivalent monolithic document
                        # (default separators: ", " / ": ")
                        f.write('{"version": %d, "users": {'
                                % self.SNAPSHOT_VERSION)
                        first = True
                        for users_items, _sessions in cuts:
                            if not users_items:
                                continue
                            rows = ", ".join(
                                dumps(uid) + ": " + dumps({
                                    "y1": eb(u.statement.y1).hex(),
                                    "y2": eb(u.statement.y2).hex(),
                                    "registered_at": u.registered_at,
                                })
                                for uid, u in users_items
                            )
                            f.write(("" if first else ", ") + rows)
                            first = False
                        f.write('}, "sessions": [')
                        first = True
                        for _users, sess_values in cuts:
                            rows = ", ".join(
                                dumps({
                                    "token": sd.token,
                                    "user_id": sd.user_id,
                                    "created_at": sd.created_at,
                                    "expires_at": sd.expires_at,
                                })
                                for sd in sess_values
                                if not sd.is_expired(now)
                            )
                            if not rows:
                                continue
                            f.write(("" if first else ", ") + rows)
                            first = False
                        f.write("]")
                        if wal_seq is not None:
                            f.write(', "wal_seq": %d' % wal_seq)
                        f.write("}")
                        f.flush()
                        os.fsync(f.fileno())  # data durable before the rename
                    os.replace(tmp, path)
                except BaseException:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                    raise

            try:
                await _asyncio.to_thread(write)
            except BaseException:
                self._persist_dirty = True  # retry next sweep
                raise
            if covered is not None:
                # commit the watermark only once the document is on disk:
                # a failed write must not let compaction drop uncovered
                # records on the strength of a snapshot that never landed
                self.snapshot_covered_seq, self.snapshot_covered_offset = covered
            return True

    # cpzk-lint: disable=FENCE-001,ACK-001 -- boot-time snapshot load runs single-threaded before serving starts: no fleet map or fence is attached yet, the WAL replay that follows supplies durability, and nothing is acknowledged to any client
    async def restore(self, path: str) -> tuple[int, int]:
        """Load a snapshot into an empty state; returns (users, sessions).

        The file is a trust boundary: statements re-validate through the
        canonical decoder, every capacity cap is enforced, sessions must
        reference registered users and carry sane expiries — a corrupt or
        tampered file fails loudly rather than registering garbage."""
        import asyncio as _asyncio
        import json

        from ..core.ristretto import Ristretto255

        def _read() -> dict:
            with open(path, encoding="utf-8") as f:
                return json.load(f)

        # worker thread: a multi-MB snapshot read must not stall the loop
        doc = await _asyncio.to_thread(_read)
        # forward-compat gate: refuse only snapshots NEWER than this
        # build writes (naming both versions — the operator needs to know
        # which binary to run), accept unstamped pre-versioning files
        # (absence IS version 1) and any older stamp, refuse junk stamps
        ver = doc.get("version")
        if ver is not None and (
            not isinstance(ver, int) or isinstance(ver, bool)
        ):
            raise UnsupportedFormat(
                f"Unsupported state snapshot version: {ver!r}"
            )
        if ver is not None and ver > self.SNAPSHOT_VERSION:
            raise UnsupportedFormat(
                f"State snapshot is version {ver}, newer than this build "
                f"supports ({self.SNAPSHOT_VERSION}) — run a binary at "
                "least as new as the one that wrote it"
            )
        # WAL sequence number this document covers (0 for pre-durability
        # snapshots); recovery replays only journal records beyond it
        wal_seq = int(doc.get("wal_seq", 0))
        # Validate and build into locals first, commit only after the FULL
        # document passes: a mid-document rejection must not leave a
        # partially-populated state (a caller catching the error and
        # serving anyway would be running half the tampered snapshot).
        if len(doc["users"]) > self.max_users:
            raise InvalidParams("Snapshot exceeds the user capacity cap")
        if len(doc["sessions"]) > self.max_sessions:
            raise InvalidParams("Snapshot exceeds the session capacity cap")
        users: dict[str, UserData] = {}
        for uid, u in doc["users"].items():
            # same rules a live registration passes (service.rs:37-56,
            # :93-97): a tampered snapshot must not smuggle in what the
            # RPC would reject
            msg = user_id_error(uid)
            if msg is not None:
                raise InvalidParams(f"Snapshot user {uid!r}: {msg}")
            st = Statement(
                Ristretto255.element_from_bytes(bytes.fromhex(u["y1"])),
                Ristretto255.element_from_bytes(bytes.fromhex(u["y2"])),
            )
            if Ristretto255.is_identity(st.y1) or Ristretto255.is_identity(st.y2):
                raise InvalidParams(
                    f"Snapshot user {uid!r} has an identity statement element"
                )
            users[uid] = UserData(
                user_id=uid, statement=st, registered_at=int(u["registered_at"])
            )
        sessions: dict[str, SessionData] = {}
        user_sessions: dict[str, list[str]] = {}
        seen_tokens: set[str] = set()
        for s in doc["sessions"]:
            created, expires = int(s["created_at"]), int(s["expires_at"])
            if expires <= created or expires - created > SESSION_EXPIRY_SECONDS:
                raise InvalidParams("Snapshot session has an invalid expiry")
            data = SessionData(
                token=str(s["token"]),
                user_id=str(s["user_id"]),
                created_at=created,
                expires_at=expires,
            )
            if data.user_id not in users:
                raise InvalidParams(
                    "Snapshot session references an unregistered user"
                )
            if data.token in seen_tokens:
                raise InvalidParams("Snapshot contains a duplicate session token")
            seen_tokens.add(data.token)
            if data.is_expired():
                continue
            per_user = user_sessions.setdefault(data.user_id, [])
            if len(per_user) >= MAX_SESSIONS_PER_USER:
                raise InvalidParams("Snapshot exceeds a per-user session cap")
            sessions[data.token] = data
            per_user.append(data.token)
        # commit: distribute into the owning shards.  Boot-time and
        # single-threaded (like replay_journal_record), so no locks.
        if self._total_users() or self._total_sessions():
            raise InvalidParams("restore requires an empty state")
        for uid, u in users.items():
            self._user_insert(self._shard_for_user(uid), u)
        for token, sd in sessions.items():
            self._session_insert(self._shard_for_user(sd.user_id), sd)
        for uid, toks in user_sessions.items():
            self._shard_for_user(uid)._user_sessions[uid] = toks
        self._persist_dirty = True  # freshly-restored state is unsaved
        self.restored_wal_seq = wal_seq
        return len(users), len(sessions)
