"""In-memory server state: users, single-use challenges, sessions.

Reference parity (``src/verifier/state.rs``): same TTLs (challenge 300 s
with a 2x-age clock-skew guard, session 3600 s), per-user caps (3
challenges, 5 sessions), global caps (10k users / 50k challenges / 100k
sessions), consume-once challenge semantics, and cleanup sweeps.

Design deviation (deliberate): ONE ``asyncio.Lock`` guards all five maps.
The reference takes five ``RwLock``s in inconsistent order between
``create_challenge`` and ``consume_challenge`` (``state.rs:165-167`` vs
``:205-206``) — a deadlock hazard under contention flagged in SURVEY.md §5;
a single lock removes the hazard and is not a throughput bottleneck next to
group operations.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field

from ..errors import InvalidParams
from ..protocol.gadgets import Statement

CHALLENGE_EXPIRY_SECONDS = 300
MAX_CHALLENGES_PER_USER = 3
SESSION_EXPIRY_SECONDS = 3600
MAX_SESSIONS_PER_USER = 5

MAX_TOTAL_USERS = 10_000
MAX_TOTAL_CHALLENGES = 50_000
MAX_TOTAL_SESSIONS = 100_000

MAX_USER_ID_LEN = 256


def _valid_user_id_chars(user_id: str) -> bool:
    return all(c.isalnum() or c in "_-." for c in user_id)


def user_id_error(user_id: str) -> str | None:
    """Registration-time user-id rules (service.rs:37-56 twin): non-empty,
    <=256 chars, ``[A-Za-z0-9._-]`` only.  Shared by the gRPC service and
    the snapshot-restore trust boundary so the two can never drift."""
    if not user_id:
        return "User ID cannot be empty"
    if len(user_id) > MAX_USER_ID_LEN:
        return "User ID too long"
    if not _valid_user_id_chars(user_id):
        return "User ID contains invalid characters"
    return None


def _now() -> int:
    return int(time.time())


@dataclass
class UserData:
    user_id: str
    statement: Statement
    registered_at: int


@dataclass
class ChallengeData:
    challenge_id: bytes
    user_id: str
    created_at: int = field(default_factory=_now)
    expires_at: int = 0

    def __post_init__(self) -> None:
        if not self.expires_at:
            self.expires_at = self.created_at + CHALLENGE_EXPIRY_SECONDS

    def is_expired(self, now: int | None = None) -> bool:
        """TTL check with the reference's 2x-age clock-skew guard
        (state.rs:101-111)."""
        if now is None:
            now = _now()
        age = max(0, now - self.created_at)
        return now >= self.expires_at or age >= 2 * CHALLENGE_EXPIRY_SECONDS


@dataclass
class SessionData:
    token: str
    user_id: str
    created_at: int = field(default_factory=_now)
    expires_at: int = 0

    def __post_init__(self) -> None:
        if not self.expires_at:
            self.expires_at = self.created_at + SESSION_EXPIRY_SECONDS

    def is_expired(self, now: int | None = None) -> bool:
        """Same 2x-age clock-skew guard as :meth:`ChallengeData.is_expired`
        (state.rs:101-111): a wall clock stepping backward after mint must
        not silently extend a bearer token's lifetime past twice its TTL."""
        if now is None:
            now = _now()
        age = max(0, now - self.created_at)
        return now >= self.expires_at or age >= 2 * SESSION_EXPIRY_SECONDS


class ServerState:
    """All server registries behind one lock (see module docstring)."""

    def __init__(self) -> None:
        self._lock = asyncio.Lock()
        # serializes whole snapshot() calls: overlapping writers (cleanup
        # sweep vs shutdown) must rename in document-build order, or an
        # older doc can land over a newer one with _persist_dirty false
        self._snapshot_lock = asyncio.Lock()
        self._users: dict[str, UserData] = {}
        self._challenges: dict[bytes, ChallengeData] = {}
        self._user_challenges: dict[str, list[bytes]] = {}
        self._sessions: dict[str, SessionData] = {}
        self._user_sessions: dict[str, list[str]] = {}
        # set on any change to persisted data (users/sessions); lets the
        # periodic snapshot skip writes on an idle server
        self._persist_dirty = True
        # durability journal hook (WriteAheadLog | None): when attached,
        # every acknowledged mutation to persisted data is appended —
        # under the state lock, so WAL order always equals application
        # order — and fsynced (per policy) before the RPC returns
        self.journal = None
        # WAL sequence number the last-restored snapshot covered
        self.restored_wal_seq = 0
        # (seq, byte offset) of the journal at the last snapshot write:
        # the compaction watermark — everything before it is covered
        self.snapshot_covered_seq = 0
        self.snapshot_covered_offset = 0

    # --- durability journal (cpzk_tpu/durability/) ---

    def attach_journal(self, wal) -> None:
        """Install the write-ahead log as this state's journal hook (done
        once by ``DurabilityManager.recover`` before serving starts)."""
        self.journal = wal

    # cpzk-lint: disable=LOCK-001 -- append funnel: every caller holds self._lock (docstring contract)
    def _journal_append(self, rtype: str, payload: dict) -> None:
        """Append one record — callers hold ``self._lock``, which pins WAL
        order to in-memory application order."""
        if self.journal is not None:
            self.journal.append(rtype, payload)

    async def _journal_sync(self) -> None:
        """Make appended records durable per the WAL's fsync policy; called
        AFTER the state lock is released (fsync flushes every earlier
        append too, so interleaved mutations stay individually durable)
        and BEFORE the mutation is acknowledged to the caller."""
        wal = self.journal
        if wal is not None and wal.needs_sync():
            await asyncio.to_thread(wal.sync)

    # cpzk-lint: disable=LOCK-001 -- boot-time replay runs single-threaded before serving starts
    def replay_journal_record(self, rec: dict) -> str | None:
        """Boot-time replay of one WAL record through the same
        trust-boundary validators as :meth:`restore` — a tampered log
        cannot smuggle in what the live RPC would reject.  Single-threaded
        (recovery runs before serving starts), so no lock.  Returns None
        when applied, else the skip reason; never raises on malformed
        input (the fuzz harness holds this as an invariant)."""
        from ..core.ristretto import Ristretto255

        try:
            rtype = rec.get("type")
            if rtype == "register_user":
                uid = str(rec["user_id"])
                msg = user_id_error(uid)
                if msg is not None:
                    return msg
                if uid in self._users:
                    return "already registered"
                if len(self._users) >= MAX_TOTAL_USERS:
                    return "user capacity cap"
                y1 = Ristretto255.element_from_bytes(bytes.fromhex(rec["y1"]))
                y2 = Ristretto255.element_from_bytes(bytes.fromhex(rec["y2"]))
                if Ristretto255.is_identity(y1) or Ristretto255.is_identity(y2):
                    return "identity statement element"
                self._users[uid] = UserData(
                    user_id=uid,
                    statement=Statement(y1, y2),
                    registered_at=int(rec["registered_at"]),
                )
                self._persist_dirty = True
                return None
            if rtype == "create_session":
                token, uid = str(rec["token"]), str(rec["user_id"])
                created, expires = int(rec["created_at"]), int(rec["expires_at"])
                if expires <= created or expires - created > SESSION_EXPIRY_SECONDS:
                    return "invalid session expiry"
                if uid not in self._users:
                    return "unregistered user"
                if token in self._sessions:
                    return "duplicate session token"
                if len(self._sessions) >= MAX_TOTAL_SESSIONS:
                    return "session capacity cap"
                data = SessionData(
                    token=token, user_id=uid, created_at=created, expires_at=expires
                )
                if data.is_expired():
                    return None  # same silent drop as restore()
                per_user = self._user_sessions.setdefault(uid, [])
                if len(per_user) >= MAX_SESSIONS_PER_USER:
                    return "per-user session cap"
                self._sessions[token] = data
                per_user.append(token)
                self._persist_dirty = True
                return None
            if rtype == "revoke_session":
                data = self._sessions.pop(str(rec["token"]), None)
                if data is None:
                    return "session not found"
                per_user = self._user_sessions.get(data.user_id)
                if per_user is not None and data.token in per_user:
                    per_user.remove(data.token)
                self._persist_dirty = True
                return None
            if rtype == "expire_sessions":
                now = int(rec["now"])
                for t in [
                    t for t, d in self._sessions.items() if d.is_expired(now)
                ]:
                    data = self._sessions.pop(t)
                    per_user = self._user_sessions.get(data.user_id)
                    if per_user is not None and t in per_user:
                        per_user.remove(t)
                self._persist_dirty = True
                return None
            return f"unknown record type {rtype!r}"
        except Exception as e:  # malformed fields are a rejection, not a crash
            return f"malformed record: {e!r}"

    # --- users (state.rs:136-161) ---

    async def register_user(self, user_data: UserData) -> None:
        async with self._lock:
            if len(self._users) >= MAX_TOTAL_USERS:
                raise InvalidParams(
                    f"Server has reached maximum user capacity ({MAX_TOTAL_USERS})"
                )
            if user_data.user_id in self._users:
                raise InvalidParams(f"User '{user_data.user_id}' already registered")
            self._users[user_data.user_id] = user_data
            self._persist_dirty = True
            if self.journal is not None:
                from ..core.ristretto import Ristretto255

                eb = Ristretto255.element_to_bytes
                self._journal_append(
                    "register_user",
                    {
                        "user_id": user_data.user_id,
                        "y1": eb(user_data.statement.y1).hex(),
                        "y2": eb(user_data.statement.y2).hex(),
                        "registered_at": user_data.registered_at,
                    },
                )
        await self._journal_sync()

    async def get_user(self, user_id: str) -> UserData | None:
        return (await self.get_users([user_id]))[0]

    async def get_users(self, user_ids: list[str]) -> list[UserData | None]:
        async with self._lock:
            return [self._users.get(u) for u in user_ids]

    # --- challenges (state.rs:164-249) ---

    async def create_challenge(self, user_id: str, challenge_id: bytes) -> int:
        async with self._lock:
            if len(self._challenges) >= MAX_TOTAL_CHALLENGES:
                raise InvalidParams(
                    f"Server has reached maximum challenge capacity ({MAX_TOTAL_CHALLENGES})"
                )
            if user_id not in self._users:
                raise InvalidParams(f"User '{user_id}' not found")
            per_user = self._user_challenges.setdefault(user_id, [])
            if len(per_user) >= MAX_CHALLENGES_PER_USER:
                raise InvalidParams(f"Too many active challenges for user '{user_id}'")
            data = ChallengeData(challenge_id=challenge_id, user_id=user_id)
            per_user.append(challenge_id)
            self._challenges[challenge_id] = data
            return data.expires_at

    async def get_challenge(self, challenge_id: bytes) -> ChallengeData | None:
        async with self._lock:
            return self._challenges.get(challenge_id)

    async def consume_challenge(self, challenge_id: bytes) -> ChallengeData:
        """Single-use removal; expired challenges are removed AND rejected.
        Thin wrapper over the bulk form so the two can never desync."""
        data = (await self.consume_challenges([challenge_id]))[0]
        if data is None:
            raise InvalidParams("Invalid or expired challenge")
        return data

    async def consume_challenges(self, ids: list[bytes]) -> list[ChallengeData | None]:
        """Bulk consume-once under ONE lock acquisition (the batch RPC's
        hot path: n sequential ``consume_challenge`` awaits cost n event-
        loop round-trips).  Per-id semantics identical to
        :meth:`consume_challenge`, with ``None`` standing in for the
        invalid/expired rejection; duplicate ids in one batch behave as
        they would sequentially (first wins)."""
        async with self._lock:
            out: list[ChallengeData | None] = []
            for cid in ids:
                data = self._challenges.get(cid)
                if data is None:
                    out.append(None)
                    continue
                del self._challenges[cid]
                per_user = self._user_challenges.get(data.user_id)
                if per_user is not None and cid in per_user:
                    per_user.remove(cid)
                out.append(None if data.is_expired() else data)
            return out

    async def cleanup_expired_challenges(self) -> int:
        async with self._lock:
            expired = [cid for cid, d in self._challenges.items() if d.is_expired()]
            for cid in expired:
                data = self._challenges.pop(cid)
                per_user = self._user_challenges.get(data.user_id)
                if per_user is not None and cid in per_user:
                    per_user.remove(cid)
            return len(expired)

    # --- sessions (state.rs:252-327) ---

    async def create_session(self, token: str, user_id: str) -> None:
        """Thin wrapper over the bulk form so the two can never desync."""
        msg = (await self.create_sessions([(token, user_id)]))[0]
        if msg is not None:
            raise InvalidParams(msg)

    async def create_sessions(self, pairs: list[tuple[str, str]]) -> list[str | None]:
        """Bulk session mint under ONE lock: per-(token, user_id) result is
        ``None`` on success or the same error message
        :meth:`create_session` would raise, applied in order (so caps are
        enforced exactly as a sequential loop would)."""
        async with self._lock:
            out: list[str | None] = []
            for token, user_id in pairs:
                if len(self._sessions) >= MAX_TOTAL_SESSIONS:
                    out.append(
                        f"Server has reached maximum session capacity ({MAX_TOTAL_SESSIONS})"
                    )
                    continue
                per_user = self._user_sessions.setdefault(user_id, [])
                if len(per_user) >= MAX_SESSIONS_PER_USER:
                    out.append(
                        f"User '{user_id}' has reached maximum session limit ({MAX_SESSIONS_PER_USER})"
                    )
                    continue
                data = SessionData(token=token, user_id=user_id)
                self._sessions[token] = data
                per_user.append(token)
                self._persist_dirty = True
                self._journal_append(
                    "create_session",
                    {
                        "token": data.token,
                        "user_id": data.user_id,
                        "created_at": data.created_at,
                        "expires_at": data.expires_at,
                    },
                )
                out.append(None)
        await self._journal_sync()
        return out

    async def validate_session(self, token: str) -> str:
        async with self._lock:
            data = self._sessions.get(token)
            if data is None:
                raise InvalidParams("Invalid session token")
            if data.is_expired():
                raise InvalidParams("Session expired")
            return data.user_id

    async def revoke_session(self, token: str) -> None:
        async with self._lock:
            data = self._sessions.pop(token, None)
            if data is None:
                raise InvalidParams("Session not found")
            per_user = self._user_sessions.get(data.user_id)
            if per_user is not None and token in per_user:
                per_user.remove(token)
            self._persist_dirty = True
            self._journal_append("revoke_session", {"token": token})
        await self._journal_sync()

    async def cleanup_expired_sessions(self) -> int:
        async with self._lock:
            # one timestamp for the whole sweep, so the journaled record
            # replays to exactly the set of sessions removed here
            now = _now()
            expired = [t for t, d in self._sessions.items() if d.is_expired(now)]
            for t in expired:
                data = self._sessions.pop(t)
                per_user = self._user_sessions.get(data.user_id)
                if per_user is not None and t in per_user:
                    per_user.remove(t)
            if expired:
                self._persist_dirty = True
                self._journal_append("expire_sessions", {"now": now})
        await self._journal_sync()
        return len(expired)

    # --- counts (state.rs:330-342) ---

    async def user_count(self) -> int:
        async with self._lock:
            return len(self._users)

    async def session_count(self) -> int:
        async with self._lock:
            return len(self._sessions)

    async def challenge_count(self) -> int:
        async with self._lock:
            return len(self._challenges)

    # --- snapshot / restore (checkpoint-resume, SURVEY.md §5) -------------
    #
    # The reference has no persistence: a restart loses everything
    # (state.rs holds only in-memory maps).  In-memory remains this
    # framework's default for parity; snapshots are OPT-IN new capability
    # (--state-file).  Scope: users and sessions — challenges are 300-second
    # single-use nonces, and persisting them would extend their attack
    # window across restarts for no operational benefit (clients simply
    # re-request).  Format: versioned JSON, public data only (statements
    # are public by protocol design; session tokens are bearer secrets, so
    # the file must be protected like a session store — written 0600).
    # With a durability journal attached, each snapshot also records the
    # WAL sequence number it covers ("wal_seq"), so boot-time recovery
    # replays only the log suffix beyond it (cpzk_tpu/durability/).

    SNAPSHOT_VERSION = 1

    async def snapshot(self, path: str) -> bool:
        """Write users + live sessions to ``path`` (JSON); returns whether
        a write happened (skipped when nothing changed since the last
        snapshot).  The in-memory copy is taken under the state lock; the
        serialization + fsync + atomic rename run on a worker thread so
        the event loop (and every handler waiting on the lock) never
        stalls on disk I/O.  Whole calls serialize on a snapshot lock so
        overlapping writers (cleanup sweep vs shutdown) rename in
        document-build order — otherwise an older document could land
        over a newer one with ``_persist_dirty`` already false."""
        import asyncio as _asyncio
        import json
        import os

        from ..core.ristretto import Ristretto255

        eb = Ristretto255.element_to_bytes
        async with self._snapshot_lock:
            async with self._lock:
                if not self._persist_dirty:
                    return False
                doc = {
                    "version": self.SNAPSHOT_VERSION,
                    "users": {
                        uid: {
                            "y1": eb(u.statement.y1).hex(),
                            "y2": eb(u.statement.y2).hex(),
                            "registered_at": u.registered_at,
                        }
                        for uid, u in self._users.items()
                    },
                    "sessions": [
                        {
                            "token": s.token,
                            "user_id": s.user_id,
                            "created_at": s.created_at,
                            "expires_at": s.expires_at,
                        }
                        for s in self._sessions.values()
                        if not s.is_expired()
                    ],
                }
                covered: tuple[int, int] | None = None
                if self.journal is not None:
                    # captured under the state lock (appends hold it too),
                    # so this (seq, byte offset) pair names EXACTLY the WAL
                    # prefix this document covers — the compaction watermark
                    doc["wal_seq"] = self.journal.seq
                    covered = (self.journal.seq, self.journal.size)
                self._persist_dirty = False

            def write() -> None:
                # unique tmp name so a racing writer can never rename a
                # torn document; a distinctive prefix lets us sweep debris
                # a hard crash (SIGKILL between mkstemp and rename) left
                # behind — those files hold live bearer tokens
                import tempfile

                d = os.path.dirname(os.path.abspath(path)) or "."
                prefix = "." + os.path.basename(path) + ".tmp."
                for stale in os.listdir(d):
                    if stale.startswith(prefix):
                        try:
                            os.unlink(os.path.join(d, stale))
                        except OSError:
                            pass
                # mkstemp creates 0600 — the bearer-token protection requirement
                fd, tmp = tempfile.mkstemp(prefix=prefix, dir=d)
                try:
                    with os.fdopen(fd, "w") as f:
                        json.dump(doc, f)
                        f.flush()
                        os.fsync(f.fileno())  # data durable before the rename
                    os.replace(tmp, path)
                except BaseException:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                    raise

            try:
                await _asyncio.to_thread(write)
            except BaseException:
                self._persist_dirty = True  # retry next sweep
                raise
            if covered is not None:
                # commit the watermark only once the document is on disk:
                # a failed write must not let compaction drop uncovered
                # records on the strength of a snapshot that never landed
                self.snapshot_covered_seq, self.snapshot_covered_offset = covered
            return True

    async def restore(self, path: str) -> tuple[int, int]:
        """Load a snapshot into an empty state; returns (users, sessions).

        The file is a trust boundary: statements re-validate through the
        canonical decoder, every capacity cap is enforced, sessions must
        reference registered users and carry sane expiries — a corrupt or
        tampered file fails loudly rather than registering garbage."""
        import asyncio as _asyncio
        import json

        from ..core.ristretto import Ristretto255

        def _read() -> dict:
            with open(path, encoding="utf-8") as f:
                return json.load(f)

        # worker thread: a multi-MB snapshot read must not stall the loop
        doc = await _asyncio.to_thread(_read)
        if doc.get("version") != self.SNAPSHOT_VERSION:
            raise InvalidParams(
                f"Unsupported state snapshot version: {doc.get('version')!r}"
            )
        # WAL sequence number this document covers (0 for pre-durability
        # snapshots); recovery replays only journal records beyond it
        wal_seq = int(doc.get("wal_seq", 0))
        # Validate and build into locals first, commit only after the FULL
        # document passes: a mid-document rejection must not leave a
        # partially-populated state (a caller catching the error and
        # serving anyway would be running half the tampered snapshot).
        if len(doc["users"]) > MAX_TOTAL_USERS:
            raise InvalidParams("Snapshot exceeds the user capacity cap")
        if len(doc["sessions"]) > MAX_TOTAL_SESSIONS:
            raise InvalidParams("Snapshot exceeds the session capacity cap")
        users: dict[str, UserData] = {}
        for uid, u in doc["users"].items():
            # same rules a live registration passes (service.rs:37-56,
            # :93-97): a tampered snapshot must not smuggle in what the
            # RPC would reject
            msg = user_id_error(uid)
            if msg is not None:
                raise InvalidParams(f"Snapshot user {uid!r}: {msg}")
            st = Statement(
                Ristretto255.element_from_bytes(bytes.fromhex(u["y1"])),
                Ristretto255.element_from_bytes(bytes.fromhex(u["y2"])),
            )
            if Ristretto255.is_identity(st.y1) or Ristretto255.is_identity(st.y2):
                raise InvalidParams(
                    f"Snapshot user {uid!r} has an identity statement element"
                )
            users[uid] = UserData(
                user_id=uid, statement=st, registered_at=int(u["registered_at"])
            )
        sessions: dict[str, SessionData] = {}
        user_sessions: dict[str, list[str]] = {}
        seen_tokens: set[str] = set()
        for s in doc["sessions"]:
            created, expires = int(s["created_at"]), int(s["expires_at"])
            if expires <= created or expires - created > SESSION_EXPIRY_SECONDS:
                raise InvalidParams("Snapshot session has an invalid expiry")
            data = SessionData(
                token=str(s["token"]),
                user_id=str(s["user_id"]),
                created_at=created,
                expires_at=expires,
            )
            if data.user_id not in users:
                raise InvalidParams(
                    "Snapshot session references an unregistered user"
                )
            if data.token in seen_tokens:
                raise InvalidParams("Snapshot contains a duplicate session token")
            seen_tokens.add(data.token)
            if data.is_expired():
                continue
            per_user = user_sessions.setdefault(data.user_id, [])
            if len(per_user) >= MAX_SESSIONS_PER_USER:
                raise InvalidParams("Snapshot exceeds a per-user session cap")
            sessions[data.token] = data
            per_user.append(data.token)
        async with self._lock:
            if self._users or self._sessions:
                raise InvalidParams("restore requires an empty state")
            self._users = users
            self._sessions = sessions
            self._user_sessions = user_sessions
            self._persist_dirty = True  # freshly-restored state is unsaved
            self.restored_wal_seq = wal_seq
            return len(users), len(sessions)
