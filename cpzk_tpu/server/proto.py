"""Protobuf message bindings for ``proto/auth.proto``.

``grpc_tools`` is not available in this environment, so the message module
is generated with the ``protoc`` binary on first import (into
``cpzk_tpu/_gen/``) and the gRPC plumbing is hand-wired from grpcio's
generic handler API instead of a generated ``*_pb2_grpc`` module (see
``service.py`` / ``client/rpc.py``). Reference analog: ``build.rs:1-12``
compiling the proto with tonic-build at build time.
"""

from __future__ import annotations

import importlib
import os
import subprocess
import sys

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_GEN_DIR = os.path.join(_PKG_DIR, "_gen")
_PROTO_DIR = os.path.join(os.path.dirname(_PKG_DIR), "proto")

SERVICE_NAME = "auth.AuthService"

_METHODS = {
    "Register": ("RegistrationRequest", "RegistrationResponse"),
    "RegisterBatch": ("BatchRegistrationRequest", "BatchRegistrationResponse"),
    "CreateChallenge": ("ChallengeRequest", "ChallengeResponse"),
    "VerifyProof": ("VerificationRequest", "VerificationResponse"),
    "VerifyProofBatch": ("BatchVerificationRequest", "BatchVerificationResponse"),
}

#: Bidirectional-streaming RPCs (wired via stream_stream handlers, kept
#: out of ``_METHODS`` so the unary stub/handler loops stay unchanged).
_STREAM_METHODS = {
    "VerifyProofStream": ("StreamVerifyRequest", "StreamVerifyResponse"),
}


def _generate(name: str) -> None:
    os.makedirs(_GEN_DIR, exist_ok=True)
    open(os.path.join(_GEN_DIR, "__init__.py"), "a").close()
    subprocess.run(
        [
            "protoc",
            f"--python_out={_GEN_DIR}",
            f"-I{_PROTO_DIR}",
            name,
        ],
        check=True,
        capture_output=True,
        timeout=60,
    )


def _load(module: str, proto_name: str):
    gen_path = os.path.join(_GEN_DIR, module + ".py")
    if not os.path.exists(gen_path):
        _generate(proto_name)
    if _GEN_DIR not in sys.path:
        sys.path.insert(0, _GEN_DIR)
    return importlib.import_module(module)


def load_pb2():
    """The generated ``auth_pb2`` module (generating it if needed)."""
    return _load("auth_pb2", "auth.proto")


def load_health_pb2():
    """The generated ``health_pb2`` module (grpc.health.v1)."""
    return _load("health_pb2", "health.proto")


def load_replication_pb2():
    """The generated ``replication_pb2`` module (WAL segment shipping)."""
    return _load("replication_pb2", "replication.proto")


def method_types(pb2):
    """{rpc name: (request class, response class)} for the unary RPCs."""
    return {
        name: (getattr(pb2, req), getattr(pb2, resp))
        for name, (req, resp) in _METHODS.items()
    }


def stream_method_types(pb2):
    """{rpc name: (request class, response class)} for the bidi-streaming
    RPCs (``VerifyProofStream``)."""
    return {
        name: (getattr(pb2, req), getattr(pb2, resp))
        for name, (req, resp) in _STREAM_METHODS.items()
    }
