"""Server configuration and rate limiting.

Same layering and names as the reference (``src/verifier/config.rs``):
defaults <- TOML file (path from ``SERVER_CONFIG_PATH``, default
``config/server.toml``) <- ``.env`` file <- ``SERVER_*`` environment
variables, then CLI flags on top (the reference leaves CLI/figment
unreconciled — SURVEY.md §3.3 flags it; here the CLI layer goes through the
same resolved object). Token-bucket rate limiter with fractional refill and
burst cap (``config.rs:103-118``).
"""

from __future__ import annotations

import os
import time
import asyncio

try:
    import tomllib  # Python >= 3.11
except ModuleNotFoundError:  # pragma: no cover - 3.10 containers
    import tomli as tomllib  # type: ignore[no-redef]
from dataclasses import dataclass, field


@dataclass
class ServerSettings:
    """Transport-plane knobs (``[server]``): the native wire path and the
    SO_REUSEPORT sharded-ingest mode.  See ``docs/operations.md``
    §"Wire path & ingest shards"."""

    wire: str = "native"   # "native" = hand-rolled C++ parse of the hot
                           # request messages straight off the socket
                           # bytes (unconditional fallback to the Python
                           # protobuf runtime when the .so is absent or a
                           # message is outside the parser's recognized
                           # subset) | "python" = always the protobuf
                           # runtime (today's path)
    ingest_shards: int = 1  # 1 = in-process listener (today's path,
                            # structurally unchanged); N > 1 = N forked
                            # event-loop processes each bind the listener
                            # via SO_REUSEPORT and run admission + native
                            # parse, feeding this dispatch/state process
                            # over a CRC-framed unix-socket seam — ingest
                            # scales with host cores the way the device
                            # plane scales with chips
    # global state-capacity caps (reference-parity defaults; raise them
    # for million-user deployments — the soak harness does).  Counters
    # are maintained integers, so a large cap costs nothing per RPC.
    max_users: int = 10_000
    max_challenges: int = 50_000
    max_sessions: int = 100_000


@dataclass
class RateLimitSettings:
    """The GLOBAL token bucket — the aggregate backstop behind the
    per-client buckets in ``[admission]``.  ``requests_per_minute`` has
    no "0 disables" semantics here (a server that admits nothing is a
    misconfiguration): set it very large to effectively disable.  The
    per-client limits in :class:`AdmissionSettings` use ``0`` = unset."""

    requests_per_minute: int = 100
    burst: int = 10

    def build_limiter(self) -> "RateLimiter":
        return RateLimiter(self.requests_per_minute, self.burst)


@dataclass
class MetricsSettings:
    enabled: bool = False  # opt-in, like the reference's --metrics flag
    host: str = "127.0.0.1"
    port: int = 9090


@dataclass
class TlsSettings:
    enabled: bool = False
    cert_path: str = ""
    key_path: str = ""


@dataclass
class TpuSettings:
    """TPU serving knobs (the additions VERDICT r1 asked for: backend
    selection, batch-size target, queue deadline, mesh shape) plus the
    resilience-subsystem knobs (breaker recovery, probe sizing, deadline
    shedding)."""

    backend: str = "cpu"          # "cpu" (inline host verify) | "tpu"
    batch_max: int = 4096         # dynamic-batcher device batch target
    batch_window_ms: float = 5.0  # queue deadline before dispatch
    mesh_devices: int = 0         # 0 = all visible devices
    lanes: int = 1                # per-device dispatch lanes behind the
                                  # LaneRouter: 1 = single-lane (today's
                                  # path, structurally unchanged),
                                  # -1 = one lane per local device,
                                  # k > 1 = the first k local devices
    mesh_threshold: int = 0       # entries at/above which a settled batch
                                  # routes to the mesh lane (one sharded
                                  # program over all lane devices) instead
                                  # of one per-device lane; 0 = never —
                                  # the crossover is silicon-specific, so
                                  # it ships as a measured knob, not a
                                  # guess
    pipeline_depth: int = 2       # in-flight batches (1 = serial dispatch);
                                  # >1 double-buffers host prep against
                                  # device compute on the dispatch lane
    prewarm_quanta: str = ""      # comma list of batch sizes whose verify
                                  # kernels are AOT-compiled BEFORE the
                                  # server reports ready (empty = no
                                  # prewarm; first dispatch per padded
                                  # shape pays the XLA trace+compile)

    def parsed_prewarm_quanta(self) -> list[int]:
        """Batch sizes from the comma-separated config string."""
        text = self.prewarm_quanta.strip()
        if not text:
            return []
        return [int(part) for part in text.split(",") if part.strip()]
    recovery_after_s: float = 30.0  # breaker cooldown before a TPU probe
                                    # (0 = probe immediately; -1 = never
                                    # self-heal, degrade until /reset)
    probe_batch_max: int = 64     # rows re-verified on the TPU per probe
    shed_expired: bool = True     # drop deadline-expired queue entries
    stream_window: int = 8192     # max in-flight proofs per
                                  # VerifyProofStream before the reader
                                  # stops pulling (gRPC flow control then
                                  # pushes back on the sender)
    stream_entry_deadline_ms: float = 0.0  # per-entry verify deadline on
                                  # streams; 0 = only the stream's own
                                  # gRPC deadline applies


@dataclass
class OpsplaneSettings:
    """HTTP introspection server (ops plane): remote, read-only access to
    ``/metrics``, ``/statusz``, ``/tracez``, ``/flightrec``, ``/healthz``,
    and ``/slo`` — the surfaces that were REPL-only before.  Dependency-
    free (stdlib asyncio); started by the daemon BEFORE the gRPC listener
    so a booting box is observable while it recovers.  No auth layer:
    bind to loopback (default) or an internal interface.  See
    ``docs/operations.md`` §"Ops plane & SLOs"."""

    enabled: bool = False
    host: str = "127.0.0.1"
    port: int = 9092          # 0 = OS-assigned (tests bind ephemeral)


@dataclass
class SloSettings:
    """SLO objectives + burn-rate alerting thresholds over the per-RPC
    request/duration families (``observability/slo.py``).  Burn rates are
    computed over the standard multi-window pairs (5m/1h fast, 30m/6h
    slow); a page fires only when BOTH windows of a pair exceed the
    pair's threshold.  See ``docs/operations.md`` §"Ops plane & SLOs"."""

    availability_target: float = 0.999  # fraction of requests that must
                                        # succeed (99.9%)
    latency_ms: str = ""          # per-RPC mean-latency targets as
                                  # "Rpc=ms" pairs, comma-separated
                                  # (e.g. "VerifyProof=250,Register=100");
                                  # empty = built-in per-class defaults
    fast_burn_threshold: float = 14.4  # page when 5m AND 1h burn >= this
    slow_burn_threshold: float = 6.0   # page when 30m AND 6h burn >= this
    tick_interval_ms: float = 5000.0   # engine sampling cadence

    def parsed_latency_ms(self) -> dict[str, float]:
        """{rpc: target ms} overrides from the config string."""
        out: dict[str, float] = {}
        text = self.latency_ms.strip()
        if not text:
            return out
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            rpc, _, value = part.partition("=")
            if not rpc.strip() or not value.strip():
                raise ValueError(f"malformed latency_ms entry: {part!r}")
            out[rpc.strip()] = float(value)
        return out


@dataclass
class ObservabilitySettings:
    """Tracing/telemetry knobs (observability subsystem): the JSON log
    formatter opt-in, the slow-request WARNING threshold, the completed-
    trace ring capacity behind the admin REPL's ``/tracez``, and an
    optional override of the TPU-tuned histogram bucket schedule."""

    json_logs: bool = False        # structured JSON log records (opt-in)
    slow_request_ms: float = 1000.0  # >= this logs a WARNING with stage
                                     # breakdown; 0 logs every request,
                                     # -1 disables slow-request logging
    trace_ring: int = 256          # completed traces kept for /tracez
    latency_buckets_ms: str = ""   # comma-separated upper bounds in ms;
                                   # empty keeps the built-in schedule
    flight_ring: int = 512         # device batches kept for /flightrec
                                   # and the SIGUSR2 JSON dump
    compile_storm_threshold: int = 8  # first-sight jit compiles per 60s
                                      # window that trigger the
                                      # compile-storm WARNING

    def parsed_buckets(self) -> list[float]:
        """Bucket bounds in SECONDS from the ms-denominated config string
        (empty list = keep the metrics module's built-in default)."""
        text = self.latency_buckets_ms.strip()
        if not text:
            return []
        return [float(part) / 1000.0 for part in text.split(",") if part.strip()]


@dataclass
class DurabilitySettings:
    """Crash-consistent persistence knobs (durability subsystem): upgrade
    the opt-in ``state_file`` snapshot to snapshot + write-ahead log, so
    an acknowledged mutation survives a crash between cleanup sweeps.
    See ``docs/operations.md`` §"Durability & recovery"."""

    enabled: bool = False         # opt-in; requires state_file to be set
    wal_path: str = ""            # empty = "<state_file>.wal"
    fsync: str = "always"         # "always" | "interval" | "off"
    fsync_interval_ms: float = 50.0  # fsync cadence under the interval
                                     # policy (= the acknowledged-write
                                     # loss window)
    compact_bytes: int = 1_048_576   # compact the WAL once it outgrows
                                     # this after a covering snapshot;
                                     # 0 = compact on every snapshot
    wal_segment_bytes: int = 0       # rotate the WAL into sealed
                                     # <wal>.<first>-<last>.seg files at
                                     # about this size (0 = single-file
                                     # log, copy-compaction).  Sealed
                                     # segments make compaction an
                                     # unlink of fully-covered files —
                                     # append stalls stop scaling with
                                     # the surviving tail


@dataclass
class ReplicationSettings:
    """Replicated server state (replication subsystem): WAL segment
    shipping from the primary to a warm standby, lease-based promotion,
    and epoch fencing.  Built on [durability] (``enabled`` requires it).
    See ``docs/operations.md`` §"Replication & failover"."""

    enabled: bool = False
    role: str = "primary"        # "primary" (ships) | "standby" (receives)
    peer: str = ""               # primary: the standby's gRPC address
    mode: str = "async"          # "async" (lose <= renew_interval of acked
                                 # writes on failover) | "sync" (acks wait
                                 # for standby apply: zero loss)
    lease_ms: float = 3000.0     # standby promotes after this long without
                                 # contact from an equal-or-higher epoch
    renew_interval_ms: float = 500.0  # ship/renew cadence; MUST be < lease_ms
    segment_bytes: int = 65536   # seal shipped segments at about this size
    sync_timeout_ms: float = 1000.0   # sync-mode ack deadline (past it the
                                      # mutation FAILS, not silently async)
    auto_promote: bool = True    # standby self-promotes on lease expiry
                                 # (false = operator /promote only)
    epoch_file: str = ""         # empty = "<state_file>.epoch"
    shards: int = 16             # ServerState lock shards; ids/tokens carry
                                 # the shard tag, so a replicated pair MUST
                                 # agree on this value (1..256)
    handover_on_term: bool = True     # SIGTERM on a primary with a standby
                                      # attached runs the coordinated
                                      # handover before draining (a missing
                                      # or stale standby falls back to the
                                      # plain drain, loudly)
    handover_timeout_ms: float = 5000.0  # deadline for the whole handover
                                         # (fence-watermark catch-up + the
                                         # promote exchange); past it the
                                         # handover aborts and unfences


@dataclass
class AuditSettings:
    """Proof-log audit trail (audit subsystem): when enabled, the service
    appends one CRC-framed record per verified proof (statement,
    challenge, proof wire, verdict) to ``log_path``; ``python -m
    cpzk_tpu.audit run`` later replays it through the batch engine and
    emits a Schnorr-signed report.  See ``docs/operations.md``
    §"Streaming & audit"."""

    enabled: bool = False         # opt-in; requires log_path
    log_path: str = ""            # the proof log (created 0600)
    fsync: str = "off"            # "always" | "interval" | "off" — an
                                  # audit trail usually tolerates losing
                                  # the last instants of a crash
    fsync_interval_ms: float = 200.0  # cadence under the interval policy
    segment_bytes: int = 0        # rotate the log into sealed
                                  # <log>.<first>-<last>.seg files at about
                                  # this size; sealed segments ship to the
                                  # replication standby so a machine death
                                  # loses at most the unsealed tail
                                  # (0 = never rotate)


@dataclass
class FleetSettings:
    """N-partition fleet routing (fleet subsystem): this daemon's slot in
    a versioned :class:`~cpzk_tpu.fleet.PartitionMap`.  Every auth RPC
    then checks ownership before touching state and redirects
    wrong-partition requests with the map version + owner address in
    trailing metadata; the ops plane serves the map read-only at
    ``/partitionmap``.  See ``docs/operations.md`` §"Partitioned
    fleet"."""

    enabled: bool = False      # opt-in; requires map_path
    map_path: str = ""         # the serialized partition-map JSON file
    partition: int = -1        # this daemon's partition index;
                               # -1 = discover by matching `advertise`
                               # (or host:port) against the map
    advertise: str = ""        # this partition's address as it appears in
                               # the map (empty = "<host>:<port>")


@dataclass
class ControllerSettings:
    """Self-driving fleet control loop (``fleet/controller.py``): a
    daemon-resident ticker that consumes the existing observability
    signals (SLO burn pages, per-shard sizes + lock-wait, lane breaker
    states) and acts through the existing actuators — live partition
    split, lane drain/re-admit, admission level cap.  Ships with
    ``dry_run = true``: decisions are computed, traced, and surfaced on
    ``/statusz`` but no actuator fires until an operator flips it.  See
    ``docs/operations.md`` §"Fleet controller & failure storms"."""

    enabled: bool = False
    tick_interval_ms: float = 1000.0  # signal sampling cadence
    dry_run: bool = True          # compute + publish decisions, act on none
    decision_ring: int = 64       # last-N decisions kept for /statusz
    # two-sided hysteresis: a signal must stay hot for act_ticks
    # consecutive ticks before the action fires, and stay clear for
    # clear_ticks consecutive ticks before the reverse action (lane
    # re-admit, admission cap restore) fires — the controller cannot flap
    act_ticks: int = 3
    clear_ticks: int = 5
    # live partition split: fires when THIS partition's user count or
    # sustained mean shard lock-wait crosses its capacity envelope
    # (calibrate both from the soak harness; 0 = that trigger disabled)
    split_user_threshold: int = 0
    split_lock_wait_ms: float = 0.0
    split_target_address: str = ""  # address the new partition will own in
                                    # the flipped map; empty disarms splits
    split_cooldown_s: float = 600.0
    # lane drain: fires when a lane breaker stays OPEN this long;
    # re-admit once the breaker has been CLOSED for clear_ticks ticks
    lane_open_after_s: float = 10.0
    lane_cooldown_s: float = 30.0   # min seconds a drained lane stays out
    # admission bias: cap the AIMD level one tier down per shrink while a
    # login SLO burn page is firing; restore tier-by-tier on clear ticks
    slo_rpc: str = "VerifyProof"    # the RPC whose burn pages drive it
    admission_cooldown_s: float = 15.0
    # retry spacing after an actuator RAISED: the failed action's full
    # cooldown is rolled back (nothing changed in the planes) and this
    # short backoff governs the retry instead — a transient split
    # failure must not burn the 600 s split cooldown
    error_backoff_s: float = 30.0


@dataclass
class AdmissionSettings:
    """Adaptive overload control (admission subsystem): per-client keyed
    token buckets in an LRU-bounded table, DAGOR-style priority-aware
    shedding driven by live queue signals, and server retry-pushback
    sizing.  See ``docs/operations.md`` §"Overload & admission"."""

    enabled: bool = True
    # per-client fair limiting; 0 = DISABLED (the unset state — unlike
    # the global [rate_limit] bucket, where 0 is invalid)
    per_client_rpm: int = 0
    per_client_burst: int = 20
    max_clients: int = 1024       # LRU bound on the keyed-bucket table
    # adaptive priority shedding (AIMD on the admission level)
    high_watermark: float = 0.75  # queue utilization that sheds harder
    low_watermark: float = 0.50   # utilization below which we re-admit
    target_queue_wait_ms: float = 50.0  # avg queue_wait that counts as
                                        # overload even at low depth
    adjust_interval_ms: float = 100.0   # signal sampling / AIMD cadence
    increase_step: float = 0.1    # additive level increase per healthy tick
    decrease_factor: float = 0.5  # multiplicative decrease on overload
    # server pushback bounds (cpzk-retry-after-ms trailing metadata)
    retry_after_min_ms: float = 25.0
    retry_after_max_ms: float = 5000.0


@dataclass
class RetrySettings:
    """Client retry knobs (resilience subsystem): exponential backoff with
    full jitter and a shared retry budget, applied by ``AuthClient`` to
    idempotent-safe RPCs only.  ``budget = 0`` disables retries."""

    max_attempts: int = 3
    initial_backoff_ms: float = 50.0
    max_backoff_ms: float = 1000.0
    multiplier: float = 2.0
    budget: float = 10.0       # channel-wide retry tokens
    token_ratio: float = 0.1   # budget refill per success

    def build_policy(self):
        """Resolve to a ``RetryPolicy`` (None when retries are disabled)."""
        from ..resilience.retry import RetryBudget, RetryPolicy

        if self.budget <= 0 or self.max_attempts <= 1:
            return None
        return RetryPolicy(
            max_attempts=self.max_attempts,
            initial_backoff_s=self.initial_backoff_ms / 1000.0,
            max_backoff_s=self.max_backoff_ms / 1000.0,
            multiplier=self.multiplier,
            budget=RetryBudget(tokens=self.budget, token_ratio=self.token_ratio),
        )


@dataclass
class ServerConfig:
    host: str = "127.0.0.1"
    port: int = 50051
    # opt-in checkpoint/resume (empty = in-memory only, reference parity)
    state_file: str = ""
    server: ServerSettings = field(default_factory=ServerSettings)
    rate_limit: RateLimitSettings = field(default_factory=RateLimitSettings)
    admission: AdmissionSettings = field(default_factory=AdmissionSettings)
    metrics: MetricsSettings = field(default_factory=MetricsSettings)
    tls: TlsSettings = field(default_factory=TlsSettings)
    tpu: TpuSettings = field(default_factory=TpuSettings)
    retry: RetrySettings = field(default_factory=RetrySettings)
    observability: ObservabilitySettings = field(
        default_factory=ObservabilitySettings
    )
    durability: DurabilitySettings = field(default_factory=DurabilitySettings)
    replication: ReplicationSettings = field(
        default_factory=ReplicationSettings
    )
    audit: AuditSettings = field(default_factory=AuditSettings)
    opsplane: OpsplaneSettings = field(default_factory=OpsplaneSettings)
    slo: SloSettings = field(default_factory=SloSettings)
    fleet: FleetSettings = field(default_factory=FleetSettings)
    controller: ControllerSettings = field(default_factory=ControllerSettings)

    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def fingerprint(self) -> str:
        """Stable 12-hex digest of the fully-resolved config — the
        ``config_fingerprint`` row of the ops plane's ``/statusz``, so an
        operator can tell at a glance whether two boxes (or a box and a
        deploy manifest) are running the same configuration."""
        import dataclasses
        import hashlib
        import json

        doc = json.dumps(dataclasses.asdict(self), sort_keys=True)
        return hashlib.sha256(doc.encode()).hexdigest()[:12]

    # --- loading (config.rs:218-232 precedence) ---

    @classmethod
    def from_env(cls) -> "ServerConfig":
        _load_dotenv()
        cfg = cls()
        config_path = os.environ.get("SERVER_CONFIG_PATH", "config/server.toml")
        if os.path.exists(config_path):
            with open(config_path, "rb") as f:
                cfg._merge_mapping(tomllib.load(f))
        cfg._merge_env()
        return cfg

    def _merge_mapping(self, data: dict) -> None:
        if "host" in data:
            self.host = str(data["host"])
        if "port" in data:
            self.port = int(data["port"])
        if "state_file" in data:
            self.state_file = str(data["state_file"])
        for section, obj in (
            ("server", self.server),
            ("rate_limit", self.rate_limit),
            ("admission", self.admission),
            ("metrics", self.metrics),
            ("tls", self.tls),
            ("tpu", self.tpu),
            ("retry", self.retry),
            ("observability", self.observability),
            ("durability", self.durability),
            ("replication", self.replication),
            ("audit", self.audit),
            ("opsplane", self.opsplane),
            ("slo", self.slo),
            ("fleet", self.fleet),
            ("controller", self.controller),
        ):
            for key, value in data.get(section, {}).items():
                if hasattr(obj, key):
                    setattr(obj, key, type(getattr(obj, key))(value))

    def _merge_env(self) -> None:
        """``SERVER_`` prefix, components split on ``_`` like figment's
        ``Env.prefixed("SERVER_").split("_")`` (nested keys greedy-match the
        known sections, e.g. SERVER_RATE_LIMIT_BURST)."""
        env = os.environ

        def get(name: str) -> str | None:
            return env.get(f"SERVER_{name}")

        def get_alias(primary: str, alias: str) -> str | None:
            # A set-but-empty primary must win over the alias (ADVICE r2:
            # `get(a) or get(b)` treats "" as unset and falls through) —
            # but an empty value means "explicitly unset": it suppresses
            # the alias AND keeps the default, rather than crashing int()
            # (deployment templates render optional vars as empty).
            v = get(primary)
            if v is None:
                v = get(alias)
            return v if v != "" else None

        if (v := get("HOST")) is not None:
            self.host = v
        if (v := get("PORT")) is not None:
            self.port = int(v)
        if (v := get("STATE_FILE")) is not None:
            self.state_file = v
        # transport-plane knobs (native wire path + sharded ingest)
        if (v := get("WIRE")) is not None:
            self.server.wire = v.lower()
        if (v := get("INGEST_SHARDS")) is not None:
            self.server.ingest_shards = int(v)
        if (v := get("MAX_USERS")) is not None:
            self.server.max_users = int(v)
        if (v := get("MAX_CHALLENGES")) is not None:
            self.server.max_challenges = int(v)
        if (v := get("MAX_SESSIONS")) is not None:
            self.server.max_sessions = int(v)
        # short aliases mirror the reference's clap env names
        if (v := get_alias("RATE_LIMIT_REQUESTS_PER_MINUTE", "RATE_LIMIT")) is not None:
            self.rate_limit.requests_per_minute = int(v)
        if (v := get_alias("RATE_LIMIT_BURST", "RATE_BURST")) is not None:
            self.rate_limit.burst = int(v)
        # admission knobs (overload control subsystem)
        if (v := get("ADMISSION_ENABLED")) is not None:
            self.admission.enabled = v.lower() in ("1", "true", "yes", "on")
        if (v := get("ADMISSION_PER_CLIENT_RPM")) is not None:
            self.admission.per_client_rpm = int(v)
        if (v := get("ADMISSION_PER_CLIENT_BURST")) is not None:
            self.admission.per_client_burst = int(v)
        if (v := get("ADMISSION_MAX_CLIENTS")) is not None:
            self.admission.max_clients = int(v)
        if (v := get("ADMISSION_HIGH_WATERMARK")) is not None:
            self.admission.high_watermark = float(v)
        if (v := get("ADMISSION_LOW_WATERMARK")) is not None:
            self.admission.low_watermark = float(v)
        if (v := get("ADMISSION_TARGET_QUEUE_WAIT_MS")) is not None:
            self.admission.target_queue_wait_ms = float(v)
        if (v := get("ADMISSION_ADJUST_INTERVAL_MS")) is not None:
            self.admission.adjust_interval_ms = float(v)
        if (v := get("ADMISSION_INCREASE_STEP")) is not None:
            self.admission.increase_step = float(v)
        if (v := get("ADMISSION_DECREASE_FACTOR")) is not None:
            self.admission.decrease_factor = float(v)
        if (v := get("ADMISSION_RETRY_AFTER_MIN_MS")) is not None:
            self.admission.retry_after_min_ms = float(v)
        if (v := get("ADMISSION_RETRY_AFTER_MAX_MS")) is not None:
            self.admission.retry_after_max_ms = float(v)
        if (v := get_alias("METRICS_ENABLED", "METRICS")) is not None:
            self.metrics.enabled = v.lower() in ("1", "true", "yes", "on")
        if (v := get("METRICS_HOST")) is not None:
            self.metrics.host = v
        if (v := get("METRICS_PORT")) is not None:
            self.metrics.port = int(v)
        if (v := get("TLS_ENABLED")) is not None:
            self.tls.enabled = v.lower() in ("1", "true", "yes", "on")
        if (v := get("TLS_CERT_PATH")) is not None:
            self.tls.cert_path = v
        if (v := get("TLS_KEY_PATH")) is not None:
            self.tls.key_path = v
        if (v := get("TPU_BACKEND")) is not None:
            self.tpu.backend = v.lower()
        if (v := get("TPU_BATCH_MAX")) is not None:
            self.tpu.batch_max = int(v)
        if (v := get("TPU_BATCH_WINDOW_MS")) is not None:
            self.tpu.batch_window_ms = float(v)
        if (v := get("TPU_MESH_DEVICES")) is not None:
            self.tpu.mesh_devices = int(v)
        if (v := get("TPU_LANES")) is not None:
            self.tpu.lanes = int(v)
        if (v := get("TPU_MESH_THRESHOLD")) is not None:
            self.tpu.mesh_threshold = int(v)
        if (v := get("TPU_PIPELINE_DEPTH")) is not None:
            self.tpu.pipeline_depth = int(v)
        if (v := get("TPU_RECOVERY_AFTER_S")) is not None:
            self.tpu.recovery_after_s = float(v)
        if (v := get("TPU_PROBE_BATCH_MAX")) is not None:
            self.tpu.probe_batch_max = int(v)
        if (v := get("TPU_SHED_EXPIRED")) is not None:
            self.tpu.shed_expired = v.lower() in ("1", "true", "yes", "on")
        if (v := get("TPU_PREWARM_QUANTA")) is not None:
            self.tpu.prewarm_quanta = v
        if (v := get("TPU_STREAM_WINDOW")) is not None:
            self.tpu.stream_window = int(v)
        if (v := get("TPU_STREAM_ENTRY_DEADLINE_MS")) is not None:
            self.tpu.stream_entry_deadline_ms = float(v)
        if (v := get("RETRY_MAX_ATTEMPTS")) is not None:
            self.retry.max_attempts = int(v)
        if (v := get("RETRY_INITIAL_BACKOFF_MS")) is not None:
            self.retry.initial_backoff_ms = float(v)
        if (v := get("RETRY_MAX_BACKOFF_MS")) is not None:
            self.retry.max_backoff_ms = float(v)
        if (v := get("RETRY_MULTIPLIER")) is not None:
            self.retry.multiplier = float(v)
        if (v := get("RETRY_BUDGET")) is not None:
            self.retry.budget = float(v)
        if (v := get("RETRY_TOKEN_RATIO")) is not None:
            self.retry.token_ratio = float(v)
        # observability knobs (short OBS_* aliases mirror the section name)
        if (v := get_alias("OBSERVABILITY_JSON_LOGS", "OBS_JSON_LOGS")) is not None:
            self.observability.json_logs = v.lower() in ("1", "true", "yes", "on")
        if (v := get_alias("OBSERVABILITY_SLOW_REQUEST_MS", "OBS_SLOW_REQUEST_MS")) is not None:
            self.observability.slow_request_ms = float(v)
        if (v := get_alias("OBSERVABILITY_TRACE_RING", "OBS_TRACE_RING")) is not None:
            self.observability.trace_ring = int(v)
        if (v := get_alias("OBSERVABILITY_LATENCY_BUCKETS_MS", "OBS_LATENCY_BUCKETS_MS")) is not None:
            self.observability.latency_buckets_ms = v
        if (v := get_alias("OBSERVABILITY_FLIGHT_RING", "OBS_FLIGHT_RING")) is not None:
            self.observability.flight_ring = int(v)
        if (v := get_alias("OBSERVABILITY_COMPILE_STORM_THRESHOLD", "OBS_COMPILE_STORM_THRESHOLD")) is not None:
            self.observability.compile_storm_threshold = int(v)
        # durability knobs (snapshot + write-ahead log)
        if (v := get("DURABILITY_ENABLED")) is not None:
            self.durability.enabled = v.lower() in ("1", "true", "yes", "on")
        if (v := get("DURABILITY_WAL_PATH")) is not None:
            self.durability.wal_path = v
        if (v := get("DURABILITY_FSYNC")) is not None:
            self.durability.fsync = v.lower()
        if (v := get("DURABILITY_FSYNC_INTERVAL_MS")) is not None:
            self.durability.fsync_interval_ms = float(v)
        if (v := get("DURABILITY_COMPACT_BYTES")) is not None:
            self.durability.compact_bytes = int(v)
        if (v := get("DURABILITY_WAL_SEGMENT_BYTES")) is not None:
            self.durability.wal_segment_bytes = int(v)
        # replication knobs (WAL segment shipping + lease-based promotion)
        if (v := get("REPLICATION_ENABLED")) is not None:
            self.replication.enabled = v.lower() in ("1", "true", "yes", "on")
        if (v := get("REPLICATION_ROLE")) is not None:
            self.replication.role = v.lower()
        if (v := get("REPLICATION_PEER")) is not None:
            self.replication.peer = v
        if (v := get("REPLICATION_MODE")) is not None:
            self.replication.mode = v.lower()
        if (v := get("REPLICATION_LEASE_MS")) is not None:
            self.replication.lease_ms = float(v)
        if (v := get("REPLICATION_RENEW_INTERVAL_MS")) is not None:
            self.replication.renew_interval_ms = float(v)
        if (v := get("REPLICATION_SEGMENT_BYTES")) is not None:
            self.replication.segment_bytes = int(v)
        if (v := get("REPLICATION_SYNC_TIMEOUT_MS")) is not None:
            self.replication.sync_timeout_ms = float(v)
        if (v := get("REPLICATION_AUTO_PROMOTE")) is not None:
            self.replication.auto_promote = v.lower() in ("1", "true", "yes", "on")
        if (v := get("REPLICATION_EPOCH_FILE")) is not None:
            self.replication.epoch_file = v
        if (v := get("REPLICATION_SHARDS")) is not None:
            self.replication.shards = int(v)
        if (v := get("REPLICATION_HANDOVER_ON_TERM")) is not None:
            self.replication.handover_on_term = v.lower() in (
                "1", "true", "yes", "on",
            )
        if (v := get("REPLICATION_HANDOVER_TIMEOUT_MS")) is not None:
            self.replication.handover_timeout_ms = float(v)
        # ops plane knobs (HTTP introspection server)
        if (v := get("OPSPLANE_ENABLED")) is not None:
            self.opsplane.enabled = v.lower() in ("1", "true", "yes", "on")
        if (v := get("OPSPLANE_HOST")) is not None:
            self.opsplane.host = v
        if (v := get("OPSPLANE_PORT")) is not None:
            self.opsplane.port = int(v)
        # SLO knobs (burn-rate engine behind the ops plane's /slo)
        if (v := get("SLO_AVAILABILITY_TARGET")) is not None:
            self.slo.availability_target = float(v)
        if (v := get("SLO_LATENCY_MS")) is not None:
            self.slo.latency_ms = v
        if (v := get("SLO_FAST_BURN_THRESHOLD")) is not None:
            self.slo.fast_burn_threshold = float(v)
        if (v := get("SLO_SLOW_BURN_THRESHOLD")) is not None:
            self.slo.slow_burn_threshold = float(v)
        if (v := get("SLO_TICK_INTERVAL_MS")) is not None:
            self.slo.tick_interval_ms = float(v)
        # audit knobs (proof-log trail behind the bulk audit pipeline)
        if (v := get("AUDIT_ENABLED")) is not None:
            self.audit.enabled = v.lower() in ("1", "true", "yes", "on")
        if (v := get("AUDIT_LOG_PATH")) is not None:
            self.audit.log_path = v
        if (v := get("AUDIT_FSYNC")) is not None:
            self.audit.fsync = v.lower()
        if (v := get("AUDIT_FSYNC_INTERVAL_MS")) is not None:
            self.audit.fsync_interval_ms = float(v)
        if (v := get("AUDIT_SEGMENT_BYTES")) is not None:
            self.audit.segment_bytes = int(v)
        # fleet knobs (partition-map routing)
        if (v := get("FLEET_ENABLED")) is not None:
            self.fleet.enabled = v.lower() in ("1", "true", "yes", "on")
        if (v := get("FLEET_MAP_PATH")) is not None:
            self.fleet.map_path = v
        if (v := get("FLEET_PARTITION")) is not None:
            self.fleet.partition = int(v)
        if (v := get("FLEET_ADVERTISE")) is not None:
            self.fleet.advertise = v
        # controller knobs (self-driving fleet control loop)
        if (v := get("CONTROLLER_ENABLED")) is not None:
            self.controller.enabled = v.lower() in ("1", "true", "yes", "on")
        if (v := get("CONTROLLER_TICK_INTERVAL_MS")) is not None:
            self.controller.tick_interval_ms = float(v)
        if (v := get("CONTROLLER_DRY_RUN")) is not None:
            self.controller.dry_run = v.lower() in ("1", "true", "yes", "on")
        if (v := get("CONTROLLER_DECISION_RING")) is not None:
            self.controller.decision_ring = int(v)
        if (v := get("CONTROLLER_ACT_TICKS")) is not None:
            self.controller.act_ticks = int(v)
        if (v := get("CONTROLLER_CLEAR_TICKS")) is not None:
            self.controller.clear_ticks = int(v)
        if (v := get("CONTROLLER_SPLIT_USER_THRESHOLD")) is not None:
            self.controller.split_user_threshold = int(v)
        if (v := get("CONTROLLER_SPLIT_LOCK_WAIT_MS")) is not None:
            self.controller.split_lock_wait_ms = float(v)
        if (v := get("CONTROLLER_SPLIT_TARGET_ADDRESS")) is not None:
            self.controller.split_target_address = v
        if (v := get("CONTROLLER_SPLIT_COOLDOWN_S")) is not None:
            self.controller.split_cooldown_s = float(v)
        if (v := get("CONTROLLER_LANE_OPEN_AFTER_S")) is not None:
            self.controller.lane_open_after_s = float(v)
        if (v := get("CONTROLLER_LANE_COOLDOWN_S")) is not None:
            self.controller.lane_cooldown_s = float(v)
        if (v := get("CONTROLLER_SLO_RPC")) is not None:
            self.controller.slo_rpc = v
        if (v := get("CONTROLLER_ADMISSION_COOLDOWN_S")) is not None:
            self.controller.admission_cooldown_s = float(v)
        if (v := get("CONTROLLER_ERROR_BACKOFF_S")) is not None:
            self.controller.error_backoff_s = float(v)

    # --- validation (config.rs:238-273) ---

    def validate(self) -> None:
        if self.tls.enabled:
            if not self.tls.cert_path:
                raise ValueError("TLS is enabled but cert_path is empty")
            if not self.tls.key_path:
                raise ValueError("TLS is enabled but key_path is empty")
            if not os.path.exists(self.tls.cert_path):
                raise ValueError(
                    f"TLS certificate file does not exist: {self.tls.cert_path}"
                )
            if not os.path.exists(self.tls.key_path):
                raise ValueError(f"TLS key file does not exist: {self.tls.key_path}")
        # the global bucket has no "0 disables" escape hatch: 0 admits
        # nothing, and negatives used to slip through silently and refill
        # the bucket BACKWARDS (satellite fix) — both are now rejected
        if self.rate_limit.requests_per_minute == 0:
            raise ValueError("Rate limit requests_per_minute cannot be zero")
        if self.rate_limit.requests_per_minute < 0:
            raise ValueError("Rate limit requests_per_minute cannot be negative")
        if self.rate_limit.burst == 0:
            raise ValueError("Rate limit burst cannot be zero")
        if self.rate_limit.burst < 0:
            raise ValueError("Rate limit burst cannot be negative")
        # per-client limits: 0 = unset/disabled, negative = error
        if self.admission.per_client_rpm < 0:
            raise ValueError(
                "admission.per_client_rpm cannot be negative "
                "(0 disables per-client limiting)"
            )
        if self.admission.per_client_burst < 1:
            raise ValueError("admission.per_client_burst must be >= 1")
        if self.admission.max_clients < 1:
            raise ValueError("admission.max_clients must be >= 1")
        if not (0.0 < self.admission.low_watermark <= self.admission.high_watermark <= 1.0):
            raise ValueError(
                "admission watermarks must satisfy "
                "0 < low_watermark <= high_watermark <= 1"
            )
        if self.admission.target_queue_wait_ms < 0:
            raise ValueError("admission.target_queue_wait_ms cannot be negative")
        if self.admission.adjust_interval_ms <= 0:
            raise ValueError("admission.adjust_interval_ms must be positive")
        if self.admission.increase_step <= 0:
            raise ValueError("admission.increase_step must be positive")
        if not (0.0 < self.admission.decrease_factor < 1.0):
            raise ValueError("admission.decrease_factor must be in (0, 1)")
        if not (0.0 <= self.admission.retry_after_min_ms
                <= self.admission.retry_after_max_ms):
            raise ValueError(
                "admission retry_after bounds must satisfy "
                "0 <= retry_after_min_ms <= retry_after_max_ms"
            )
        if self.server.wire not in ("native", "python"):
            raise ValueError(
                "server.wire must be 'native' (C++ request parse with "
                "Python fallback) or 'python' (protobuf runtime only)"
            )
        if not 1 <= self.server.ingest_shards <= 64:
            raise ValueError(
                "server.ingest_shards must be in [1, 64] (1 = the "
                "in-process listener)"
            )
        if min(
            self.server.max_users,
            self.server.max_challenges,
            self.server.max_sessions,
        ) < 1:
            raise ValueError(
                "server.max_users/max_challenges/max_sessions must be >= 1"
            )
        if (
            self.server.ingest_shards > 1
            and self.replication.enabled
            and self.replication.role == "standby"
        ):
            raise ValueError(
                "server.ingest_shards > 1 requires replication.role = "
                "'primary': ingest shards proxy only auth + health, and "
                "a standby must receive ShipSegment on its own listener"
            )
        if self.tpu.backend not in ("cpu", "tpu"):
            raise ValueError(f"Unknown verifier backend: {self.tpu.backend}")
        if self.tpu.pipeline_depth < 1:
            raise ValueError("tpu.pipeline_depth must be >= 1")
        if self.tpu.batch_max < 1:
            raise ValueError("tpu.batch_max must be positive")
        if self.tpu.batch_window_ms < 0:
            raise ValueError("tpu.batch_window_ms cannot be negative")
        if self.tpu.mesh_devices < 0:
            raise ValueError("tpu.mesh_devices cannot be negative")
        if self.tpu.lanes == 0 or self.tpu.lanes < -1:
            raise ValueError(
                "tpu.lanes must be a positive lane count, or -1 for one "
                "lane per local device"
            )
        if self.tpu.mesh_threshold < 0:
            raise ValueError(
                "tpu.mesh_threshold cannot be negative (0 disables the "
                "big-batch mesh path)"
            )
        if self.tpu.mesh_threshold > 0 and self.tpu.lanes == 1:
            raise ValueError(
                "tpu.mesh_threshold needs tpu.lanes != 1 (the mesh lane "
                "shards over the per-device lanes' devices)"
            )
        if self.tpu.recovery_after_s < 0 and self.tpu.recovery_after_s != -1:
            raise ValueError(
                "tpu.recovery_after_s must be >= 0, or -1 to disable self-healing"
            )
        if self.tpu.probe_batch_max < 1:
            raise ValueError("tpu.probe_batch_max must be positive")
        if self.tpu.stream_window < 1:
            raise ValueError("tpu.stream_window must be positive")
        if self.tpu.stream_entry_deadline_ms < 0:
            raise ValueError(
                "tpu.stream_entry_deadline_ms cannot be negative "
                "(0 disables per-entry deadlines)"
            )
        try:
            quanta = self.tpu.parsed_prewarm_quanta()
        except ValueError:
            raise ValueError(
                "tpu.prewarm_quanta must be a comma-separated list of "
                "batch sizes"
            ) from None
        if any(q < 1 for q in quanta):
            raise ValueError("tpu.prewarm_quanta entries must be positive")
        if self.retry.max_attempts < 1:
            raise ValueError("retry.max_attempts must be >= 1")
        if self.retry.initial_backoff_ms < 0 or self.retry.max_backoff_ms < 0:
            raise ValueError("retry backoff bounds cannot be negative")
        if self.retry.multiplier < 1.0:
            raise ValueError("retry.multiplier must be >= 1")
        if self.retry.budget < 0:
            raise ValueError("retry.budget cannot be negative")
        if self.observability.trace_ring < 1:
            raise ValueError("observability.trace_ring must be >= 1")
        if (
            self.observability.slow_request_ms < 0
            and self.observability.slow_request_ms != -1
        ):
            raise ValueError(
                "observability.slow_request_ms must be >= 0, or -1 to disable"
            )
        if self.observability.flight_ring < 1:
            raise ValueError("observability.flight_ring must be >= 1")
        if self.observability.compile_storm_threshold < 1:
            raise ValueError(
                "observability.compile_storm_threshold must be >= 1"
            )
        if self.durability.fsync not in ("always", "interval", "off"):
            raise ValueError(
                "durability.fsync must be one of: always, interval, off"
            )
        if self.durability.fsync_interval_ms <= 0:
            raise ValueError("durability.fsync_interval_ms must be positive")
        if self.durability.compact_bytes < 0:
            raise ValueError("durability.compact_bytes cannot be negative")
        if self.durability.wal_segment_bytes < 0:
            raise ValueError(
                "durability.wal_segment_bytes cannot be negative "
                "(0 = single-file log)"
            )
        if self.durability.enabled and not self.state_file:
            raise ValueError(
                "durability.enabled requires state_file (the snapshot path "
                "the write-ahead log is paired with)"
            )
        if self.replication.role not in ("primary", "standby"):
            raise ValueError(
                "replication.role must be 'primary' or 'standby'"
            )
        if self.replication.mode not in ("async", "sync"):
            raise ValueError("replication.mode must be 'async' or 'sync'")
        if self.replication.renew_interval_ms <= 0:
            raise ValueError("replication.renew_interval_ms must be positive")
        # a lease at or below the renewal cadence guarantees spurious
        # failovers: one delayed renewal and the standby deposes a healthy
        # primary — reject the configuration outright
        if self.replication.lease_ms <= self.replication.renew_interval_ms:
            raise ValueError(
                "replication.lease_ms must be strictly greater than "
                "replication.renew_interval_ms (a lease the renewal "
                "cadence cannot keep alive promotes on every hiccup)"
            )
        if self.replication.segment_bytes < 1:
            raise ValueError("replication.segment_bytes must be positive")
        if self.replication.sync_timeout_ms <= 0:
            raise ValueError("replication.sync_timeout_ms must be positive")
        if self.replication.handover_timeout_ms <= 0:
            raise ValueError(
                "replication.handover_timeout_ms must be positive"
            )
        if not 1 <= self.replication.shards <= 256:
            raise ValueError(
                "replication.shards must be in [1, 256] (the shard tag is "
                "one byte of the challenge id)"
            )
        if self.replication.enabled:
            if not self.durability.enabled:
                raise ValueError(
                    "replication.enabled requires durability.enabled (the "
                    "write-ahead log is what gets shipped)"
                )
            if self.replication.role == "primary" and not self.replication.peer:
                raise ValueError(
                    "replication on the primary requires peer (the "
                    "standby's gRPC address)"
                )
        if not 0 <= self.opsplane.port <= 65535:
            raise ValueError(
                "opsplane.port must be in [0, 65535] (0 = OS-assigned)"
            )
        if self.opsplane.enabled and not self.opsplane.host:
            raise ValueError("opsplane.enabled requires a host to bind")
        if not 0.0 < self.slo.availability_target < 1.0:
            raise ValueError(
                "slo.availability_target must be in (0, 1) — 1.0 leaves "
                "zero error budget and every failure pages"
            )
        if self.slo.fast_burn_threshold <= 0:
            raise ValueError("slo.fast_burn_threshold must be positive")
        if self.slo.slow_burn_threshold <= 0:
            raise ValueError("slo.slow_burn_threshold must be positive")
        if self.slo.tick_interval_ms <= 0:
            raise ValueError("slo.tick_interval_ms must be positive")
        try:
            latency_targets = self.slo.parsed_latency_ms()
        except ValueError:
            raise ValueError(
                "slo.latency_ms must be comma-separated Rpc=ms pairs "
                '(e.g. "VerifyProof=250,Register=100")'
            ) from None
        if any(ms <= 0 for ms in latency_targets.values()):
            raise ValueError("slo.latency_ms targets must be positive")
        if self.audit.fsync not in ("always", "interval", "off"):
            raise ValueError(
                "audit.fsync must be one of: always, interval, off"
            )
        if self.audit.fsync_interval_ms <= 0:
            raise ValueError("audit.fsync_interval_ms must be positive")
        if self.audit.enabled and not self.audit.log_path:
            raise ValueError(
                "audit.enabled requires log_path (where the proof log "
                "is appended)"
            )
        if self.audit.segment_bytes < 0:
            raise ValueError(
                "audit.segment_bytes cannot be negative (0 disables "
                "proof-log rotation)"
            )
        if self.fleet.enabled and not self.fleet.map_path:
            raise ValueError(
                "fleet.enabled requires map_path (the partition-map JSON "
                "every daemon in the fleet shares)"
            )
        if self.fleet.partition < -1:
            raise ValueError(
                "fleet.partition must be a partition index, or -1 to "
                "discover it from the advertise address"
            )
        if self.controller.tick_interval_ms <= 0:
            raise ValueError("controller.tick_interval_ms must be positive")
        if self.controller.decision_ring < 1:
            raise ValueError("controller.decision_ring must be >= 1")
        if self.controller.act_ticks < 1 or self.controller.clear_ticks < 1:
            raise ValueError(
                "controller.act_ticks and controller.clear_ticks must be "
                ">= 1 (the hysteresis windows cannot be empty)"
            )
        if self.controller.split_user_threshold < 0:
            raise ValueError(
                "controller.split_user_threshold cannot be negative "
                "(0 disables the user-count split trigger)"
            )
        if self.controller.split_lock_wait_ms < 0:
            raise ValueError(
                "controller.split_lock_wait_ms cannot be negative "
                "(0 disables the lock-wait split trigger)"
            )
        if min(
            self.controller.split_cooldown_s,
            self.controller.lane_cooldown_s,
            self.controller.admission_cooldown_s,
            self.controller.error_backoff_s,
        ) < 0:
            raise ValueError("controller cooldowns cannot be negative")
        if self.controller.lane_open_after_s <= 0:
            raise ValueError("controller.lane_open_after_s must be positive")
        if not self.controller.slo_rpc:
            raise ValueError(
                "controller.slo_rpc must name the RPC whose burn pages "
                "drive the admission action"
            )
        if (
            self.controller.enabled
            and (
                self.controller.split_user_threshold > 0
                or self.controller.split_lock_wait_ms > 0
            )
            and not self.controller.split_target_address
        ):
            raise ValueError(
                "controller split triggers are armed but "
                "controller.split_target_address is empty (the flipped map "
                "needs an address for the new partition)"
            )
        try:
            buckets = self.observability.parsed_buckets()
        except ValueError:
            raise ValueError(
                "observability.latency_buckets_ms must be a comma-separated "
                "list of numbers"
            ) from None
        if buckets and sorted(buckets) != buckets:
            raise ValueError(
                "observability.latency_buckets_ms must be sorted ascending"
            )


def _load_dotenv() -> None:
    """Minimal ``.env`` loader (dotenvy twin): walks up from cwd, first file
    wins, existing environment variables are never overridden."""
    d = os.getcwd()
    while True:
        path = os.path.join(d, ".env")
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line or line.startswith("#") or "=" not in line:
                        continue
                    key, _, value = line.partition("=")
                    key = key.strip()
                    value = value.strip().strip("\"'")
                    os.environ.setdefault(key, value)
            return
        parent = os.path.dirname(d)
        if parent == d:
            return
        d = parent


class RateLimitExceeded(Exception):
    """Global-bucket rejection.  ``retry_after_s`` is the time until one
    token refills — the service layer sizes its ``cpzk-retry-after-ms``
    pushback from it (every RESOURCE_EXHAUSTED path carries pushback)."""

    def __init__(self, message: str = "Rate limit exceeded",
                 retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class RateLimiter:
    """Token bucket with fractional refill (config.rs:64-118 twin)."""

    def __init__(self, requests_per_minute: int, burst: int):
        self.rate = requests_per_minute
        self.burst = burst
        self._tokens = float(burst)
        self._last_update = time.monotonic()
        self._lock = asyncio.Lock()

    async def check_rate_limit(self) -> None:
        async with self._lock:
            now = time.monotonic()
            elapsed = now - self._last_update
            self._tokens = min(self._tokens + elapsed * (self.rate / 60.0), float(self.burst))
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self._last_update = now
            else:
                per_s = self.rate / 60.0
                raise RateLimitExceeded(
                    "Rate limit exceeded",
                    retry_after_s=(
                        (1.0 - self._tokens) / per_s if per_s > 0 else 1.0
                    ),
                )
