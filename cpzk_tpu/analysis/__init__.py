"""cpzk-lint: AST-based invariant analyzer for this codebase's security
and concurrency discipline.

The reference crate enforces its safety properties structurally —
``subtle::ConstantTimeEq``, ``zeroize``, the borrow checker.  The Python
port documents the same rules (docs/security.md); this package makes
them machine-checked and self-hosted: tier-1 runs the analyzer over the
whole tree and asserts zero findings, so every future PR is gated
without needing CI.

Rule pack (see docs/security.md "Mechanically enforced invariants"):

- **CT-001** — equality on secret-derived bytes/ints must be constant-time
- **CT-002** — no secret-dependent branching in ``core/`` / ``protocol/``
- **LEAK-001** — secret taint never reaches logs/format/exceptions/traces/labels
- **LOCK-001** — ``ServerState`` map mutations + WAL appends under ``self._lock``
- **ASYNC-001** — no blocking calls in serving-plane ``async def`` bodies
- **ASYNC-002** — spawned task handles must be retained
- **GRPC-001** — RESOURCE_EXHAUSTED aborts route through ``_abort_exhausted``
- **JAX-001** — jit purity + real ``static_argnames``/``static_argnums``
- **THREAD-001** — asyncio objects untouched from thread/process context
  except via ``loop.call_soon_threadsafe`` (execution-context inference)
- **FUNNEL-001** — ``ServerState`` registry mutations ride the
  ``_*_insert``/``_*_remove`` funnels (wheel/counter consistency)
- **PROC-001** — spawn ``Process`` targets are module-level with
  picklable, spawn-safe args
- **FRAME-001** — length+CRC framing only via the shared WAL helpers
- **WAIVER-001** / **WAIVER-002** / **PARSE-001** — waivers need reasons
  and must stay live; files must parse

Run: ``python -m cpzk_tpu.analysis cpzk_tpu/`` (``--json`` for the
machine-readable report, ``--audit-waivers`` for every suppression with
its liveness).  Waive a finding inline with
``# cpzk-lint: disable=RULE-ID -- <reason>`` (the reason is mandatory,
and the waiver must keep suppressing a live finding — WAIVER-002).
"""

from __future__ import annotations

from .engine import (
    REGISTRY,
    Finding,
    Module,
    Report,
    Rule,
    all_rule_ids,
    analyze_paths,
    analyze_source,
    parse_module,
    register,
)

__all__ = [
    "REGISTRY",
    "Finding",
    "Module",
    "Report",
    "Rule",
    "all_rule_ids",
    "analyze_paths",
    "analyze_source",
    "parse_module",
    "register",
]
