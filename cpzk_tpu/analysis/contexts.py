"""Execution-context inference: which context(s) can run each function.

The serving plane spans four execution contexts — the asyncio event
loop, the persistent prep/device lane threads (``server/dispatch.py``),
spawn-context ingest processes (``server/ingest.py``), and the
WAL/snapshot worker threads — and the correctness contracts differ per
context: an asyncio ``Future`` may only be settled on its event loop, a
``multiprocessing`` spawn target must be picklable, a blocking call is
fine on a worker thread but fatal on the loop.  This pass gives the rule
pack that vocabulary.  It builds an **intra-module call graph** and
classifies every function by the contexts that can reach it:

- :data:`EVENT_LOOP` — runs on an asyncio event loop.  Seeded by every
  ``async def``, and by callables handed to the loop-callback APIs
  (``call_soon_threadsafe``, ``call_soon``, ``call_later``, ``call_at``)
  — ``call_soon_threadsafe`` is exactly the sanctioned bridge THREAD-001
  exists to enforce, so its callback is event-loop context by
  construction.
- :data:`THREAD` — runs on a worker thread.  Seeded by
  ``threading.Thread(target=...)``, ``asyncio.to_thread(...)``, and
  ``run_in_executor(...)`` targets.
- :data:`PROCESS` — runs in a spawned child process.  Seeded by
  ``multiprocessing`` / spawn-context ``Process(target=...)`` targets.

Contexts then propagate caller -> callee over resolved calls, with two
deliberate exceptions: THREAD/PROCESS never flow **into** an ``async
def`` (calling one from a thread only builds a coroutine object — the
thread would still need ``run_coroutine_threadsafe`` to run it, which is
its own sanctioned bridge), and nothing flows through the spawn/bridge
calls themselves (their callable argument is seeded, not called).

Call resolution is deliberately conservative — the same trade the taint
pass makes (``engine.py`` docstring).  An edge exists only for:

- ``f(...)`` where ``f`` is a nested ``def`` in the lexical scope chain
  or a module-level ``def``;
- ``self.m(...)`` / ``cls.m(...)`` for a method of the enclosing class;
- ``ClassName.m(...)`` for a class defined in the same module.

A generic ``obj.attr(...)`` never resolves: following every ``.append``
or ``.get`` by bare name would smear thread context across unrelated
classes and turn the context-sensitive rules into noise.  The graph is
per-module (the engine analyzes one file at a time); every contract the
context rules enforce today — lane-thread result posting, spawn-target
hygiene — lives inside one module by design, and docs/security.md
documents the module boundary as the inference horizon.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

#: Context tags (values appear in findings and tests).
EVENT_LOOP = "event-loop"
THREAD = "thread"
PROCESS = "process"

#: Spawn APIs whose callable argument runs on a worker thread:
#: name -> index of the callable positional argument (``target=`` kwarg
#: always wins for Thread/Process).
_THREAD_SPAWNERS = {"to_thread": 0, "run_in_executor": 1, "Thread": None}
_PROCESS_SPAWNERS = {"Process": None}
#: Loop-callback APIs: the callable argument runs on the event loop.
_LOOP_CALLBACK_ARG = {
    "call_soon_threadsafe": 0,
    "call_soon": 0,
    "call_later": 1,
    "call_at": 1,
}


def call_name(func: ast.expr) -> str:
    """Last dotted segment of a call target (``a.b.c`` -> ``c``)."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


@dataclass
class FuncInfo:
    """One function (or method) definition and its inferred contexts."""

    node: ast.FunctionDef | ast.AsyncFunctionDef
    qualname: str
    is_async: bool
    parent: "FuncInfo | None" = None      # lexically enclosing function
    cls: str | None = None                # enclosing class name, if a method
    children: dict[str, "FuncInfo"] = field(default_factory=dict)
    contexts: set[str] = field(default_factory=set)
    calls: list["FuncInfo"] = field(default_factory=list)


class ContextInference:
    """Collect functions, seed contexts at spawn sites, propagate."""

    def __init__(self, tree: ast.Module):
        self.tree = tree
        self.functions: list[FuncInfo] = []
        self.by_node: dict[ast.AST, FuncInfo] = {}
        self.module_funcs: dict[str, FuncInfo] = {}
        #: class name -> {method name -> FuncInfo}
        self.methods: dict[str, dict[str, FuncInfo]] = {}

    # -- collection ----------------------------------------------------------

    def _collect(
        self, body: list[ast.stmt], parent: FuncInfo | None,
        cls: str | None, prefix: str,
    ) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{stmt.name}" if prefix else stmt.name
                info = FuncInfo(
                    node=stmt, qualname=qual,
                    is_async=isinstance(stmt, ast.AsyncFunctionDef),
                    parent=parent, cls=cls,
                )
                if info.is_async:
                    info.contexts.add(EVENT_LOOP)
                self.functions.append(info)
                self.by_node[stmt] = info
                if parent is not None:
                    parent.children[stmt.name] = info
                elif cls is not None:
                    self.methods.setdefault(cls, {})[stmt.name] = info
                else:
                    self.module_funcs[stmt.name] = info
                self._collect(stmt.body, info, cls, qual + ".")
            elif isinstance(stmt, ast.ClassDef):
                self._collect(
                    stmt.body, None, stmt.name, f"{prefix}{stmt.name}.",
                )
            else:
                # defs nested in plain compound statements (if/try/with)
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if sub:
                        self._collect(sub, parent, cls, prefix)
                for handler in getattr(stmt, "handlers", []) or []:
                    self._collect(handler.body, parent, cls, prefix)

    # -- resolution ----------------------------------------------------------

    def resolve(
        self, expr: ast.expr, scope: FuncInfo | None
    ) -> FuncInfo | None:
        """The function ``expr`` names, or None.  Conservative on purpose
        — see the module docstring for the resolution table."""
        if isinstance(expr, ast.Name):
            # lexical chain: nested defs of the enclosing functions first
            walk = scope
            while walk is not None:
                if expr.id in walk.children:
                    return walk.children[expr.id]
                walk = walk.parent
            return self.module_funcs.get(expr.id)
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            root = expr.value.id
            if root in ("self", "cls") and scope is not None and scope.cls:
                return self.methods.get(scope.cls, {}).get(expr.attr)
            if root in self.methods:  # ClassName.method
                return self.methods[root].get(expr.attr)
        return None

    # -- seeding -------------------------------------------------------------

    def _spawn_target(self, call: ast.Call, pos: int | None) -> ast.expr | None:
        """The callable argument of a spawn/bridge call: the ``target=``
        keyword for Thread/Process (positional never carries it there),
        else the given positional index."""
        if pos is None:
            for kw in call.keywords:
                if kw.arg == "target":
                    return kw.value
            return None
        if len(call.args) > pos:
            return call.args[pos]
        return None

    def _seed(self) -> None:
        # every Call in the module once, attributed to its enclosing
        # function through a node -> scope map built in one walk
        scope_of: dict[ast.AST, FuncInfo | None] = {}

        def assign_scopes(node: ast.AST, scope: FuncInfo | None) -> None:
            for child in ast.iter_child_nodes(node):
                child_scope = scope
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    child_scope = self.by_node.get(child, scope)
                scope_of[child] = child_scope
                assign_scopes(child, child_scope)

        assign_scopes(self.tree, None)
        for node, scope in scope_of.items():
            if isinstance(node, ast.Call):
                self._seed_call(node, scope)
                self._edge_call(node, scope)

    def _seed_call(self, call: ast.Call, scope: FuncInfo | None) -> None:
        name = call_name(call.func)
        if name in _THREAD_SPAWNERS:
            target = self._spawn_target(call, _THREAD_SPAWNERS[name])
            info = self.resolve(target, scope) if target is not None else None
            if info is not None:
                info.contexts.add(THREAD)
        if name in _PROCESS_SPAWNERS:
            target = self._spawn_target(call, _PROCESS_SPAWNERS[name])
            info = self.resolve(target, scope) if target is not None else None
            if info is not None:
                info.contexts.add(PROCESS)
        if name in _LOOP_CALLBACK_ARG:
            pos = _LOOP_CALLBACK_ARG[name]
            if len(call.args) > pos:
                info = self.resolve(call.args[pos], scope)
                if info is not None:
                    info.contexts.add(EVENT_LOOP)

    def _edge_call(self, call: ast.Call, scope: FuncInfo | None) -> None:
        if scope is None:
            return
        callee = self.resolve(call.func, scope)
        if callee is not None and callee is not scope:
            scope.calls.append(callee)

    # -- propagation ---------------------------------------------------------

    def _propagate(self) -> None:
        """Fixed point: caller contexts flow to sync callees.  THREAD and
        PROCESS never enter an ``async def`` (see module docstring)."""
        changed = True
        while changed:
            changed = False
            for f in self.functions:
                for callee in f.calls:
                    flow = set(f.contexts)
                    if callee.is_async:
                        flow -= {THREAD, PROCESS}
                    if not flow <= callee.contexts:
                        callee.contexts |= flow
                        changed = True

    def run(self) -> dict[ast.AST, "FuncInfo"]:
        try:
            self._collect(self.tree.body, None, None, "")
            self._seed()
            self._propagate()
        except RecursionError:  # pathological nesting: degrade, don't crash
            pass
        return self.by_node
