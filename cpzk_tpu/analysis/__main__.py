"""cpzk-lint CLI.

Usage::

    python -m cpzk_tpu.analysis [paths ...] [--format text|json|sarif]
                                [--json] [--rules IDS]
                                [--list-rules] [--audit-waivers]

Exit codes: 0 — clean; 1 — findings; 2 — usage or I/O error.  The JSON
report schema is pinned by tests/test_static_analysis.py (CI uploads it
as an artifact); ``--format sarif`` emits the same findings as a SARIF
2.1.0 document so CI can annotate PRs (exit codes and the default human
output are unchanged — ``--json`` stays an alias for ``--format json``).
``--audit-waivers`` lists every live waiver with its reason and liveness
(a stale one — whose rule would no longer fire — is also a WAIVER-002
finding on a normal run).
"""

from __future__ import annotations

import argparse
import json
import sys

from .engine import all_rule_ids, analyze_paths


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cpzk-lint",
        description="AST-based invariant analyzer (constant-time, "
        "secret-hygiene, lock, async, abort-path discipline)",
    )
    p.add_argument(
        "paths", nargs="*", default=["cpzk_tpu"],
        help="files or directories to analyze (default: cpzk_tpu)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="machine-readable report (alias for --format json)",
    )
    p.add_argument(
        "--format", dest="fmt", choices=("text", "json", "sarif"),
        default=None,
        help="output format: text (default), json (the schema-v2 report), "
        "or sarif (SARIF 2.1.0 for CI annotation)",
    )
    p.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule inventory and exit",
    )
    p.add_argument(
        "--audit-waivers", action="store_true",
        help="list every live waiver (path:line, rules, reason, liveness) "
        "instead of findings",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        from .engine import REGISTRY, _load_rules

        _load_rules()
        for rule_id in all_rule_ids():
            print(f"{rule_id}: {REGISTRY[rule_id].summary}")
        return 0
    rules = None
    if args.rules is not None:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in all_rule_ids()]
        if unknown:
            print(f"unknown rule ids: {', '.join(unknown)}", file=sys.stderr)
            return 2
    try:
        report = analyze_paths(args.paths, rules=rules)
    except OSError as e:
        print(f"cpzk-lint: {e}", file=sys.stderr)
        return 2
    if args.audit_waivers:
        for w in report.waivers:
            print(w.render())
        stale = sum(1 for w in report.waivers if w.stale)
        print(
            f"cpzk-lint: {len(report.waivers)} waivers "
            f"({stale} stale)"
        )
        return 1 if stale else 0
    fmt = args.fmt or ("json" if args.json else "text")
    if fmt == "json":
        json.dump(report.to_dict(), sys.stdout, indent=2, sort_keys=True)
        print()
    elif fmt == "sarif":
        json.dump(report.to_sarif(), sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        for f in report.findings:
            print(f.render())
        print(
            f"cpzk-lint: {report.files} files, "
            f"{len(report.findings)} findings, "
            f"{len(report.waived)} waived"
        )
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
