"""Await-point dataflow for the async-atomicity rules (cpzk-lint v3).

The v2 execution-context inference (``contexts.py``) answers *where* a
function runs (event loop, worker thread, spawned process).  This pass
answers *when* its effects happen relative to the event loop's
suspension points: for every function it records an ordered stream of
the events the atomicity rules care about —

- ``guard``   — an ownership/admission read whose verdict licenses later
  work: ``owns()`` / ``_check_owner`` / ``_wrong_partition*`` /
  admission ``_admit``-style verdicts, an epoch comparison, or a
  write-time fence call (``self._fence`` / ``owner_fence`` or a local
  alias bound from ``.owner_fence`` — those additionally carry
  ``is_fence``);
- ``await``   — a suspension point: any ``await`` expression, including
  ``async with`` / ``async for`` protocol entries.  Every other handler
  on the loop can run here, and in particular a live split's
  export→copy→map-flip can land here (the PR 16 bug window);
- ``mutate``  — a user-keyed state mutation: one of ``ServerState``'s
  six insert/remove funnels (``is_funnel``) or a public mutator
  (``register_user`` / ``create_challenge`` / ``create_session[s]`` /
  ``revoke_session``);
- ``journal`` — a durability event: ``_journal_append`` /
  ``_journal_sync`` or an ``append*``/``sync`` call on a
  journal/WAL-named receiver;
- ``ack``     — a path out of the function that a caller observes as
  success: an explicit ``return``, a ``Future.set_result``, or the
  synthesized fall-off-the-end event (``name == "end"``).

Each event also carries the region facts the rules need: ``lock`` (the
id of the innermost enclosing ``with``/``async with`` acquiring a
``*lock`` attribute — two events share a lock section iff their ``lock``
values match), and ``wp`` (lexically inside a ``try`` whose handlers
catch ``WrongPartition`` — call-site evidence that the mutation's
write-time fence outcome is handled).

The walk is a linearization: statements and expressions are visited in
source order and branch structure is flattened, the same approximation
every other cpzk-lint rule makes.  An ``await`` wrapping a call is
ordered against that call's own event by when its verdict/effect
happens: ``await guard()`` emits ``await`` then ``guard`` (the verdict
is only fresh as of resumption), while ``await mutator()`` emits
``mutate`` then ``await`` (the callee is entered at the call; the
suspension matters only to *later* statements).

The horizon is the module boundary, like the context inference: nested
``def``s get their own flow and are not inlined.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

#: Ownership/admission guard reads (verdict licenses later work).
GUARD_CALLS = frozenset({
    "owns", "_check_owner", "_wrong_partition", "_wrong_partition_counted",
    "_admit", "admit", "check_admission",
})

#: Write-time fence reads — guards that additionally satisfy FENCE-001's
#: in-lock re-check and AWAIT-001's post-await re-check.
FENCE_CALLS = frozenset({"_fence", "owner_fence"})

#: ServerState's six mutation funnels (the FUNNEL-001 surface).
FUNNEL_CALLS = frozenset({
    "_user_insert", "_user_remove",
    "_session_insert", "_session_remove",
    "_challenge_insert", "_challenge_remove",
})

#: Public user-keyed mutators (ack-bearing; fence re-checked inside, so a
#: cross-module caller must handle ``WrongPartition`` at the call site).
MUTATOR_CALLS = frozenset({
    "register_user", "create_challenge", "create_session",
    "create_sessions", "revoke_session",
})

#: Durability events: the journal funnel and its sync barrier.
JOURNAL_CALLS = frozenset({"_journal_append", "_journal_sync"})

#: Receiver-name fragments that mark an ``append*``/``sync`` call as a
#: WAL/journal write (``self.journal.append``, ``wal.append_frames``).
JOURNAL_RECEIVERS = ("journal", "wal")


@dataclass
class FlowEvent:
    """One ordered event in a function's await-point dataflow."""

    kind: str               # guard | await | mutate | journal | ack
    name: str               # call/attr name, "return", "end", "epoch-compare"
    node: ast.AST
    order: int
    lock: int | None = None  # id of the innermost enclosing lock-with
    wp: bool = False         # inside a try that catches WrongPartition
    is_fence: bool = False   # guard that is a write-time fence re-check
    is_funnel: bool = False  # mutate through one of the six funnels


@dataclass
class FuncFlow:
    """The ordered event stream of one function definition."""

    node: ast.AST
    name: str
    qualname: str
    cls: str | None          # enclosing class name, if any
    is_async: bool
    events: list[FlowEvent] = field(default_factory=list)

    def of_kind(self, kind: str) -> list[FlowEvent]:
        return [e for e in self.events if e.kind == kind]

    @property
    def has_fence(self) -> bool:
        return any(e.is_fence for e in self.events)


def _call_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _receiver_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Attribute):
        v = f.value
        if isinstance(v, ast.Name):
            return v.id
        if isinstance(v, ast.Attribute):
            return v.attr
    return None


def _is_lock_acquire(expr: ast.expr) -> bool:
    """``with``/``async with`` item that takes a lock: any attribute (or
    bare name) that is ``lock`` or ends in ``_lock``, optionally called
    (``lock.acquire()`` style context managers are out of scope)."""
    if isinstance(expr, ast.Attribute):
        return expr.attr == "lock" or expr.attr.endswith("_lock")
    if isinstance(expr, ast.Name):
        return expr.id == "lock" or expr.id.endswith("_lock")
    return False


def _catches_wrong_partition(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return False
    for node in ast.walk(t):
        if isinstance(node, ast.Name) and node.id == "WrongPartition":
            return True
        if isinstance(node, ast.Attribute) and node.attr == "WrongPartition":
            return True
    return False


def _epoch_compare(node: ast.Compare) -> bool:
    """A comparison reading an epoch — the lease-fencing guard shape."""
    for side in [node.left, *node.comparators]:
        if isinstance(side, ast.Attribute) and (
            side.attr == "epoch" or side.attr.endswith("_epoch")
        ):
            return True
        if isinstance(side, ast.Name) and (
            side.id == "epoch" or side.id.endswith("_epoch")
        ):
            return True
    return False


class _FuncWalker:
    """Builds one function's event stream (linearized, region-tracked)."""

    def __init__(self, flow: FuncFlow):
        self.flow = flow
        self._order = 0
        self._lock: list[int] = []       # stack of with-node ids
        self._wp_depth = 0
        self._fence_aliases: set[str] = set()

    # -- emission ----------------------------------------------------------

    def _emit(self, kind: str, name: str, node: ast.AST, **flags) -> None:
        self._order += 1
        self.flow.events.append(FlowEvent(
            kind=kind, name=name, node=node, order=self._order,
            lock=self._lock[-1] if self._lock else None,
            wp=self._wp_depth > 0,
            **flags,
        ))

    def _classify_call(self, call: ast.Call) -> None:
        name = _call_name(call)
        if name is None:
            return
        if name in FENCE_CALLS or name in self._fence_aliases:
            self._emit("guard", name, call, is_fence=True)
        elif name in GUARD_CALLS:
            self._emit("guard", name, call)
        elif name in FUNNEL_CALLS:
            self._emit("mutate", name, call, is_funnel=True)
        elif name in MUTATOR_CALLS:
            self._emit("mutate", name, call)
        elif name in JOURNAL_CALLS:
            self._emit("journal", name, call)
        elif name in ("append", "append_frames", "sync") and any(
            frag in (_receiver_name(call) or "").lower()
            for frag in JOURNAL_RECEIVERS
        ):
            self._emit("journal", name, call)
        elif name in ("set_result", "set_exception"):
            self._emit("ack", name, call)

    # -- expressions -------------------------------------------------------

    def expr(self, node: ast.expr | None) -> None:
        if node is None:
            return
        if isinstance(node, ast.Await):
            value = node.value
            if isinstance(value, ast.Call):
                name = _call_name(value)
                for arg in value.args:
                    self.expr(arg)
                for kw in value.keywords:
                    self.expr(kw.value)
                if name in FUNNEL_CALLS or name in MUTATOR_CALLS:
                    # the callee is entered at the call; the suspension
                    # only matters to later statements
                    self._classify_call(value)
                    self._emit("await", name or "await", node)
                else:
                    # a verdict is only fresh as of resumption
                    self._emit("await", name or "await", node)
                    self._classify_call(value)
                return
            self.expr(value)
            self._emit("await", "await", node)
            return
        if isinstance(node, ast.Call):
            self.expr(node.func if not isinstance(
                node.func, (ast.Name, ast.Attribute)) else None)
            for arg in node.args:
                self.expr(arg)
            for kw in node.keywords:
                self.expr(kw.value)
            self._classify_call(node)
            return
        if isinstance(node, ast.Compare):
            self.expr(node.left)
            for c in node.comparators:
                self.expr(c)
            if _epoch_compare(node):
                self._emit("guard", "epoch-compare", node)
            return
        if isinstance(node, (ast.Lambda,)):
            return  # separate execution, not part of this flow
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.expr(child)

    # -- statements --------------------------------------------------------

    def _note_fence_alias(self, stmt: ast.stmt) -> None:
        """``fence = self.owner_fence`` binds a local fence alias whose
        later call is a fence event (the create_sessions shape)."""
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            return
        t, v = stmt.targets[0], stmt.value
        if (
            isinstance(t, ast.Name)
            and isinstance(v, ast.Attribute)
            and v.attr == "owner_fence"
        ):
            self._fence_aliases.add(t.id)

    def stmts(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self.stmt(stmt)

    def stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs get their own flow
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            took_lock = False
            for item in stmt.items:
                self.expr(item.context_expr)
                if _is_lock_acquire(item.context_expr):
                    took_lock = True
            if isinstance(stmt, ast.AsyncWith):
                self._emit("await", "async-with", stmt)
            if took_lock:
                self._lock.append(id(stmt))
            self.stmts(stmt.body)
            if took_lock:
                self._lock.pop()
            return
        if isinstance(stmt, ast.Try):
            wp = any(_catches_wrong_partition(h) for h in stmt.handlers)
            if wp:
                self._wp_depth += 1
            self.stmts(stmt.body)
            self.stmts(stmt.orelse)
            if wp:
                self._wp_depth -= 1
            for h in stmt.handlers:
                self.stmts(h.body)
            self.stmts(stmt.finalbody)
            return
        if isinstance(stmt, ast.Return):
            self.expr(stmt.value)
            self._emit("ack", "return", stmt)
            return
        if isinstance(stmt, (ast.AsyncFor,)):
            self.expr(stmt.iter)
            self._emit("await", "async-for", stmt)
            self.stmts(stmt.body)
            self.stmts(stmt.orelse)
            return
        self._note_fence_alias(stmt)
        # expressions attached directly to this statement, in eval order
        for fname in ("test", "iter", "value", "exc"):
            sub = getattr(stmt, fname, None)
            if isinstance(sub, ast.expr):
                self.expr(sub)
        # compound bodies, linearized
        for fname in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, fname, None)
            if isinstance(sub, list):
                self.stmts(sub)


class FlowPass:
    """Builds :class:`FuncFlow` for every function definition in a tree."""

    def __init__(self, tree: ast.Module):
        self.tree = tree

    def run(self) -> dict[ast.AST, FuncFlow]:
        out: dict[ast.AST, FuncFlow] = {}
        self._walk(self.tree.body, cls=None, prefix="", out=out)
        return out

    def _walk(
        self, body: list[ast.stmt], cls: str | None, prefix: str,
        out: dict[ast.AST, FuncFlow],
    ) -> None:
        for stmt in body:
            if isinstance(stmt, ast.ClassDef):
                self._walk(
                    stmt.body, cls=stmt.name,
                    prefix=f"{prefix}{stmt.name}.", out=out,
                )
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                flow = FuncFlow(
                    node=stmt, name=stmt.name,
                    qualname=f"{prefix}{stmt.name}", cls=cls,
                    is_async=isinstance(stmt, ast.AsyncFunctionDef),
                )
                walker = _FuncWalker(flow)
                walker.stmts(stmt.body)
                if not isinstance(stmt.body[-1], (ast.Return, ast.Raise)):
                    walker._emit("ack", "end", stmt)
                out[stmt] = flow
                # nested defs (helpers, wrappers) get their own flows
                self._walk(
                    stmt.body, cls=cls,
                    prefix=f"{prefix}{stmt.name}.", out=out,
                )
        return
