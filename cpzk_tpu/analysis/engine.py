"""cpzk-lint core: module loading, inline waivers, and secret-taint dataflow.

The framework the rule pack (:mod:`cpzk_tpu.analysis.rules`) plugs into.
Three layers:

- **Module loading** — walk the given paths for ``.py`` files (skipping
  ``_gen`` and caches), parse each into a :class:`Module` carrying the
  AST, source lines, the inline waivers, and the plane (the first package
  directory under ``cpzk_tpu``, which scopes plane-specific rules like
  CT-002 and ASYNC-001).  A file ``ast.parse`` rejects becomes a single
  ``PARSE-001`` finding, never a crash — the fuzz harness
  (``fuzz/fuzz_lint.py``) holds "never raise on any input" as an
  invariant.

- **Waivers** — ``# cpzk-lint: disable=RULE-ID[,RULE-ID] -- <reason>``.
  A waiver on a statement line covers findings on that line; on a
  comment-only line it covers the next code line; on a ``def`` / ``class``
  line it covers the whole body (how a documented single-threaded
  exception like ``ServerState.replay_journal_record`` waives LOCK-001
  once instead of per-statement).  The reason is **mandatory**: a waiver
  without one is itself a ``WAIVER-001`` finding, so suppressions always
  carry their justification in the diff.

- **Secret taint** — a forward, per-function dataflow pass seeded from
  the protocol's named secret types (``Witness``, ``Nonce``,
  ``Response``), KDF outputs (``password_to_scalar`` /
  ``hash_secret_raw``), and ``password*``/``secret*`` parameters.  Three
  kinds are tracked: ``OBJ`` (a secret wrapper object), ``SCALAR`` (a
  :class:`~cpzk_tpu.core.ristretto.Scalar` holding secret material —
  its ``__eq__`` is constant-time, so comparing two is fine), and
  ``RAW`` (bytes/int/str derived from a secret — the kind CT-001 and
  LEAK-001 fire on).  Taint propagates through arithmetic, subscripts,
  f-strings, known scalar-ring helpers, and generic calls; a small
  sanitizer set (``hmac.compare_digest``, ``len`` …) declassifies.

- **Execution contexts** — an interprocedural (per-module) pass
  (:mod:`.contexts`) builds a call graph, seeds contexts at spawn sites
  (``threading.Thread(target=)``, ``to_thread``, ``run_in_executor``,
  ``multiprocessing`` spawn targets, loop-callback registrations), and
  propagates them caller -> callee.  The context-sensitive rules
  (THREAD-001, PROC-001) read the result through
  :meth:`Module.func_info`; ASYNC-001 uses it to follow blocking calls
  into nested helpers that provably run on the event loop.

The taint analysis is intentionally intra-procedural and heuristic: it
will not follow taint across call boundaries (the context pass is the
one interprocedural layer, and it stops at the module boundary).  That
is the right trade for a lint gate — rules fire on the patterns
reviewers actually miss (a ``==`` on secret bytes, a secret in an
f-string log, a map mutation outside the state lock, a Future settled
from a lane thread) with near-zero false positives on this codebase,
enforced by the self-hosted zero-findings test in
``tests/test_static_analysis.py``.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

from .contexts import ContextInference, FuncInfo
from .flows import FlowPass, FuncFlow  # noqa: F401 (re-export for rules)

# -- findings -----------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


# -- waivers ------------------------------------------------------------------

WAIVER_RE = re.compile(
    r"#\s*cpzk-lint:\s*disable=([A-Za-z0-9_\-, ]+?)\s*(?:--\s*(\S.*))?$"
)


@dataclass
class Waiver:
    """One inline ``# cpzk-lint: disable=...`` comment."""

    line: int                      # physical line of the comment
    rules: tuple[str, ...]
    reason: str | None
    span: tuple[int, int] = (0, 0)  # inclusive line range it covers

    def covers(self, rule: str, line: int) -> bool:
        return rule in self.rules and self.span[0] <= line <= self.span[1]


def _comment_lines(source: str) -> dict[int, str] | None:
    """Line -> text for every REAL comment token, via ``tokenize`` — a
    waiver spelled inside a string literal or docstring (the docs quote
    the syntax verbatim) must not register as a live waiver, which the
    historical line-regex scan could not distinguish.  ``None`` when the
    source does not tokenize (the regex fallback handles it)."""
    import io
    import tokenize

    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError, ValueError):
        return None
    return out


def _parse_waivers(source: str, tree: ast.AST) -> list[Waiver]:
    """Extract waivers and resolve the line span each one covers."""
    lines = source.splitlines()
    comments = _comment_lines(source)
    # def/class lines -> (start, end) body span, for whole-scope waivers
    scope_spans: dict[int, tuple[int, int]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            scope_spans[node.lineno] = (node.lineno, node.end_lineno or node.lineno)
            # decorators shift node.lineno to the `def`; map those lines too
            for dec in node.decorator_list:
                scope_spans.setdefault(
                    dec.lineno, (dec.lineno, node.end_lineno or node.lineno)
                )
    out: list[Waiver] = []
    for i, text in enumerate(lines, start=1):
        if comments is not None:
            m = WAIVER_RE.search(comments.get(i, ""))
        else:  # untokenizable source: the historical whole-line scan
            m = WAIVER_RE.search(text)
        if m is None:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = m.group(2).strip() if m.group(2) else None
        target = i
        if text.lstrip().startswith("#"):
            # comment-only line: the waiver targets the next code line
            j = i + 1
            while j <= len(lines) and (
                not lines[j - 1].strip() or lines[j - 1].lstrip().startswith("#")
            ):
                j += 1
            target = j
        span = scope_spans.get(target, (target, target))
        out.append(Waiver(line=i, rules=rules, reason=reason, span=span))
    return out


# -- secret taint -------------------------------------------------------------

#: Taint kinds, ordered by "rawness" — combining taints takes the max.
OBJ = "obj"        # a secret wrapper instance (Witness / Nonce / Response)
SCALAR = "scalar"  # a Scalar holding secret material (ct __eq__ is safe)
RAW = "raw"        # bytes / int / str derived from a secret

_KIND_ORDER = {OBJ: 0, SCALAR: 1, RAW: 2}

SECRET_TYPES = frozenset({"Witness", "Nonce", "Response"})
#: Attribute names that conventionally hold a secret wrapper (self.witness).
SECRET_ATTRS = frozenset({"witness", "nonce"})
#: Wrapper internals: Nonce._k, Witness._x, Response._s / .s
SECRET_FIELDS = frozenset({"s", "_s", "_k", "_x"})
SECRET_PARAM_RE = re.compile(r"^(password|passwd|secret)")

#: KDF outputs: scalar-typed vs raw-byte results.
KDF_SCALAR_FUNCS = frozenset({"password_to_scalar"})
KDF_RAW_FUNCS = frozenset({"hash_secret_raw", "_argon2id"})

#: Scalar-ring helpers: Ristretto255.* return Scalar, sc_* return raw ints.
SCALAR_OPS_SCALAR = frozenset({
    "scalar_add", "scalar_sub", "scalar_mul_scalar", "scalar_negate",
    "scalar_invert",
})
SCALAR_OPS_RAW = frozenset({
    "sc_add", "sc_sub", "sc_mul", "sc_neg", "sc_invert",
    "sc_from_bytes_canonical", "sc_from_bytes_mod_order_wide",
})
TO_RAW_FUNCS = frozenset({
    "sc_to_bytes", "scalar_to_bytes", "bytes", "bytearray", "int", "str",
    "repr", "format",
})
TO_RAW_METHODS = frozenset({"to_bytes", "hex", "encode", "digest", "hexdigest"})
#: Calls whose result is never secret even with tainted arguments.
SANITIZERS = frozenset({
    "compare_digest", "len", "isinstance", "type", "id", "range", "bool",
})


def _max_kind(*kinds: str | None) -> str | None:
    best: str | None = None
    for k in kinds:
        if k is not None and (best is None or _KIND_ORDER[k] > _KIND_ORDER[best]):
            best = k
    return best


def _call_name(func: ast.expr) -> str:
    """Last dotted segment of a call target (``a.b.c(...)`` -> ``c``)."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def dotted_parts(node: ast.expr) -> list[str]:
    """``a.b.c`` -> ["a", "b", "c"]; [] when the chain has a non-name root."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


class TaintPass:
    """Single forward pass annotating every expression with its taint kind.

    Results land in ``self.kinds`` keyed by AST node identity; rules read
    them through :meth:`Module.kind`.  Branches are merged optimistically
    (both arms update one shared environment) — sound enough for lint.
    """

    def __init__(self) -> None:
        self.kinds: dict[ast.AST, str] = {}

    def run(self, tree: ast.AST) -> dict[ast.AST, str]:
        self._exec_body(getattr(tree, "body", []), {})
        return self.kinds

    # -- statements ----------------------------------------------------------

    def _exec_body(self, body: list[ast.stmt], env: dict[str, str]) -> None:
        for stmt in body:
            self._exec(stmt, env)

    def _seed_params(
        self, node: ast.FunctionDef | ast.AsyncFunctionDef, env: dict[str, str]
    ) -> None:
        args = node.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            if SECRET_PARAM_RE.match(a.arg):
                env[a.arg] = RAW
            elif a.annotation is not None:
                ann = dotted_parts(a.annotation)
                if ann and ann[-1] in SECRET_TYPES:
                    env[a.arg] = OBJ

    def _exec(self, stmt: ast.stmt, env: dict[str, str]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = dict(env)  # nested defs inherit the enclosing taint
            self._seed_params(stmt, inner)
            self._exec_body(stmt.body, inner)
        elif isinstance(stmt, ast.ClassDef):
            self._exec_body(stmt.body, {})
        elif isinstance(stmt, ast.Assign):
            kind = self._eval(stmt.value, env)
            for target in stmt.targets:
                self._bind(target, kind, env)
        elif isinstance(stmt, ast.AnnAssign):
            kind = self._eval(stmt.value, env) if stmt.value is not None else None
            ann = dotted_parts(stmt.annotation)
            if ann and ann[-1] in SECRET_TYPES:
                kind = _max_kind(kind, OBJ)
            self._bind(stmt.target, kind, env)
        elif isinstance(stmt, ast.AugAssign):
            kind = _max_kind(
                self._eval(stmt.value, env), self._eval(stmt.target, env)
            )
            self._bind(stmt.target, kind, env)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            kind = self._eval(stmt.iter, env)
            self._bind(stmt.target, kind, env)
            self._exec_body(stmt.body, env)
            self._exec_body(stmt.orelse, env)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                kind = self._eval(item.context_expr, env)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, kind, env)
            self._exec_body(stmt.body, env)
        elif isinstance(stmt, ast.If):
            self._eval(stmt.test, env)
            self._exec_body(stmt.body, env)
            self._exec_body(stmt.orelse, env)
        elif isinstance(stmt, ast.While):
            self._eval(stmt.test, env)
            self._exec_body(stmt.body, env)
            self._exec_body(stmt.orelse, env)
        elif isinstance(stmt, ast.Try):
            self._exec_body(stmt.body, env)
            for handler in stmt.handlers:
                self._exec_body(handler.body, env)
            self._exec_body(stmt.orelse, env)
            self._exec_body(stmt.finalbody, env)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._eval(stmt.value, env)
        elif isinstance(stmt, ast.Expr):
            self._eval(stmt.value, env)
        elif isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self._eval(stmt.exc, env)
        elif isinstance(stmt, ast.Assert):
            self._eval(stmt.test, env)
            if stmt.msg is not None:
                self._eval(stmt.msg, env)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._eval(t, env)
        # Import / Global / Pass / Break / Continue: no taint flow

    def _bind(self, target: ast.expr, kind: str | None, env: dict[str, str]) -> None:
        if isinstance(target, ast.Name):
            if kind is None:
                env.pop(target.id, None)  # rebinding declassifies
            else:
                env[target.id] = kind
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, kind, env)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, kind, env)
        # attribute / subscript stores: reads go through SECRET_ATTRS

    # -- expressions ---------------------------------------------------------

    def _eval(self, node: ast.expr | None, env: dict[str, str]) -> str | None:
        if node is None:
            return None
        kind = self._eval_inner(node, env)
        if kind is not None:
            self.kinds[node] = kind
        return kind

    def _eval_inner(self, node: ast.expr, env: dict[str, str]) -> str | None:
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self._eval(node.value, env)
            if node.attr in SECRET_ATTRS:
                return OBJ
            if base == OBJ:
                # OBJ taint flows ONLY through the secret accessors: a
                # wrapper's other attributes (prover.statement, methods)
                # are public by design
                return SCALAR if node.attr in SECRET_FIELDS else None
            if base == SCALAR:
                return RAW if node.attr == "value" else None
            return base  # RAW: fields/slices of raw secrets stay secret
        if isinstance(node, ast.Call):
            return self._eval_call(node, env)
        if isinstance(node, ast.BinOp):
            left = self._eval(node.left, env)
            right = self._eval(node.right, env)
            return _max_kind(left, right)
        if isinstance(node, ast.UnaryOp):
            return self._eval(node.operand, env)
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                self._eval(v, env)
            return None  # a bool result is not itself secret
        if isinstance(node, ast.Compare):
            self._eval(node.left, env)
            for c in node.comparators:
                self._eval(c, env)
            return None
        if isinstance(node, ast.Subscript):
            kind = self._eval(node.value, env)
            self._eval(node.slice, env)
            return kind
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return _max_kind(*(self._eval(e, env) for e in node.elts))
        if isinstance(node, ast.Dict):
            kinds = [self._eval(v, env) for v in node.values if v is not None]
            for k in node.keys:
                if k is not None:
                    kinds.append(self._eval(k, env))
            return _max_kind(*kinds)
        if isinstance(node, ast.JoinedStr):
            tainted = None
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    k = self._eval(v.value, env)
                    if k is not None:
                        self.kinds[v] = k
                    tainted = _max_kind(tainted, k)
            return RAW if tainted is not None else None
        if isinstance(node, ast.IfExp):
            self._eval(node.test, env)
            return _max_kind(self._eval(node.body, env), self._eval(node.orelse, env))
        if isinstance(node, ast.Await):
            return self._eval(node.value, env)
        if isinstance(node, ast.Starred):
            return self._eval(node.value, env)
        if isinstance(node, ast.NamedExpr):
            kind = self._eval(node.value, env)
            self._bind(node.target, kind, env)
            return kind
        if isinstance(node, ast.Lambda):
            self._eval(node.body, dict(env))
            return None
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            inner = dict(env)
            for gen in node.generators:
                kind = self._eval(gen.iter, inner)
                self._bind(gen.target, kind, inner)
                for cond in gen.ifs:
                    self._eval(cond, inner)
            return self._eval(node.elt, inner)
        if isinstance(node, ast.DictComp):
            inner = dict(env)
            for gen in node.generators:
                kind = self._eval(gen.iter, inner)
                self._bind(gen.target, kind, inner)
                for cond in gen.ifs:
                    self._eval(cond, inner)
            return _max_kind(self._eval(node.key, inner), self._eval(node.value, inner))
        if isinstance(node, ast.FormattedValue):
            return self._eval(node.value, env)
        return None

    def _eval_call(self, node: ast.Call, env: dict[str, str]) -> str | None:
        name = _call_name(node.func)
        recv_kind = None
        if isinstance(node.func, ast.Attribute):
            recv_kind = self._eval(node.func.value, env)
        arg_kinds = [self._eval(a, env) for a in node.args]
        arg_kinds += [self._eval(kw.value, env) for kw in node.keywords]
        any_arg = _max_kind(*arg_kinds)

        if name in SANITIZERS:
            return None
        if name in SECRET_TYPES:
            return OBJ
        if name in KDF_SCALAR_FUNCS:
            return SCALAR
        if name in KDF_RAW_FUNCS:
            return RAW
        if recv_kind == OBJ and name in ("secret", "k"):
            return SCALAR
        if name in SCALAR_OPS_SCALAR and any_arg is not None:
            return SCALAR
        if name in SCALAR_OPS_RAW and any_arg is not None:
            return RAW
        if name == "Scalar" and any_arg is not None:
            return SCALAR
        if name in TO_RAW_FUNCS and any_arg is not None:
            return RAW
        if name in TO_RAW_METHODS and _max_kind(recv_kind, any_arg) is not None:
            return RAW
        # Generic propagation: a call over SCALAR/RAW inputs yields a RAW
        # secret (hash of a secret, arithmetic on one...).  OBJ inputs do
        # NOT propagate: passing a Witness to a constructor (Prover(...))
        # must not taint the receiver's public surface — only the named
        # accessors above extract the secret.
        kinds = [recv_kind, *arg_kinds]
        if any(k in (SCALAR, RAW) for k in kinds):
            return RAW
        return None


# -- modules ------------------------------------------------------------------


@dataclass
class Module:
    """One parsed source file plus its lint-relevant metadata."""

    path: str
    source: str
    tree: ast.Module
    waivers: list[Waiver] = field(default_factory=list)
    taint: dict[ast.AST, str] = field(default_factory=dict)
    #: function node -> FuncInfo (execution contexts + call edges)
    contexts: dict[ast.AST, FuncInfo] = field(default_factory=dict)
    #: the inference pass itself (rules reuse its resolver/scope maps)
    inference: ContextInference | None = None
    #: function node -> FuncFlow (await-point event streams, cpzk-lint v3)
    flows: dict[ast.AST, "FuncFlow"] = field(default_factory=dict)

    @property
    def plane(self) -> str:
        """First package directory under ``cpzk_tpu`` ("core", "server",
        ...), or "" for files outside the package."""
        parts = self.path.replace(os.sep, "/").split("/")
        if "cpzk_tpu" in parts:
            i = parts.index("cpzk_tpu")
            if i + 2 <= len(parts) - 1:
                return parts[i + 1]
        return ""

    @property
    def filename(self) -> str:
        return os.path.basename(self.path)

    def kind(self, node: ast.AST) -> str | None:
        """Taint kind of an expression node (None = untainted)."""
        return self.taint.get(node)

    def any_tainted(self, node: ast.AST) -> str | None:
        """Max taint kind across ``node`` and its descendants."""
        best = self.taint.get(node)
        for sub in ast.walk(node):
            best = _max_kind(best, self.taint.get(sub))
        return best

    def func_info(self, node: ast.AST) -> FuncInfo | None:
        """Context info for a function-def node (None for non-functions)."""
        return self.contexts.get(node)

    def func_contexts(self, node: ast.AST) -> frozenset[str]:
        """Inferred execution contexts of a function-def node."""
        info = self.contexts.get(node)
        return frozenset(info.contexts) if info is not None else frozenset()


def parse_module(source: str, path: str) -> Module | Finding:
    """Parse one source file; a syntax error becomes a PARSE-001 finding."""
    try:
        tree = ast.parse(source)
    except (SyntaxError, ValueError) as e:
        line = getattr(e, "lineno", 1) or 1
        return Finding("PARSE-001", path, line, 0, f"file does not parse: {e.msg if hasattr(e, 'msg') else e}")
    mod = Module(path=path, source=source, tree=tree)
    mod.waivers = _parse_waivers(source, tree)
    mod.taint = TaintPass().run(tree)
    mod.inference = ContextInference(tree)
    mod.contexts = mod.inference.run()
    mod.flows = FlowPass(tree).run()
    return mod


def collect_files(paths: list[str]) -> list[str]:
    """All ``.py`` files under ``paths`` (skipping generated/cache dirs)."""
    skip_dirs = {"_gen", "__pycache__", ".git"}
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        if not os.path.isdir(p):
            # a typo'd path exiting 0 would be a silently green gate
            raise FileNotFoundError(f"no such file or directory: {p}")
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in skip_dirs)
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(root, f))
    return out


# -- rules + runner -----------------------------------------------------------


class Rule:
    """One lint rule.  Subclasses set ``id``/``summary``/``rationale`` and
    implement :meth:`check`."""

    id: str = ""
    summary: str = ""
    rationale: str = ""

    def check(self, module: Module) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, module: Module, node: ast.AST, message: str) -> Finding:
        return Finding(
            self.id, module.path,
            getattr(node, "lineno", 1), getattr(node, "col_offset", 0),
            message,
        )


REGISTRY: dict[str, Rule] = {}


def register(rule_cls: type[Rule]) -> type[Rule]:
    """Class decorator adding a rule to the global registry."""
    inst = rule_cls()
    if not inst.id:
        raise ValueError(f"rule {rule_cls.__name__} has no id")
    REGISTRY[inst.id] = inst
    return rule_cls


def all_rule_ids() -> list[str]:
    _load_rules()
    return sorted(REGISTRY)


_RULES_LOADED = False


def _load_rules() -> None:
    """Import the rule pack exactly once (registration side effects)."""
    global _RULES_LOADED
    if not _RULES_LOADED:
        from . import rules  # noqa: F401
        _RULES_LOADED = True


@dataclass
class WaiverAudit:
    """One live waiver's audit row (the ``--audit-waivers`` surface and
    the ``waivers`` key of the JSON report)."""

    path: str
    line: int
    rules: tuple[str, ...]
    reason: str | None
    waived: int                      # findings this waiver suppressed
    stale: tuple[str, ...] = ()      # waived rule ids that never fired

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "rules": list(self.rules),
            "reason": self.reason,
            "waived": self.waived,
            "stale": list(self.stale),
        }

    def render(self) -> str:
        status = (
            f"STALE: {','.join(self.stale)} would not fire"
            if self.stale else f"active ({self.waived} waived)"
        )
        reason = self.reason or "<NO REASON>"
        return (
            f"{self.path}:{self.line}: disable={','.join(self.rules)} "
            f"-- {reason} [{status}]"
        )


@dataclass
class Report:
    """One analysis run: active findings, waived findings, file count,
    and the waiver audit."""

    findings: list[Finding] = field(default_factory=list)
    waived: list[Finding] = field(default_factory=list)
    waivers: list[WaiverAudit] = field(default_factory=list)
    files: int = 0

    def to_dict(self) -> dict:
        """The ``--json`` document.  Schema-stable: the drift-guard test in
        tests/test_static_analysis.py pins these keys.  Version 2 added
        the ``waivers`` audit list (WAIVER-002)."""
        return {
            "schema_version": 2,
            "tool": "cpzk-lint",
            "rule_ids": all_rule_ids(),
            "files": self.files,
            "findings": [f.to_dict() for f in self.findings],
            "waived": [f.to_dict() for f in self.waived],
            "waivers": [w.to_dict() for w in self.waivers],
            "summary": {
                "findings": len(self.findings),
                "waived": len(self.waived),
            },
        }

    def to_sarif(self) -> dict:
        """The ``--format sarif`` document (SARIF 2.1.0, minimal profile)
        so CI can annotate PRs.  Waived findings are carried with
        ``suppressions`` so annotation UIs hide them by default; exit
        codes and the human/text output are unaffected."""
        _load_rules()
        rules = [
            {
                "id": rule_id,
                "shortDescription": {"text": REGISTRY[rule_id].summary},
                "fullDescription": {"text": REGISTRY[rule_id].rationale},
            }
            for rule_id in all_rule_ids()
        ]

        def result(f: Finding, suppressed: bool) -> dict:
            row = {
                "ruleId": f.rule,
                "level": "error",
                "message": {"text": f.message},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path.replace(os.sep, "/"),
                        },
                        "region": {
                            "startLine": max(1, f.line),
                            "startColumn": max(1, f.col + 1),
                        },
                    },
                }],
            }
            if suppressed:
                row["suppressions"] = [{"kind": "inSource"}]
            return row

        return {
            "$schema": (
                "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json"
            ),
            "version": "2.1.0",
            "runs": [{
                "tool": {
                    "driver": {
                        "name": "cpzk-lint",
                        "informationUri": (
                            "https://github.com/kobby-pentangeli/"
                            "chaum-pedersen-zkp"
                        ),
                        "rules": rules,
                    },
                },
                "results": (
                    [result(f, False) for f in self.findings]
                    + [result(f, True) for f in self.waived]
                ),
            }],
        }


def analyze_source(
    source: str, path: str = "cpzk_tpu/fixture.py",
    rules: list[str] | None = None,
) -> Report:
    """Analyze one in-memory source blob (the fixture-test entry point).
    ``path`` is virtual and drives plane-scoped rules."""
    return _analyze([(source, path)], rules)


def analyze_paths(paths: list[str], rules: list[str] | None = None) -> Report:
    """Analyze files/directories on disk (the CLI entry point)."""
    blobs: list[tuple[str, str]] = []
    for path in collect_files(paths):
        with open(path, encoding="utf-8", errors="replace") as f:
            blobs.append((f.read(), os.path.relpath(path)))
    return _analyze(blobs, rules)


def _analyze(blobs: list[tuple[str, str]], rules: list[str] | None) -> Report:
    _load_rules()
    active = [
        REGISTRY[r] for r in (rules if rules is not None else sorted(REGISTRY))
        if r in REGISTRY
    ]
    active_ids = {r.id for r in active}
    report = Report(files=len(blobs))
    want_waiver_rule = rules is None or "WAIVER-001" in (rules or [])
    want_stale_rule = rules is None or "WAIVER-002" in (rules or [])
    for source, path in blobs:
        mod = parse_module(source, path)
        if isinstance(mod, Finding):
            report.findings.append(mod)
            continue
        raw: list[Finding] = []
        for rule in active:
            try:
                raw.extend(rule.check(mod))
            except Exception as e:  # a rule bug must not kill the whole run
                raw.append(Finding(
                    rule.id, mod.path, 1, 0,
                    f"internal rule error (treat as a finding): {e!r}",
                ))
        waived_count: dict[int, int] = {}
        for f in raw:
            waiver = next(
                (w for w in mod.waivers if w.covers(f.rule, f.line)), None
            )
            if waiver is not None:
                report.waived.append(f)
                waived_count[waiver.line] = waived_count.get(waiver.line, 0) + 1
            else:
                report.findings.append(f)
        for w in mod.waivers:
            if want_waiver_rule and w.reason is None:
                report.findings.append(Finding(
                    "WAIVER-001", mod.path, w.line, 0,
                    "waiver without a reason: write "
                    "`# cpzk-lint: disable=RULE-ID -- <why>`",
                ))
            # WAIVER-002: a waived rule that no longer fires anywhere in
            # the waiver's span is stale — the code it excused is gone (or
            # changed), so the suppression must not outlive it.  Judged
            # only for rules that actually ran this pass (a --rules filter
            # that skipped the rule cannot call its waiver stale); a rule
            # id no registered rule answers to can never fire and is
            # always stale on a full run.
            stale: list[str] = []
            for rid in w.rules:
                if rid in active_ids:
                    if not any(
                        f.rule == rid and w.span[0] <= f.line <= w.span[1]
                        for f in raw
                    ):
                        stale.append(rid)
                elif rules is None and rid not in REGISTRY:
                    stale.append(rid)
            if stale and want_stale_rule:
                report.findings.append(Finding(
                    "WAIVER-002", mod.path, w.line, 0,
                    f"stale waiver: {', '.join(stale)} would not fire on "
                    "the waived lines — delete the disable comment (or "
                    "fix its rule id)",
                ))
            report.waivers.append(WaiverAudit(
                path=mod.path, line=w.line, rules=w.rules, reason=w.reason,
                waived=waived_count.get(w.line, 0),
                stale=tuple(stale) if want_stale_rule else (),
            ))
    report.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    report.waived.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    report.waivers.sort(key=lambda w: (w.path, w.line))
    return report
