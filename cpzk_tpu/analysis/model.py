"""Explicit-state model checking for the fence/handover/split protocols.

The static rules (AWAIT-001/ACK-001/FENCE-001) check *code shapes*; this
module checks the *protocols themselves*: small hand-written state
machines of the three distributed-operations protocols this repo ships,
explored exhaustively (BFS over every interleaving of protocol steps,
client actions, and crash points) in the spirit of TLA+-style explicit-
state checking — no external dependencies, states are flat dicts, a
counterexample is a readable step-by-step interleaving.

The three models, each faithful to its implementation and to the chaos
suite's crash-point semantics:

- :class:`FailoverModel` — lease failover + epoch fencing (PR 8):
  sync-barrier replication, lease expiry on death or partition, standby
  promotion at epoch+1, stale-epoch ship fencing, and the
  ``REPLICATION_CRASH_POINTS`` (``pre_ship`` / ``mid_segment`` /
  ``pre_promote``).
- :class:`SplitModel` — the live split's decide/commit/rollback with
  the write-time owner fence (PR 16): atomic export→copy→map-flip, a
  multi-await VerifyProof-shaped handler that can straddle the flip,
  crash-resume at every ``FLEET_CRASH_POINTS`` stage, and the drain
  that destroys the source's stale copies.
- :class:`HandoverModel` — the coordinated handover incl. the challenge
  create/consume redirect (PR 18): fence → ship-tail-at-watermark →
  promote → deposed, abort-to-serving on every pre-promote crash
  (``HANDOVER_CRASH_POINTS``), and a login flow (mint + consume) that
  must never strand.

Invariants (checked in every reachable state):

- **no-split-brain** — never two epoch-equal primaries accepting
  (acking) writes;
- **no-acked-write-loss** — an acknowledged write exists on the node
  that owns it, across every crash point in the ``FaultPlan``
  registries;
- **no-stranded-login** — every minted, unconsumed challenge is
  consumable on some node that serves (or will again serve) it.

**Validated by mutation**: re-introducing the two bugs the last
robustness PRs actually shipped must each produce a counterexample —
``--model split --mutate drop_write_fence`` (PR 16: the mint after the
batcher await acks onto a stale copy the drain then destroys) and
``--model handover --mutate serve_fenced_challenges`` (PR 18: a fenced
primary minting challenges locally strands the login once the standby
is promoted).  CI runs both with ``--expect-violation``.

CLI::

    python -m cpzk_tpu.analysis.model [--model all|failover|split|handover]
        [--mutate NAME] [--expect-violation] [--max-states N]
        [--max-depth N] [--list] [--quiet]

Exit codes: 0 — every requested model clean (or a counterexample found
under ``--expect-violation``); 1 — violation (or an expected violation
that did not appear); 2 — usage error.  See docs/operations.md for the
counterexample reading guide.
"""

from __future__ import annotations

import argparse
import sys
import time
from collections import deque
from dataclasses import dataclass, field

from ..resilience.faults import (
    FLEET_CRASH_POINTS,
    HANDOVER_CRASH_POINTS,
    REPLICATION_CRASH_POINTS,
)

#: Bounded client traffic per model run — two writes is enough to
#: distinguish "acked prefix" from "everything" in every protocol here.
MAX_WRITES = 2

State = dict
Frozen = tuple


def freeze(state: State) -> Frozen:
    return tuple(sorted(state.items()))


def thaw(frozen: Frozen) -> State:
    return dict(frozen)


class Model:
    """One protocol state machine.  Subclasses define ``initial()``,
    ``actions(state)`` (yielding ``(label, next_state)``), and
    ``invariants()`` (``(name, predicate)`` pairs).  ``crash_points``
    names the FaultPlan registry entries this model explores — each must
    appear as a ``crash:<point>`` transition label (the drift guard in
    tests/test_model_checker.py holds the registries to this)."""

    name = ""
    description = ""
    crash_points: tuple[str, ...] = ()
    #: mutation name -> the bug it re-introduces (for --list and errors)
    mutations: dict[str, str] = {}

    def __init__(self, mutation: str | None = None):
        if mutation is not None and mutation not in self.mutations:
            known = ", ".join(sorted(self.mutations)) or "none"
            raise ValueError(
                f"model {self.name!r} has no mutation {mutation!r} "
                f"(known: {known})"
            )
        self.mutation = mutation

    def initial(self) -> State:
        raise NotImplementedError

    def actions(self, s: State) -> list[tuple[str, State]]:
        raise NotImplementedError

    def invariants(self) -> list[tuple[str, "callable"]]:
        raise NotImplementedError

    def render(self, s: State) -> str:
        return " ".join(f"{k}={v}" for k, v in sorted(s.items()))


@dataclass
class Violation:
    invariant: str
    state: Frozen
    #: the interleaving from the initial state: (label, state) per step;
    #: step 0 is ("initial", initial_state)
    trace: list[tuple[str, Frozen]]


@dataclass
class CheckResult:
    model: Model
    states: int = 0
    transitions: int = 0
    depth: int = 0
    elapsed_s: float = 0.0
    complete: bool = False       # frontier exhausted within the bounds
    labels: set = field(default_factory=set)
    violation: Violation | None = None

    @property
    def ok(self) -> bool:
        return self.violation is None


def check(
    model: Model, max_states: int = 500_000, max_depth: int = 500,
) -> CheckResult:
    """Exhaustive BFS over the model's reachable states.  Stops at the
    first invariant violation (BFS order makes the counterexample a
    shortest trace) or when the frontier is exhausted."""
    t0 = time.monotonic()
    result = CheckResult(model=model)
    invs = model.invariants()

    def violated(fs: Frozen) -> str | None:
        s = thaw(fs)
        for name, pred in invs:
            if not pred(s):
                return name
        return None

    init = freeze(model.initial())
    parents: dict[Frozen, tuple[Frozen, str] | None] = {init: None}
    depth_of = {init: 0}
    queue: deque[Frozen] = deque([init])
    result.states = 1

    def trace_to(fs: Frozen) -> list[tuple[str, Frozen]]:
        steps: list[tuple[str, Frozen]] = []
        cur: Frozen | None = fs
        while cur is not None:
            link = parents[cur]
            if link is None:
                steps.append(("initial", cur))
                break
            prev, label = link
            steps.append((label, cur))
            cur = prev
        steps.reverse()
        return steps

    bad = violated(init)
    if bad is not None:
        result.violation = Violation(bad, init, trace_to(init))
        result.elapsed_s = time.monotonic() - t0
        return result

    complete = True
    while queue:
        fs = queue.popleft()
        d = depth_of[fs]
        result.depth = max(result.depth, d)
        if d >= max_depth:
            complete = False
            continue
        for label, nxt in model.actions(thaw(fs)):
            result.transitions += 1
            result.labels.add(label)
            nfs = freeze(nxt)
            if nfs in parents:
                continue
            if len(parents) >= max_states:
                complete = False
                continue
            parents[nfs] = (fs, label)
            depth_of[nfs] = d + 1
            result.states += 1
            bad = violated(nfs)
            if bad is not None:
                result.violation = Violation(bad, nfs, trace_to(nfs))
                result.elapsed_s = time.monotonic() - t0
                return result
            queue.append(nfs)
    result.complete = complete
    result.elapsed_s = time.monotonic() - t0
    return result


def render_trace(result: CheckResult) -> str:
    """A counterexample as a readable step-by-step interleaving (the
    format the docs/operations.md reading guide documents)."""
    v = result.violation
    model = result.model
    if v is None:
        return (
            f"model {model.name!r}: no counterexample — {result.states} "
            f"states, {result.transitions} transitions, depth "
            f"{result.depth}, invariants hold"
        )
    lines = [
        f"counterexample: invariant '{v.invariant}' violated in model "
        f"'{model.name}'"
        + (f" (mutation: {model.mutation})" if model.mutation else ""),
        f"  shortest trace, {len(v.trace) - 1} steps:",
    ]
    prev: State | None = None
    for i, (label, fs) in enumerate(v.trace):
        s = thaw(fs)
        if prev is None:
            lines.append(f"  step {i}: {label}")
            lines.append(f"      {model.render(s)}")
        else:
            changed = {
                k: v2 for k, v2 in s.items() if prev.get(k) != v2
            }
            delta = (
                " ".join(f"{k}={v2}" for k, v2 in sorted(changed.items()))
                or "(no state change)"
            )
            lines.append(f"  step {i}: {label}")
            lines.append(f"      -> {delta}")
        prev = s
    lines.append(f"  violated: {v.invariant}")
    lines.append(f"      full state: {model.render(thaw(v.state))}")
    return "\n".join(lines)


# -- model 1: lease failover + epoch fencing (PR 8) ---------------------------


class FailoverModel(Model):
    """Primary/standby pair under sync-barrier replication.

    A write is acknowledged only after the standby applied it (the
    ``attach_replication_barrier`` contract), the standby promotes at
    ``epoch+1`` when the lease expires (primary dead OR partitioned),
    and a healed old primary's ships and renewals are answered
    ``fenced: stale epoch`` — after which it stops acking.  The crash
    points are the REPLICATION registry: ``pre_ship`` (primary dies
    before a segment leaves), ``mid_segment`` (torn segment, rejected
    whole), ``pre_promote`` (standby dies at the promotion decision —
    a retried promote must succeed)."""

    name = "failover"
    description = "lease failover + epoch fencing (PR 8)"
    crash_points = REPLICATION_CRASH_POINTS
    mutations = {}

    def initial(self) -> State:
        return {
            "p_alive": True,      # primary process up
            "p_conn": True,       # primary reachable from the standby
            "p_fenced": False,    # primary observed a stale-epoch answer
            "p_epoch": 1,
            "p_log": 0,           # writes applied on the primary
            "p_known": 0,         # standby-applied seq the primary knows
            "acked": 0,           # writes acknowledged to clients
            "s_applied": 0,       # writes applied on the standby
            "s_role": "standby",
            "s_epoch": 1,
            "s_rebooted": False,  # pre_promote crash happened (retry ok)
            "lease_expired": False,
        }

    def actions(self, s: State) -> list[tuple[str, State]]:
        out: list[tuple[str, State]] = []

        def step(label: str, **upd) -> None:
            out.append((label, {**s, **upd}))

        p_serving = s["p_alive"] and not s["p_fenced"]
        # clients write to the primary while it serves (a partitioned
        # primary still appends — the sync barrier withholds the ack)
        if p_serving and s["p_log"] < MAX_WRITES:
            step("client:write", p_log=s["p_log"] + 1)
        # replication: ship the next unapplied write to the standby
        if p_serving and s["p_conn"] and s["p_log"] > s["s_applied"]:
            if s["p_epoch"] >= s["s_epoch"]:
                step(
                    "repl:ship",
                    s_applied=s["s_applied"] + 1,
                    p_known=s["s_applied"] + 1,
                )
            else:
                # promoted standby fences the stale epoch; the primary
                # observes it and stops acking (shipper.fenced)
                step("repl:fenced", p_fenced=True)
            step("crash:pre_ship", p_alive=False)
            step("crash:mid_segment", p_alive=False)
        # the sync barrier: ack only writes the primary KNOWS the
        # standby applied (knowledge travels with ship acks)
        if p_serving and s["acked"] < min(s["p_log"], s["p_known"]):
            step("client:ack", acked=s["acked"] + 1)
        # the network partitions (renewals stop) or heals
        if s["p_alive"] and s["p_conn"]:
            step("net:partition", p_conn=False)
        if s["p_alive"] and not s["p_conn"]:
            step("net:heal", p_conn=True)
        # lease expiry: primary dead or unreachable
        if not s["lease_expired"] and (not s["p_alive"] or not s["p_conn"]):
            step("lease:expire", lease_expired=True)
        # promotion (and the standby-side crash at the decision)
        if s["lease_expired"] and s["s_role"] == "standby":
            step(
                "standby:promote",
                s_role="primary", s_epoch=s["p_epoch"] + 1,
            )
            if not s["s_rebooted"]:
                step("crash:pre_promote", s_rebooted=True)
        # the promoted standby serves new writes itself (bounded with
        # the same budget; they apply locally so nothing can be lost)
        if s["s_role"] == "primary" and s["s_applied"] < MAX_WRITES:
            step("client:write_new_primary", s_applied=s["s_applied"] + 1)
        return out

    def invariants(self):
        def no_split_brain(s: State) -> bool:
            p_acking = s["p_alive"] and s["p_conn"] and not s["p_fenced"]
            s_acking = s["s_role"] == "primary"
            return not (p_acking and s_acking and s["p_epoch"] == s["s_epoch"])

        def acked_writes_survive(s: State) -> bool:
            # every acked write is applied on the standby — so promotion
            # at any crash point serves the full acked prefix
            return s["acked"] <= s["s_applied"]

        def promote_bumps_epoch(s: State) -> bool:
            return s["s_role"] != "primary" or s["s_epoch"] > s["p_epoch"]

        return [
            ("no-split-brain", no_split_brain),
            ("no-acked-write-loss", acked_writes_survive),
            ("promotion-bumps-epoch", promote_bumps_epoch),
        ]


# -- model 2: live split + write-time owner fence (PR 16) ---------------------


class SplitModel(Model):
    """The live split against one multi-await handler.

    The split runner walks idle → manifest → (atomic export→copy→flip)
    → drain → finish; a VerifyProof-shaped handler checks ownership at
    entry, suspends in the batcher, then mints — the mint's write-time
    fence (checked synchronously inside the shard lock) is what keeps
    an interleaved flip from acking onto the source's stale copy that
    the drain then destroys.  A crash at any FLEET_CRASH_POINTS stage
    leaves the standard resumable manifest; ``recover:resume`` is the
    offline ``fleet split`` completion.

    Mutation ``drop_write_fence`` re-introduces the PR 16 bug: the mint
    after the batcher await no longer re-checks ownership."""

    name = "split"
    description = "live split decide/commit/rollback + write fence (PR 16)"
    crash_points = FLEET_CRASH_POINTS
    mutations = {
        "drop_write_fence": (
            "PR 16 bug: the post-await session mint skips the write-time "
            "owner fence, acking onto the source's stale copy"
        ),
    }

    def initial(self) -> State:
        return {
            "stage": "idle",      # split file-state (manifest/copy/flip)
            "crashed": False,     # the source daemon died at a crash point
            "owner": "S",         # partition-map owner of the moved user
            "h": "start",         # the in-flight VerifyProof handler
            "acked": False,       # the handler's mint was acknowledged
            "home": "none",       # where the acked record lives (S or T)
            "lost": False,        # an acked record was destroyed
        }

    def actions(self, s: State) -> list[tuple[str, State]]:
        out: list[tuple[str, State]] = []

        def step(label: str, **upd) -> None:
            out.append((label, {**s, **upd}))

        # -- the handler (runs on the source daemon's event loop) ----------
        if not s["crashed"]:
            if s["h"] == "start":
                if s["owner"] == "S":
                    step("handler:check_owner", h="checked")
                else:
                    step("handler:entry_redirect", h="redirected")
            elif s["h"] == "checked":
                step("handler:await_batcher", h="awaiting")
            elif s["h"] == "awaiting":
                if self.mutation == "drop_write_fence":
                    # the bug: mint without re-checking ownership — the
                    # record lands in the source's store regardless
                    step("handler:mint_unfenced", h="acked",
                         acked=True, home="S")
                elif s["owner"] == "S":
                    step("handler:mint_fenced_ok", h="acked",
                         acked=True, home="S")
                else:
                    # owner_fence inside the shard lock: WrongPartition,
                    # answered with the standard redirect — no ack
                    step("handler:fence_redirect", h="redirected")

        # -- the split runner (live; no awaits inside the cut) -------------
        if not s["crashed"]:
            if s["stage"] == "idle":
                step("split:start", stage="manifest")
                step("crash:pre_manifest", crashed=True, h=_dead(s))
            elif s["stage"] == "manifest":
                step(
                    "split:cut", stage="flipped", owner="T",
                    home="T" if s["home"] == "S" else s["home"],
                )
                step("crash:pre_copy", crashed=True, h=_dead(s))
                step("crash:mid_copy", crashed=True, stage="mid_copy",
                     h=_dead(s))
                step("crash:pre_flip", crashed=True, stage="copied",
                     h=_dead(s))
            elif s["stage"] == "flipped":
                step(
                    "split:drain", stage="drained",
                    lost=s["lost"] or (s["acked"] and s["home"] == "S"),
                )
                step("crash:pre_drain", crashed=True, h=_dead(s))
            elif s["stage"] == "drained":
                step("split:finish", stage="done")
                step("crash:pre_finish", crashed=True, h=_dead(s))

        # -- crash-resume: the offline `fleet split` completion ------------
        if s["crashed"]:
            if s["stage"] == "idle":
                # pre_manifest: nothing armed; reboot serves as before
                step("recover:reboot", crashed=False)
            elif s["stage"] in ("manifest", "mid_copy", "copied"):
                # manifest exists: resume (re)copies from the source's
                # durable store — which holds every acked record — then
                # flips, drains, finishes
                step(
                    "recover:resume", crashed=False, stage="done",
                    owner="T",
                    home="T" if s["home"] == "S" else s["home"],
                )
            elif s["stage"] in ("flipped", "drained"):
                # post-flip: resume completes drain + finish; the drain
                # destroys the source's stale copies
                step(
                    "recover:resume", crashed=False, stage="done",
                    lost=s["lost"] or (
                        s["stage"] == "flipped"
                        and s["acked"] and s["home"] == "S"
                    ),
                )
        return out

    def invariants(self):
        def no_acked_write_loss(s: State) -> bool:
            return not s["lost"]

        def acked_on_owner(s: State) -> bool:
            # an acknowledged write lives on the partition that owns the
            # user — a mint onto a stale copy violates this immediately,
            # before the drain even destroys it
            return (not s["acked"]) or s["lost"] or s["home"] == s["owner"]

        return [
            ("no-acked-write-loss", no_acked_write_loss),
            ("acked-on-owner", acked_on_owner),
        ]


def _dead(s: State) -> str:
    """A daemon crash kills the in-flight handler; a delivered ack stays
    delivered (the client already has it)."""
    return "acked" if s["h"] == "acked" else "dead"


# -- model 3: coordinated handover + challenge redirect (PR 18) ---------------


class HandoverModel(Model):
    """Coordinated primary→standby handover against one login flow.

    The primary walks serving → fenced → tail_shipped → promote →
    deposed; every pre-promote crash point aborts back to serving with
    the fence rolled back (degrading to ordinary lease failover), and
    ``post_handover_promote`` leaves the standby promoted and the old
    primary deposed.  Challenges minted on the serving primary are on
    the standby too (the sync ack barrier); a *fenced* primary must
    redirect challenge traffic — PR 18's bug (mutation
    ``serve_fenced_challenges``) is minting locally instead, which
    strands the login: the challenge is beyond the fence watermark, so
    the promoted standby never has it and the deposed primary never
    serves the consume."""

    name = "handover"
    description = (
        "coordinated handover incl. challenge create/consume redirect "
        "(PR 18)"
    )
    crash_points = HANDOVER_CRASH_POINTS
    mutations = {
        "serve_fenced_challenges": (
            "PR 18 bug: a fenced primary serves challenge mints locally "
            "instead of redirecting, stranding in-flight logins"
        ),
    }

    def initial(self) -> State:
        return {
            "p": "serving",       # serving|fenced|tail_shipped|deposed
            "p_crashed": False,
            "p_epoch": 1,
            "s_role": "standby",
            "s_epoch": 1,
            "minted": False,      # the login's challenge was minted
            "ch_on_p": False,
            "ch_on_s": False,
            "consumed": False,    # the login completed
            "w_acked": False,     # one ordinary write, for ack-loss
            "w_on_s": False,
        }

    def actions(self, s: State) -> list[tuple[str, State]]:
        out: list[tuple[str, State]] = []

        def step(label: str, **upd) -> None:
            out.append((label, {**s, **upd}))

        p_up = not s["p_crashed"]
        # -- the handover protocol (primary side) --------------------------
        if p_up and s["s_role"] == "standby":
            if s["p"] == "serving":
                step("handover:fence", p="fenced")
                step("crash:pre_handover_fence")  # nothing armed: no-op
            elif s["p"] == "fenced":
                step("handover:ship_tail", p="tail_shipped")
                # abort: fence rolled back, pair unchanged
                step("crash:post_handover_fence", p="serving")
            elif s["p"] == "tail_shipped":
                step(
                    "handover:promote", p="deposed",
                    s_role="primary", s_epoch=s["p_epoch"] + 1,
                )
                step("crash:pre_handover_promote", p="serving")
                step("crash:pre_handover_ack", p="serving")
                step(
                    "crash:post_handover_promote", p="deposed",
                    p_crashed=True,
                    s_role="primary", s_epoch=s["p_epoch"] + 1,
                )
        # an unplanned death mid-operation degrades to lease failover
        if p_up and s["p"] in ("serving", "fenced"):
            step("die:primary", p_crashed=True)
        if s["p_crashed"] and s["s_role"] == "standby":
            step(
                "failover:promote",
                s_role="primary", s_epoch=s["p_epoch"] + 1,
            )

        # -- the login flow (one challenge, mint then consume) -------------
        if not s["minted"]:
            if p_up and s["p"] == "serving" and s["s_role"] == "standby":
                # sync barrier: the mint ack implies the standby has it
                step("client:mint", minted=True, ch_on_p=True, ch_on_s=True)
            elif p_up and s["p"] == "fenced":
                if self.mutation == "serve_fenced_challenges":
                    # the bug: minted beyond the fence watermark — the
                    # shipped tail will never carry it to the standby
                    step("client:mint_on_fenced", minted=True, ch_on_p=True)
                # fixed behavior: _wrong_partition redirects BEFORE the
                # create — the client retries at the new primary
            elif s["s_role"] == "primary":
                step("client:mint", minted=True, ch_on_s=True)
        if s["minted"] and not s["consumed"]:
            if p_up and s["p"] == "serving" and s["ch_on_p"]:
                step("client:consume", consumed=True)
            elif s["s_role"] == "primary" and s["ch_on_s"]:
                step("client:consume", consumed=True)

        # -- one ordinary acked write, for the ack-loss invariant ----------
        if not s["w_acked"]:
            if p_up and s["p"] == "serving" and s["s_role"] == "standby":
                step("client:write", w_acked=True, w_on_s=True)
            elif s["s_role"] == "primary":
                step("client:write", w_acked=True, w_on_s=True)
        return out

    def invariants(self):
        def no_split_brain(s: State) -> bool:
            p_accepting = not s["p_crashed"] and s["p"] == "serving"
            return not (
                p_accepting and s["s_role"] == "primary"
                and s["s_epoch"] <= s["p_epoch"]
            ) and not (p_accepting and s["s_role"] == "primary")

        def no_acked_write_loss(s: State) -> bool:
            return (not s["w_acked"]) or s["w_on_s"] or (
                not s["p_crashed"] and s["p"] in ("serving", "fenced")
            )

        def no_stranded_login(s: State) -> bool:
            if not s["minted"] or s["consumed"]:
                return True
            # the primary serves (or can abort back to serving) its copy
            p_can_serve = not s["p_crashed"] and s["p"] != "deposed"
            # the standby serves its copy now or after promotion
            consumable = (
                (s["ch_on_p"] and p_can_serve) or s["ch_on_s"]
            )
            return consumable

        return [
            ("no-split-brain", no_split_brain),
            ("no-acked-write-loss", no_acked_write_loss),
            ("no-stranded-login", no_stranded_login),
        ]


MODELS: dict[str, type[Model]] = {
    m.name: m for m in (FailoverModel, SplitModel, HandoverModel)
}


# -- CLI ----------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cpzk-model",
        description="explicit-state model checker for the fence/"
        "handover/split protocols (BFS over every interleaving)",
    )
    p.add_argument(
        "--model", default="all",
        choices=("all", *sorted(MODELS)),
        help="which protocol model to check (default: all)",
    )
    p.add_argument(
        "--mutate", default=None, metavar="NAME",
        help="re-introduce a known bug into the model (requires a "
        "single --model); see --list",
    )
    p.add_argument(
        "--expect-violation", action="store_true",
        help="invert the exit code: succeed only if a counterexample "
        "is found (the mutation-validation mode CI runs)",
    )
    p.add_argument("--max-states", type=int, default=500_000)
    p.add_argument("--max-depth", type=int, default=500)
    p.add_argument(
        "--list", action="store_true",
        help="list models, their crash points and mutations, and exit",
    )
    p.add_argument(
        "--quiet", action="store_true",
        help="suppress per-model statistics (violations still print)",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list:
        for name in sorted(MODELS):
            cls = MODELS[name]
            print(f"{name}: {cls.description}")
            print(f"  crash points: {', '.join(cls.crash_points)}")
            for mut, desc in sorted(cls.mutations.items()):
                print(f"  mutation {mut}: {desc}")
        return 0
    if args.mutate is not None and args.model == "all":
        print(
            "--mutate requires a single --model "
            "(the mutation names a specific protocol bug)",
            file=sys.stderr,
        )
        return 2
    names = sorted(MODELS) if args.model == "all" else [args.model]
    worst = 0
    for name in names:
        try:
            model = MODELS[name](mutation=args.mutate)
        except ValueError as e:
            print(f"cpzk-model: {e}", file=sys.stderr)
            return 2
        result = check(
            model, max_states=args.max_states, max_depth=args.max_depth,
        )
        if result.violation is not None:
            print(render_trace(result))
            if not args.expect_violation:
                worst = max(worst, 1)
        else:
            if not args.quiet:
                print(
                    f"model {name}: {result.states} states, "
                    f"{result.transitions} transitions, depth "
                    f"{result.depth}, "
                    f"{'exhaustive' if result.complete else 'BOUNDED'}, "
                    f"invariants hold ({result.elapsed_s:.2f}s)"
                )
            if args.expect_violation:
                print(
                    f"model {name}: expected a counterexample under "
                    f"mutation {args.mutate!r} but every invariant held "
                    "— the checker would miss the bug this mutation "
                    "re-introduces",
                    file=sys.stderr,
                )
                worst = max(worst, 1)
            if not result.complete and not args.expect_violation:
                print(
                    f"model {name}: exploration hit the "
                    f"--max-states/--max-depth bound before exhausting "
                    "the state space — raise the bounds",
                    file=sys.stderr,
                )
                worst = max(worst, 1)
    return worst


if __name__ == "__main__":
    sys.exit(main())
