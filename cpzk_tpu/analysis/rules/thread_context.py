"""THREAD-001: asyncio objects are settled/scheduled only on their loop.

Every asyncio primitive is single-threaded by contract: ``Future.set_result``
/ ``set_exception``, ``loop.call_soon`` / ``call_later`` / ``call_at``,
``create_task`` / ``ensure_future``, and ``Queue.put_nowait`` all assume
they run on the owning event loop's thread.  Called from a lane thread
(``server/dispatch.py``'s prep/device pair), a WAL/snapshot worker, or a
spawned ingest process, they race the loop's internals — the failure is
a silent lost wakeup or a cross-thread callback list corruption, not an
exception.  The one sanctioned bridge is
``loop.call_soon_threadsafe(...)`` (and ``run_coroutine_threadsafe``),
which is exactly how the dispatch lane posts results back.

This rule reads the execution-context inference
(:mod:`cpzk_tpu.analysis.contexts`): any function reachable from a
thread or process spawn site is scanned for the unsafe calls above.
Three carve-outs keep the sanctioned patterns clean:

- the bridge calls themselves (``call_soon_threadsafe``,
  ``run_coroutine_threadsafe``, ``asyncio.run``) are never findings;
- a callable registered THROUGH ``call_soon_threadsafe`` runs on the
  loop, so the context pass seeds it event-loop and it is not scanned;
- a loop the thread itself created (a local bound from
  ``asyncio.new_event_loop()``) is owned by that thread — driving it
  with ``call_soon`` / ``run_until_complete`` before ``run_forever`` is
  the standard ``start_in_thread`` bootstrap (``LaneRouter``,
  ``OpsPlane``) and is exempt.
"""

from __future__ import annotations

import ast

from ..contexts import PROCESS, THREAD, call_name
from ..engine import Finding, Module, Rule, register

#: Calls that mutate asyncio state and are only legal on the owning loop.
UNSAFE_ASYNCIO_CALLS = frozenset({
    "set_result", "set_exception",
    "call_soon", "call_later", "call_at",
    "create_task", "ensure_future",
    "put_nowait",
})
#: The sanctioned thread->loop bridges (never findings, and the context
#: pass seeds their callbacks as event-loop context).
SAFE_BRIDGES = frozenset({
    "call_soon_threadsafe", "run_coroutine_threadsafe", "run",
})
#: Constructors whose result is a loop OWNED by the creating thread.
_LOOP_FACTORIES = frozenset({"new_event_loop"})


def _receiver_root(func: ast.expr) -> str | None:
    """Root name of the call receiver (``loop.call_soon`` -> ``loop``)."""
    node = func
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


@register
class AsyncioFromThread(Rule):
    id = "THREAD-001"
    summary = (
        "asyncio futures/loops/queues are only touched from worker-thread "
        "context via loop.call_soon_threadsafe"
    )
    rationale = (
        "asyncio objects are not thread-safe: settling a Future or "
        "scheduling a callback from a lane/worker thread races the "
        "event loop's internals and loses wakeups silently; post results "
        "through loop.call_soon_threadsafe (the dispatch lane's contract)"
    )

    def check(self, module: Module) -> list[Finding]:
        out: list[Finding] = []
        for node, info in module.contexts.items():
            if info.is_async:
                continue
            hot = info.contexts & {THREAD, PROCESS}
            if not hot:
                continue
            self._scan(module, node, sorted(hot), out)
        return out

    def _scan(self, module: Module, func, hot: list[str],
              out: list[Finding]) -> None:
        owned_loops: set[str] = set()

        def visit(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue  # nested defs carry their own contexts
                if isinstance(child, ast.Assign) and isinstance(
                    child.value, ast.Call
                ):
                    # loop = asyncio.new_event_loop(): thread-owned loop
                    if call_name(child.value.func) in _LOOP_FACTORIES:
                        for t in child.targets:
                            if isinstance(t, ast.Name):
                                owned_loops.add(t.id)
                if isinstance(child, ast.Call):
                    name = call_name(child.func)
                    if (
                        name in UNSAFE_ASYNCIO_CALLS
                        and name not in SAFE_BRIDGES
                        and _receiver_root(child.func) not in owned_loops
                    ):
                        out.append(self.finding(
                            module, child,
                            f"{func.name} runs in {'/'.join(hot)} context "
                            f"and calls .{name}() on an asyncio object; "
                            "post through loop.call_soon_threadsafe(...) "
                            "(or run_coroutine_threadsafe) instead",
                        ))
                visit(child)

        visit(func)
