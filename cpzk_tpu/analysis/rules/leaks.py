"""LEAK-001: secret taint must never reach an observable text sink.

The reference wipes witnesses with ``zeroize`` and never formats them;
our port documents "secrets are never logged" in docs/security.md.  This
rule enforces it: any secret-tainted expression flowing into logging,
string formatting, exception messages, trace-ring events, metric label
values, or stdout is a finding — each of those surfaces persists or
transmits the text far outside the process's trust boundary.
"""

from __future__ import annotations

import ast

from ..engine import Finding, Module, Rule, dotted_parts, register

LOG_METHODS = frozenset({
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log",
})
#: Receiver names that identify a logging call (log.info, logger.debug,
#: logging.warning); keeps `resp.error(...)`-style calls out of scope.
LOG_RECEIVERS = frozenset({"log", "logger", "logging"})


def _is_log_call(node: ast.Call) -> bool:
    if not isinstance(node.func, ast.Attribute) or node.func.attr not in LOG_METHODS:
        return False
    parts = dotted_parts(node.func.value)
    if not parts:
        return False
    root = parts[0]
    leaf = parts[-1]
    return (
        root in LOG_RECEIVERS
        or leaf in LOG_RECEIVERS
        or root.endswith("logger")
        or (root == "logging" or leaf.startswith("getLogger"))
    )


@register
class SecretLeak(Rule):
    id = "LEAK-001"
    summary = "secret taint must not reach logs, formatting, exceptions, traces, or metric labels"
    rationale = (
        "a witness/nonce/response or KDF output formatted into a log "
        "line, exception message, trace event, or metric label leaves "
        "the process (log shippers, trace rings, Prometheus scrapes) and "
        "cannot be unleaked"
    )

    def check(self, module: Module) -> list[Finding]:
        out: list[Finding] = []
        seen: set[tuple[int, int]] = set()

        def flag(node: ast.AST, what: str) -> None:
            key = (getattr(node, "lineno", 1), getattr(node, "col_offset", 0))
            if key in seen:
                return
            seen.add(key)
            out.append(self.finding(
                module, node,
                f"secret-derived value reaches {what}; redact it (log a "
                "length/fingerprint, never the encoding)",
            ))

        def any_tainted_arg(call: ast.Call) -> bool:
            # top-level kinds only: `len(password)` evaluates through the
            # sanitizer list to untainted, while `str(password)` stays RAW
            return any(
                module.kind(a) is not None for a in call.args
            ) or any(
                module.kind(kw.value) is not None for kw in call.keywords
            )

        for node in ast.walk(module.tree):
            # f"...{secret}..."
            if isinstance(node, ast.FormattedValue):
                if module.kind(node.value) is not None:
                    flag(node, "an f-string")
                continue
            # "..." % secret
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
                if (
                    isinstance(node.left, ast.Constant)
                    and isinstance(node.left.value, str)
                    and module.kind(node.right) is not None
                ):
                    flag(node, "%-formatting")
                continue
            if isinstance(node, ast.Raise):
                if node.exc is not None and module.kind(node.exc) is not None:
                    flag(node, "an exception message")
                continue
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            attr = func.attr if isinstance(func, ast.Attribute) else ""
            name = func.id if isinstance(func, ast.Name) else ""
            if _is_log_call(node) and any_tainted_arg(node):
                flag(node, "a logging call")
            elif attr == "format" and any_tainted_arg(node):
                flag(node, "str.format()")
            elif attr == "record_event" and any_tainted_arg(node):
                flag(node, "a Tracer.record_event trace event")
            elif attr == "labels" and any_tainted_arg(node):
                flag(node, "a metric label value")
            elif name == "print" and any_tainted_arg(node):
                flag(node, "stdout via print()")
        return out
