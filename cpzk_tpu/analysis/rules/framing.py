"""FRAME-001: the length+CRC32 frame discipline has exactly one home.

Three planes now speak the same byte framing — ``length u32 | crc32 u32
| payload``, both big-endian, CRC over the payload only: the durability
WAL, the audit proof log, and the sharded-ingest unix pipe.  The framing
helpers live in :mod:`cpzk_tpu.durability.wal` (``frame_payload`` /
``encode_record`` on the write side, ``iter_frames`` /
``unpack_frame_header`` / ``frame_crc_ok`` on the read side).  A module
that re-rolls the header with ``struct.pack`` and a manual ``crc32``
works today and then drifts: a masked-vs-unmasked CRC, a flipped
endianness, a header width change in one copy — and two planes that are
supposed to interoperate (the standby replays shipped WAL frames, the
dispatch process parses shard frames) silently disagree at the byte
level.

Two patterns are findings anywhere outside ``durability/wal.py``:

- a ``pack(...)`` call (``struct.pack`` or a prebuilt ``Struct.pack``)
  whose arguments contain a ``crc32(...)`` call, or a local that was
  bound from one — hand-rolled frame *construction*;
- declaring the frame-header struct itself (``struct.Struct(">II")`` or
  ``struct.pack/unpack(">II", ...)``) — a private copy of the shared
  header that can drift from the canonical one.

Whole-object CRCs that never enter a packed header (the replication
segment checksum riding a protobuf field, the crc32-based shard/partition
hashes) are out of scope and do not fire.
"""

from __future__ import annotations

import ast

from ..contexts import call_name
from ..engine import Finding, Module, Rule, register

#: The one module allowed to define the framing (it IS the helper).
_CANONICAL = ("durability", "wal.py")

_HEADER_FMT = ">II"


def _contains_crc32(expr: ast.expr, crc_locals: set[str]) -> bool:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and call_name(sub.func) == "crc32":
            return True
        if isinstance(sub, ast.Name) and sub.id in crc_locals:
            return True
    return False


@register
class HandRolledFraming(Rule):
    id = "FRAME-001"
    summary = (
        "length+CRC framing is built/parsed only via the shared WAL "
        "framing helpers"
    )
    rationale = (
        "the WAL, proof log, and ingest pipe interoperate on one frame "
        "header; a module hand-rolling struct.pack + crc32 is a second "
        "copy of that contract, one endianness/mask/width drift away "
        "from two planes silently disagreeing at the byte level — use "
        "durability.wal.frame_payload/encode_record/iter_frames"
    )

    def check(self, module: Module) -> list[Finding]:
        if (
            module.plane == _CANONICAL[0]
            and module.filename == _CANONICAL[1]
        ):
            return []
        out: list[Finding] = []
        # locals bound from a crc32(...) expression, module-wide (cheap
        # over-approximation; the pack call is the finding anchor)
        crc_locals: set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and _contains_crc32(
                node.value, set()
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        crc_locals.add(t.id)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node.func)
            if name == "pack" and any(
                _contains_crc32(a, crc_locals) for a in node.args
            ):
                out.append(self.finding(
                    module, node,
                    "hand-rolled length+CRC frame construction; use "
                    "durability.wal.frame_payload (or encode_record for "
                    "WAL-style JSON records) so every plane shares one "
                    "header",
                ))
            elif name in ("Struct", "pack", "unpack", "pack_into",
                          "unpack_from") and node.args:
                first = node.args[0]
                if (
                    isinstance(first, ast.Constant)
                    and first.value == _HEADER_FMT
                ):
                    out.append(self.finding(
                        module, node,
                        "module declares its own copy of the shared "
                        f"frame header ({_HEADER_FMT!r}); import the "
                        "framing helpers from durability.wal instead of "
                        "re-rolling the struct",
                    ))
        return out
