"""AWAIT-001 / ACK-001 / FENCE-001: async-atomicity and ack-ordering.

The last two robustness PRs each shipped an interleaving bug that only
hand review caught, and both were instances of mechanical bug classes:

- **PR 16** (live split): ``verify_proof`` checked ownership at entry,
  awaited the batcher, then minted the session — a live split's
  export→copy→map-flip runs synchronously on the event loop and can
  land inside *any* await, so the mint acked a write on a partition
  that no longer owned the user, and the drain then dropped it.  Fixed
  by the write-time owner fence (``ServerState.owner_fence``) re-checked
  inside the shard lock, with the handler answering
  ``errors.WrongPartition`` with the standard redirect.
- **PR 18** (coordinated handover): ordering a protocol step wrong
  relative to the fence/ack watermark — a fenced primary serving
  challenges locally stranded every in-flight login for the drain
  window.

These rules machine-check the repaired shapes over the await-point
dataflow (``analysis/flows.py``, the v3 extension of the execution-
context inference):

``AWAIT-001`` — a guard read (``owns()`` / ``_check_owner`` /
``_wrong_partition*`` / an admission verdict / an epoch compare /
a fence call) followed by a suspension point followed by a user-keyed
mutation the guard licensed, with no re-check after the last await.
Accepted evidence that the mutation re-verifies at write time: a fence
or guard re-read after the last await before the mutation; the call
site lexically inside a ``try`` that catches ``WrongPartition`` (the
callee's write-time fence outcome is handled — the post-PR 16 handler
shape); or an in-module callee whose own flow contains a fence event.

``ACK-001`` — in any ``async def`` that mutates through one of
``ServerState``'s six insert/remove funnels, every acknowledgement the
caller can observe (an explicit ``return`` after the mutation, a
``Future.set_result``, or falling off the end) must be dominated by a
journal event (``_journal_append`` / ``_journal_sync`` / an ``append``
on a journal/WAL receiver) that follows the last funnel call —
acked-before-durable is unreachable by construction.

``FENCE-001`` — every funnel call inside an ``async`` method of a class
named ``ServerState`` must have a write-time fence re-check
(``self._fence(...)`` / ``owner_fence``) *earlier in the same
lock-acquiring ``with`` block*.  Reads and ``consume_challenges`` stay
unfenced on purpose and carry explicit waivers with the PR 16
rationale: removing a stale copy the split already exported cannot lose
an acknowledged write, and leaving the consume unfenced lets an
in-flight login retry at the new owner with its challenge intact there.

Like every cpzk-lint rule the analysis is a per-module linearization —
branch structure is flattened and the horizon is the module boundary.
"""

from __future__ import annotations

import ast

from ..engine import Finding, Module, Rule, register
from ..flows import FuncFlow


def _inmodule_fenced(module: Module, callee: str) -> bool:
    """Whether a call target resolves to a function in this module whose
    own flow re-checks the fence (covers in-module mutator wrappers)."""
    for flow in module.flows.values():
        if flow.name == callee and flow.has_fence:
            return True
    return False


@register
class AwaitAtomicity(Rule):
    id = "AWAIT-001"
    summary = (
        "no user-keyed mutation on a guard read that an await has "
        "invalidated — re-check ownership at write time"
    )
    rationale = (
        "a live split's export→copy→map-flip (and a handover's write "
        "fence) runs between awaits, so an ownership/admission/epoch "
        "verdict read before a suspension point is stale when the "
        "handler resumes — exactly the PR 16 VerifyProof bug, where the "
        "batcher await straddled the flip and the mint acked a write "
        "the partition no longer owned.  Re-check inside the shard lock "
        "(owner_fence/_fence), re-run the guard after the last await, "
        "or handle errors.WrongPartition at the mutation call site"
    )

    def check(self, module: Module) -> list[Finding]:
        out: list[Finding] = []
        for flow in module.flows.values():
            if not flow.is_async:
                continue
            self._check_flow(module, flow, out)
        return out

    def _check_flow(
        self, module: Module, flow: FuncFlow, out: list[Finding]
    ) -> None:
        events = flow.events
        for m in events:
            if m.kind != "mutate":
                continue
            awaits_before = [
                a for a in events if a.kind == "await" and a.order < m.order
            ]
            if not awaits_before:
                continue
            a_last = awaits_before[-1]
            licensed = [
                g for g in events
                if g.kind == "guard" and g.order < a_last.order
            ]
            if not licensed:
                continue  # nothing licensed the mutation before the await
            rechecked = any(
                g.kind == "guard" and a_last.order < g.order < m.order
                for g in events
            )
            if rechecked or m.wp:
                continue
            if _inmodule_fenced(module, m.name):
                continue
            g = licensed[-1]
            out.append(self.finding(
                module, m.node,
                f"{flow.name} mutates user-keyed state via {m.name}() "
                f"after an await (line {a_last.node.lineno}) that "
                f"invalidated the {g.name} guard read at line "
                f"{g.node.lineno} — a live split's map flip can land in "
                "that await; re-check ownership after the await "
                "(owner_fence/_fence inside the shard lock) or handle "
                "errors.WrongPartition at this call",
            ))


@register
class AckAfterDurable(Rule):
    id = "ACK-001"
    summary = (
        "a funnel mutation's journal append/sync must dominate every "
        "acknowledgement path out of the function"
    )
    rationale = (
        "the durability contract acks a mutation only after its WAL "
        "record is appended (under the mutating shard's lock) and "
        "synced — a return or Future.set_result that a caller can "
        "observe before the journal event acknowledges a write a crash "
        "can still lose, which the zero-acked-write-loss invariant "
        "(chaos suite, model checker) forbids by construction"
    )

    def check(self, module: Module) -> list[Finding]:
        out: list[Finding] = []
        for flow in module.flows.values():
            if not flow.is_async:
                continue
            funnels = [e for e in flow.events if e.is_funnel]
            if not funnels:
                continue
            for ack in flow.events:
                if ack.kind != "ack":
                    continue
                mutated = [f for f in funnels if f.order < ack.order]
                if not mutated:
                    continue
                m_last = mutated[-1]
                journaled = any(
                    e.kind == "journal"
                    and m_last.order < e.order < ack.order
                    for e in flow.events
                )
                if journaled:
                    continue
                how = (
                    "falls off the end" if ack.name == "end"
                    else f"acks via {ack.name}" if ack.name != "return"
                    else "returns"
                )
                out.append(self.finding(
                    module, ack.node if ack.name != "end" else m_last.node,
                    f"{flow.name} {how} after the {m_last.name}() "
                    f"mutation at line {m_last.node.lineno} with no "
                    "journal append/sync in between — acked-before-"
                    "durable; append the record under the shard lock "
                    "and await _journal_sync() before acknowledging",
                ))
        return out


@register
class WriteFence(Rule):
    id = "FENCE-001"
    summary = (
        "ServerState funnel mutations carry the owner_fence re-check "
        "inside their shard-lock section"
    )
    rationale = (
        "the entry-point ownership check alone cannot fence multi-await "
        "handlers across a live split's map flip (PR 16): only a fence "
        "re-checked synchronously inside the shard lock, in the same "
        "critical section as the mutation, is totally ordered against "
        "the flip.  Reads and consume_challenges stay unfenced on "
        "purpose (waived with the rationale): removing a stale copy the "
        "split already exported cannot lose an acked write, and an "
        "unfenced consume lets an in-flight login retry at the new "
        "owner with its challenge intact there"
    )

    def check(self, module: Module) -> list[Finding]:
        out: list[Finding] = []
        for flow in module.flows.values():
            if not flow.is_async or flow.cls != "ServerState":
                continue
            for m in flow.events:
                if not m.is_funnel:
                    continue
                if m.lock is None:
                    out.append(self.finding(
                        module, m.node,
                        f"{flow.name} calls {m.name}() outside any "
                        "lock-acquiring with-block — the write-time "
                        "owner fence must run inside the mutating "
                        "shard's lock section (PR 16)",
                    ))
                    continue
                fenced = any(
                    e.is_fence and e.lock == m.lock and e.order < m.order
                    for e in flow.events
                )
                if fenced:
                    continue
                out.append(self.finding(
                    module, m.node,
                    f"{flow.name} calls {m.name}() with no owner_fence/"
                    "_fence re-check earlier in the same shard-lock "
                    "section — a handler resuming after a live split's "
                    "map flip acks a write this partition no longer "
                    "owns (PR 16); call self._fence(user_id) under the "
                    "lock before the funnel, or waive with the "
                    "documented read/consume rationale",
                ))
        return out
