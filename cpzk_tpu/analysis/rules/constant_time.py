"""CT-001 / CT-002: constant-time discipline on secret-tainted values.

The reference crate gets these structurally from ``subtle``: secret
comparisons go through ``ConstantTimeEq`` and the compiler has no reason
to branch on secret bits.  The Python port documents the same rules in
docs/security.md; these two rules make them machine-checked.
"""

from __future__ import annotations

import ast

from ..engine import RAW, Finding, Module, Rule, register

#: Planes where ANY secret-dependent branching is banned (CT-002): the
#: protocol math itself.  Host planes (server/client) branch on public
#: request data constantly and are covered by CT-001/LEAK-001 instead.
CT_BRANCH_PLANES = frozenset({"core", "protocol"})


@register
class VartimeEquality(Rule):
    id = "CT-001"
    summary = "equality on secret-derived bytes/ints must be constant-time"
    rationale = (
        "`==` on bytes/int short-circuits on the first differing "
        "byte/limb — a remote timing oracle on the secret; compare via "
        "hmac.compare_digest (or Scalar.__eq__, which already does)"
    )

    def check(self, module: Module) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Compare):
                continue
            if not any(isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops):
                continue
            sides = [node.left, *node.comparators]
            if any(module.kind(s) == RAW for s in sides):
                out.append(self.finding(
                    module, node,
                    "variable-time == / != on a secret-derived value; use "
                    "hmac.compare_digest on canonical encodings (or compare "
                    "Scalar objects, whose __eq__ is constant-time)",
                ))
        return out


@register
class SecretBranch(Rule):
    id = "CT-002"
    summary = "no secret-dependent branching in core/ and protocol/"
    rationale = (
        "an if/while/short-circuit whose condition depends on secret "
        "material makes execution time a function of the secret; the "
        "protocol planes must stay branchless on witnesses, nonces, and "
        "responses (docs/security.md constant-time discipline)"
    )

    def check(self, module: Module) -> list[Finding]:
        if module.plane not in CT_BRANCH_PLANES:
            return []
        out: list[Finding] = []
        seen: set[tuple[int, int]] = set()

        def flag(test: ast.expr, what: str) -> None:
            if module.any_tainted(test) is None:
                return
            key = (test.lineno, test.col_offset)
            if key in seen:
                return
            seen.add(key)
            out.append(self.finding(
                module, test,
                f"secret-dependent {what}: rewrite branchless (masked "
                "select / unconditional compute) or hoist the decision to "
                "public data",
            ))

        for node in ast.walk(module.tree):
            if isinstance(node, ast.If):
                flag(node.test, "if condition")
            elif isinstance(node, ast.While):
                flag(node.test, "while condition")
            elif isinstance(node, ast.IfExp):
                flag(node.test, "conditional expression")
            elif isinstance(node, ast.Assert):
                flag(node.test, "assert condition")
            elif isinstance(node, ast.BoolOp):
                for value in node.values[:-1]:
                    # every operand but the last can short-circuit
                    if module.any_tainted(value) is not None:
                        flag(value, "short-circuit operand")
        return out
