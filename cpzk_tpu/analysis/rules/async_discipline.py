"""ASYNC-001 / ASYNC-002: event-loop hygiene in the host serving planes.

ASYNC-001 — a blocking primitive (``time.sleep``, ``os.fsync``, sync
file I/O, ``subprocess``, ``input``) called directly inside an ``async
def`` stalls the whole event loop: every concurrent RPC, the batcher's
dispatch window, and the health service all freeze behind it.  Blocking
work belongs on a worker thread (``asyncio.to_thread`` /
``run_in_executor``); passing the callable there is fine — only direct
*calls* are flagged.  Nested sync ``def`` helpers are judged by the
execution-context inference (:mod:`cpzk_tpu.analysis.contexts`): one
shipped to a thread (the standard pattern, e.g.
``ServerState.snapshot``'s ``write()``) is exempt, while one the async
body calls inline provably runs ON the loop and is scanned too — the
helper indirection no longer hides the stall.

ASYNC-002 — ``asyncio.create_task`` / ``ensure_future`` results that are
immediately discarded are garbage-collectable mid-flight (the event loop
keeps only a weak reference) and their exceptions are silently dropped.
Every spawned task must be retained: bound to a name/attribute, added to
a set, or awaited.
"""

from __future__ import annotations

import ast

from ..contexts import EVENT_LOOP, PROCESS, THREAD
from ..engine import Finding, Module, Rule, dotted_parts, register

#: Planes whose async defs feed the serving event loop.  ``observability``
#: joined when the ops plane's HTTP handler loop moved onto the serving
#: event loop (ISSUE 10): a blocking call in a /statusz render would
#: stall every RPC exactly like one in a handler would.
ASYNC_PLANES = frozenset(
    {"server", "client", "durability", "admission", "observability"}
)

#: Dotted-call prefixes that block the calling thread.
BLOCKING_PREFIXES: tuple[tuple[str, ...], ...] = (
    ("time", "sleep"),
    ("os", "fsync"),
    ("os", "fdatasync"),
    ("os", "system"),
    ("subprocess",),
    ("socket", "create_connection"),
)
#: Bare names that block (sync file I/O, terminal reads).
BLOCKING_NAMES = frozenset({"open", "input"})


def _blocking_reason(call: ast.Call) -> str | None:
    func = call.func
    if isinstance(func, ast.Name):
        if func.id in BLOCKING_NAMES:
            return f"{func.id}()"
        return None
    parts = dotted_parts(func)
    if not parts:
        return None
    for prefix in BLOCKING_PREFIXES:
        if tuple(parts[: len(prefix)]) == prefix:
            return ".".join(parts) + "()"
    return None


@register
class BlockingInAsync(Rule):
    id = "ASYNC-001"
    summary = "no blocking calls inside async def bodies in the serving planes"
    rationale = (
        "a sync sleep/fsync/open/subprocess inside an async handler "
        "freezes the event loop for every concurrent RPC; route it "
        "through asyncio.to_thread / run_in_executor"
    )

    def check(self, module: Module) -> list[Finding]:
        if module.plane not in ASYNC_PLANES:
            return []
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                self._check_async_body(module, node, out)
        return out

    def _check_async_body(
        self, module: Module, func: ast.AsyncFunctionDef, out: list[Finding]
    ) -> None:
        def scan(node: ast.AST, where: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.AsyncFunctionDef, ast.Lambda)):
                    # nested async defs are visited by the outer ast.walk
                    # pass in check(); lambdas are callbacks, not calls
                    continue
                if isinstance(child, ast.FunctionDef):
                    # nested sync def: exempt when it runs on a worker
                    # thread (a to_thread / Thread target — the inference
                    # seeded it THREAD), scanned when the async body
                    # provably calls it inline on the loop
                    ctx = module.func_contexts(child)
                    if EVENT_LOOP in ctx and not ctx & {THREAD, PROCESS}:
                        scan(
                            child,
                            f"`{child.name}` (called inline from `async "
                            f"def {func.name}`)",
                        )
                    continue
                if isinstance(child, ast.Call):
                    reason = _blocking_reason(child)
                    if reason is not None:
                        out.append(self.finding(
                            module, child,
                            f"blocking {reason} inside {where} stalls the "
                            "event loop; wrap it in asyncio.to_thread(...)",
                        ))
                scan(child, where)

        scan(func, f"`async def {func.name}`")


@register
class OrphanedTask(Rule):
    id = "ASYNC-002"
    summary = "create_task/ensure_future results must be retained"
    rationale = (
        "the event loop holds only a weak reference to spawned tasks: a "
        "discarded handle can be garbage-collected mid-flight and its "
        "exception is silently dropped — keep the handle and await or "
        "cancel it"
    )

    SPAWNERS = frozenset({"create_task", "ensure_future"})

    def check(self, module: Module) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Expr):
                continue
            value = node.value
            if isinstance(value, ast.Await):
                continue  # awaiting retains the task to completion
            if isinstance(value, ast.Call) and self._is_spawn(value):
                out.append(self.finding(
                    module, value,
                    "task handle discarded: bind the result of "
                    f"{_spawn_name(value)}() and await or cancel it "
                    "(or add it to a set with a done-callback discard)",
                ))
        # `_ = create_task(...)` is the same orphan in disguise
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_"
                and isinstance(node.value, ast.Call)
                and self._is_spawn(node.value)
            ):
                out.append(self.finding(
                    module, node.value,
                    "task handle bound to `_` is still discarded: keep a "
                    "real reference and await or cancel it",
                ))
        return out

    def _is_spawn(self, call: ast.Call) -> bool:
        return _spawn_name(call) in self.SPAWNERS


def _spawn_name(call: ast.Call) -> str:
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""
