"""cpzk-lint rule pack: one module per discipline, one visitor per rule.

Importing this package registers every rule with
:data:`cpzk_tpu.analysis.engine.REGISTRY`.  ``PARSE-001`` and
``WAIVER-001`` are emitted by the engine itself (a file that does not
parse, a waiver without a reason); they are registered here as
documentation-only entries so the rule inventory — the CLI's
``--list-rules``, the JSON report's ``rule_ids``, and the
docs/security.md drift guard — names every id a report can contain.
"""

from __future__ import annotations

from ..engine import Module, Rule, register
from . import (  # noqa: F401  (import-for-registration)
    async_discipline,
    atomicity,
    constant_time,
    framing,
    grpc_abort,
    jax_purity,
    leaks,
    locking,
    process_spawn,
    state_funnels,
    thread_context,
)


@register
class ParseRule(Rule):
    id = "PARSE-001"
    summary = "source file must parse"
    rationale = (
        "an unparseable file is invisible to every other rule, so it is "
        "itself a finding rather than a crash or a silent skip"
    )

    def check(self, module: Module):  # emitted by the engine's loader
        return []


@register
class WaiverRule(Rule):
    id = "WAIVER-001"
    summary = "inline waivers must carry a reason"
    rationale = (
        "`# cpzk-lint: disable=RULE-ID -- <why>` keeps every suppression "
        "justified in the diff; a bare disable is itself a finding and "
        "cannot be waived"
    )

    def check(self, module: Module):  # emitted by the engine's waiver scan
        return []


@register
class StaleWaiverRule(Rule):
    id = "WAIVER-002"
    summary = "inline waivers must still suppress a live finding"
    rationale = (
        "a disable comment whose rule would no longer fire on the waived "
        "lines excuses code that is gone — stale suppressions hide the "
        "NEXT violation someone writes under them; delete the comment "
        "(audit with --audit-waivers).  Like WAIVER-001, it cannot be "
        "waived"
    )

    def check(self, module: Module):  # emitted by the engine's waiver scan
        return []
