"""FUNNEL-001: ServerState registry mutations route through the funnels.

ISSUE 14 rebuilt ``server/state.py`` around six **mutation funnels** —
``_user_insert`` / ``_user_remove`` / ``_session_insert`` /
``_session_remove`` / ``_challenge_insert`` / ``_challenge_remove`` —
and three pieces of derived state now depend on every mutation passing
through them: the O(1) capacity counters (``_n_users`` etc.), the
per-shard expiry time-wheels, and the per-user-list churn cleanup.  A
direct write like ``shard._sessions[token] = data`` keeps serving
happily while the wheel never learns the entry exists — it is then
never swept (a slow leak) or swept wrong (a session expiring while the
cap counter still counts it).  That desynchronization is silent by
construction, which is exactly the class of invariant this analyzer
exists to pin.

The rule walks every method of any class named ``ServerState`` (real or
fixture) and flags dict-level mutations — subscript assignment, ``del``,
``.pop`` / ``.popitem`` / ``.clear`` / ``.update`` / ``.setdefault`` —
of the three wheel-and-counter-backed registries (``_users``,
``_sessions``, ``_challenges``), reached through ``self``, a shard alias
(``shard = self._shards[i]`` / ``self._shard_for_user(...)`` / ``for
shard in self._shards``), or a registry alias (``registry =
shard._sessions``).  The funnel methods themselves and ``__init__`` are
the only exempt scopes — they ARE the funnel.  The per-user index lists
(``_user_challenges`` / ``_user_sessions``) are deliberately out of
scope: the live contract is "inserts manual under the shard lock,
removals funneled", and LOCK-001 already guards their lock discipline.
"""

from __future__ import annotations

import ast

from ..engine import Finding, Module, Rule, register
from .locking import SHARDS_ATTR, _is_self_attr, _shard_expr_source

#: The wheel-and-counter-backed registries (see module docstring).
FUNNELED_MAPS = frozenset({"_users", "_sessions", "_challenges"})
#: The funnels — the ONLY scopes allowed to mutate the maps directly.
FUNNEL_METHODS = frozenset({
    "_user_insert", "_user_remove",
    "_session_insert", "_session_remove",
    "_challenge_insert", "_challenge_remove",
    "__init__",
})
#: Dict methods that mutate in place.
DICT_MUTATORS = frozenset({
    "pop", "popitem", "clear", "update", "setdefault",
})

_FUNNEL_FOR = {
    "_users": "_user_insert/_user_remove",
    "_sessions": "_session_insert/_session_remove",
    "_challenges": "_challenge_insert/_challenge_remove",
}


@register
class StateMutationFunnel(Rule):
    id = "FUNNEL-001"
    summary = (
        "ServerState registry mutations go through the _*_insert/_*_remove "
        "funnels"
    )
    rationale = (
        "the capacity counters, expiry time-wheels, and per-user-list "
        "cleanup are maintained ONLY by the six mutation funnels; a "
        "direct registry write desynchronizes the time wheel silently — "
        "the entry is never swept (leak) or the counter drifts from the "
        "map (cap lies)"
    )

    def check(self, module: Module) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef) and node.name == "ServerState":
                for item in node.body:
                    if (
                        isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and item.name not in FUNNEL_METHODS
                    ):
                        self._check_method(module, item, out)
        return out

    def _check_method(
        self, module: Module,
        func: ast.FunctionDef | ast.AsyncFunctionDef,
        out: list[Finding],
    ) -> None:
        shard_aliases: set[str] = set()
        #: registry-alias name -> registry attr it aliases
        map_aliases: dict[str, str] = {}

        def registry_of(expr: ast.expr) -> str | None:
            """The funneled registry ``expr`` denotes, or None."""
            if _is_self_attr(expr, FUNNELED_MAPS):
                return expr.attr
            if (
                isinstance(expr, ast.Attribute)
                and expr.attr in FUNNELED_MAPS
                and isinstance(expr.value, ast.Name)
                and expr.value.id in shard_aliases
            ):
                return expr.attr
            if isinstance(expr, ast.Name):
                return map_aliases.get(expr.id)
            return None

        def note_alias(stmt: ast.stmt) -> None:
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                if (
                    isinstance(stmt.target, ast.Name)
                    and _is_self_attr(stmt.iter, frozenset({SHARDS_ATTR}))
                ):
                    shard_aliases.add(stmt.target.id)
                return
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                return
            target = stmt.targets[0]
            if not isinstance(target, ast.Name):
                return
            value = stmt.value
            if _shard_expr_source(value):
                shard_aliases.add(target.id)
                return
            # registry = shard._sessions (or the ternary sweep form:
            # shard._session_X if cond else shard._challenge_X)
            candidates = (
                [value.body, value.orelse]
                if isinstance(value, ast.IfExp) else [value]
            )
            for cand in candidates:
                reg = registry_of(cand)
                if reg is not None:
                    map_aliases[target.id] = reg
                    return

        def flag(node: ast.AST, reg: str, what: str) -> None:
            out.append(self.finding(
                module, node,
                f"{func.name} {what} {reg} directly, bypassing the "
                f"{_FUNNEL_FOR[reg]} funnel — the expiry wheel and "
                "capacity counter silently desynchronize; route the "
                "mutation through the funnel",
            ))

        def visit(node: ast.AST) -> None:
            """Source-order traversal so aliases are noted before use."""
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt):
                    note_alias(child)
                if isinstance(child, (ast.Assign, ast.AugAssign)):
                    targets = (
                        child.targets if isinstance(child, ast.Assign)
                        else [child.target]
                    )
                    for t in targets:
                        if isinstance(t, ast.Subscript):
                            reg = registry_of(t.value)
                            if reg is not None:
                                flag(child, reg, "subscript-assigns into")
                elif isinstance(child, ast.Delete):
                    for t in child.targets:
                        if isinstance(t, ast.Subscript):
                            reg = registry_of(t.value)
                            if reg is not None:
                                flag(child, reg, "deletes from")
                elif isinstance(child, ast.Call):
                    f = child.func
                    if isinstance(f, ast.Attribute) and f.attr in DICT_MUTATORS:
                        reg = registry_of(f.value)
                        if reg is not None:
                            flag(child, reg, f"calls .{f.attr}() on")
                visit(child)

        visit(func)
