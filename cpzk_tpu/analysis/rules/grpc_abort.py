"""GRPC-001: RESOURCE_EXHAUSTED aborts route through ``_abort_exhausted``.

The PR-4 pushback contract: EVERY shed path answers with
``cpzk-retry-after-ms`` trailing metadata so uninstrumented retry loops
spread out instead of hammering an overloaded server (gRFC A6).  The
single funnel is ``AuthServiceImpl._abort_exhausted``; a handler calling
``context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, ...)`` directly
reintroduces a bare "try again whenever" rejection.  This rule makes the
funnel structural: any ``.abort(...)`` whose arguments mention
``RESOURCE_EXHAUSTED`` outside the funnel function is a finding.
"""

from __future__ import annotations

import ast

from ..engine import Finding, Module, Rule, register

FUNNEL = "_abort_exhausted"


@register
class ExhaustedAbortFunnel(Rule):
    id = "GRPC-001"
    summary = "RESOURCE_EXHAUSTED aborts must go through _abort_exhausted"
    rationale = (
        "every shed path promises cpzk-retry-after-ms pushback metadata "
        "(PR-4 overload contract); a direct RESOURCE_EXHAUSTED abort "
        "ships a rejection without it"
    )

    def check(self, module: Module) -> list[Finding]:
        out: list[Finding] = []
        self._walk(module, module.tree, in_funnel=False, out=out)
        return out

    def _walk(
        self, module: Module, node: ast.AST, in_funnel: bool, out: list[Finding]
    ) -> None:
        for child in ast.iter_child_nodes(node):
            child_in_funnel = in_funnel
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_in_funnel = child.name == FUNNEL
            if (
                not child_in_funnel
                and isinstance(child, ast.Call)
                and isinstance(child.func, ast.Attribute)
                and child.func.attr == "abort"
                and self._mentions_exhausted(child)
            ):
                out.append(self.finding(
                    module, child,
                    "direct RESOURCE_EXHAUSTED abort bypasses "
                    f"{FUNNEL}() and ships no cpzk-retry-after-ms "
                    f"pushback; call self.{FUNNEL}(context, msg, "
                    "retry_after_s) instead",
                ))
            self._walk(module, child, child_in_funnel, out)

    @staticmethod
    def _mentions_exhausted(call: ast.Call) -> bool:
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Attribute) and sub.attr == "RESOURCE_EXHAUSTED":
                    return True
                if isinstance(sub, ast.Name) and sub.id == "RESOURCE_EXHAUSTED":
                    return True
        return False
