"""JAX-001: jit-compiled functions must be pure and correctly staged.

``jax.jit`` traces a function ONCE per input shape and replays the
compiled program forever after: a ``time.time()`` / ``random.random()``
/ ``os.urandom()`` call inside the body is baked in as a constant, and a
mutated global silently stops updating — classic trace-time bugs that
pass a single-call unit test.  ``static_argnames`` naming a parameter
that does not exist is similarly silent: jax ignores it and the argument
is traced, churning one compilation per distinct value.  (This is also
the security boundary in docs/security.md: the TPU never generates
protocol randomness — α/β come from the host CSPRNG.)
"""

from __future__ import annotations

import ast

from ..engine import Finding, Module, Rule, dotted_parts, register

#: Dotted-call prefixes whose results are trace-time constants (or host
#: side effects) inside a jitted body.  ``jax.random`` is fine — it is
#: functional; only the *Python* RNG/clock families are banned.
IMPURE_PREFIXES: tuple[tuple[str, ...], ...] = (
    ("random",),
    ("np", "random"),
    ("numpy", "random"),
    ("os", "urandom"),
    ("secrets",),
    ("time",),
    ("datetime",),
)


def _jit_decoration(dec: ast.expr) -> ast.Call | bool | None:
    """None = not a jit decorator; True = bare ``@jax.jit``; a Call node =
    the configured form carrying static_arg* kwargs."""
    parts = dotted_parts(dec)
    if parts and parts[-1] == "jit":
        return True
    if isinstance(dec, ast.Call):
        fparts = dotted_parts(dec.func)
        if fparts and fparts[-1] == "jit":
            return dec
        if fparts and fparts[-1] == "partial":
            for arg in dec.args:
                aparts = dotted_parts(arg)
                if aparts and aparts[-1] == "jit":
                    return dec
    return None


def _static_kwargs(call: ast.Call) -> tuple[list[str] | None, list[int] | None]:
    """(static_argnames, static_argnums) literals, None when absent or
    non-literal (then unverifiable — not a finding)."""
    names: list[str] | None = None
    nums: list[int] | None = None
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names = _str_literals(kw.value)
        elif kw.arg == "static_argnums":
            nums = _int_literals(kw.value)
    return names, nums


def _str_literals(node: ast.expr) -> list[str] | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
                return None
            out.append(e.value)
        return out
    return None


def _int_literals(node: ast.expr) -> list[int] | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (
                isinstance(e, ast.Constant)
                and isinstance(e.value, int)
                and not isinstance(e.value, bool)
            ):
                return None
            out.append(e.value)
        return out
    return None


def _param_names(func: ast.FunctionDef | ast.AsyncFunctionDef) -> list[str]:
    a = func.args
    return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]


@register
class JitPurity(Rule):
    id = "JAX-001"
    summary = "jit bodies stay pure; static_argnames/nums name real parameters"
    rationale = (
        "jax.jit traces once and replays: Python RNG/clock calls become "
        "baked-in constants, global mutation stops happening, and a "
        "misspelled static_argnames is silently ignored (one "
        "recompilation per value)"
    )

    def check(self, module: Module) -> list[Finding]:
        out: list[Finding] = []
        defs: dict[str, ast.FunctionDef | ast.AsyncFunctionDef] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)

        # decorator form
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                jit = _jit_decoration(dec)
                if jit is None:
                    continue
                if isinstance(jit, ast.Call):
                    self._check_static_args(module, jit, node, out)
                self._check_purity(module, node, out)

        # call form: jax.jit(fn, ...) with fn resolvable in this module
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fparts = dotted_parts(node.func)
            if not fparts or fparts[-1] != "jit":
                continue
            names, nums = _static_kwargs(node)
            target = None
            if node.args and isinstance(node.args[0], ast.Name):
                target = defs.get(node.args[0].id)
            if target is not None:
                self._check_static_args(module, node, target, out)
                self._check_purity(module, target, out)
            elif names or nums:
                # unresolvable target with static args: nothing to verify
                pass
        return out

    def _check_static_args(
        self, module: Module, call: ast.Call,
        func: ast.FunctionDef | ast.AsyncFunctionDef, out: list[Finding],
    ) -> None:
        params = _param_names(func)
        names, nums = _static_kwargs(call)
        if names is not None:
            for n in names:
                if n not in params:
                    out.append(self.finding(
                        module, call,
                        f"static_argnames names {n!r}, which is not a "
                        f"parameter of {func.name}() — jax silently "
                        "ignores it and retraces per value",
                    ))
        if nums is not None:
            has_vararg = func.args.vararg is not None
            for i in nums:
                if i < 0 or (i >= len(params) and not has_vararg):
                    out.append(self.finding(
                        module, call,
                        f"static_argnums index {i} is out of range for "
                        f"{func.name}() ({len(params)} parameters)",
                    ))

    def _check_purity(
        self, module: Module,
        func: ast.FunctionDef | ast.AsyncFunctionDef, out: list[Finding],
    ) -> None:
        for sub in ast.walk(func):
            if isinstance(sub, ast.Global):
                out.append(self.finding(
                    module, sub,
                    f"`global` mutation inside jitted {func.name}() "
                    "happens at trace time only — thread state through "
                    "arguments and return values",
                ))
            elif isinstance(sub, ast.Call):
                parts = dotted_parts(sub.func)
                if not parts:
                    continue
                if parts[0] in ("jax", "jnp"):  # jax.random etc. is functional
                    continue
                for prefix in IMPURE_PREFIXES:
                    if tuple(parts[: len(prefix)]) == prefix:
                        dotted = ".".join(parts)
                        out.append(self.finding(
                            module, sub,
                            f"{dotted}() inside jitted {func.name}() is "
                            "evaluated once at trace time and baked into "
                            "the compiled program; draw randomness/clocks "
                            "on the host and pass them in",
                        ))
                        break
