"""LOCK-001: ServerState registry mutations stay inside the state lock.

``ServerState`` deliberately guards all five maps with ONE asyncio lock
(see its module docstring — the reference's five RwLocks deadlock under
inconsistent ordering).  That design only holds if every mutation site
actually takes the lock; Rust's ``MutexGuard`` proves it in types, here
it is one forgotten ``async with self._lock`` away from a lost update.
This rule walks every method of any class named ``ServerState`` (real or
fixture) and flags mutations of the protected maps — and WAL appends,
whose ordering contract is "append under the state lock" — that are not
lexically inside a ``with self._lock`` block.

``__init__`` is exempt (the instance is not yet shared).  The documented
single-threaded boot path (``replay_journal_record``) carries an inline
waiver with its reason rather than an engine special case.
"""

from __future__ import annotations

import ast

from ..engine import Finding, Module, Rule, register

#: The five registries the state lock guards, plus the journal hook.
PROTECTED_ATTRS = frozenset({
    "_users", "_sessions", "_challenges", "_user_challenges",
    "_user_sessions",
})
#: Container methods that mutate in place.
MUTATORS = frozenset({
    "pop", "popitem", "setdefault", "clear", "update", "append", "remove",
    "extend", "insert", "add", "discard",
})
#: The maps whose .get()/.setdefault() hand back a *mutable member list*
#: — an alias to protected state, unlike the dataclass values in _users.
CONTAINER_MAPS = frozenset({"_user_challenges", "_user_sessions"})
#: Journal-append calls (WAL order must equal application order, which
#: only holds when the append happens under the state lock).
JOURNAL_CALLS = frozenset({"_journal_append"})


def _is_self_attr(node: ast.expr, attrs: frozenset[str]) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr in attrs
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


def _is_lock_expr(node: ast.expr) -> bool:
    """``self._lock`` (or anything ending ._lock on self)."""
    return (
        isinstance(node, ast.Attribute)
        and node.attr.endswith("_lock")
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


@register
class StateLockDiscipline(Rule):
    id = "LOCK-001"
    summary = "ServerState map mutations and WAL appends only under self._lock"
    rationale = (
        "one asyncio.Lock guards all five registries by design; a "
        "mutation outside it reorders against concurrent handlers and "
        "desyncs the WAL from in-memory application order"
    )

    def check(self, module: Module) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef) and node.name == "ServerState":
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        if item.name == "__init__":
                            continue
                        self._check_method(module, item, out)
        return out

    def _check_method(
        self, module: Module,
        func: ast.FunctionDef | ast.AsyncFunctionDef,
        out: list[Finding],
    ) -> None:
        aliases: set[str] = set()  # locals aliasing a protected container

        def note_alias(stmt: ast.stmt) -> None:
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                return
            target = stmt.targets[0]
            if not isinstance(target, ast.Name):
                return
            value = stmt.value
            # per_user = self._user_sessions  (whole-map alias)
            if _is_self_attr(value, PROTECTED_ATTRS):
                aliases.add(target.id)
            # per_user = self._user_sessions.setdefault/get(...)  (member list)
            elif (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr in ("get", "setdefault")
                and _is_self_attr(value.func.value, CONTAINER_MAPS)
            ):
                aliases.add(target.id)

        def is_protected(expr: ast.expr) -> bool:
            if _is_self_attr(expr, PROTECTED_ATTRS):
                return True
            return isinstance(expr, ast.Name) and expr.id in aliases

        def mutation_of(stmt_or_expr: ast.AST) -> str | None:
            """A human-readable description when the node mutates
            protected state, else None."""
            node = stmt_or_expr
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    if _is_self_attr(t, PROTECTED_ATTRS):
                        return f"rebinds self.{t.attr}"
                    if isinstance(t, ast.Subscript) and is_protected(t.value):
                        return "subscript-assigns into a protected map"
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and is_protected(t.value):
                        return "deletes from a protected map"
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute):
                    if f.attr in MUTATORS and is_protected(f.value):
                        return f"calls .{f.attr}() on a protected container"
                    if (
                        f.attr in JOURNAL_CALLS
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "self"
                    ):
                        return "appends to the journal"
                    if (
                        f.attr == "append"
                        and _is_self_attr(f.value, frozenset({"journal"}))
                    ):
                        return "appends to the journal"
            return None

        def own_exprs(stmt: ast.stmt) -> list[ast.expr]:
            """Expression trees attached directly to this statement —
            expressions cannot contain statements, so scanning them never
            leaks into a nested (possibly locked) block."""
            if isinstance(stmt, ast.Expr):
                return [stmt.value]
            if isinstance(stmt, (ast.Assign, ast.AugAssign)):
                return [stmt.value]
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                return [stmt.value]
            if isinstance(stmt, (ast.If, ast.While)):
                return [stmt.test]
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                return [stmt.iter]
            if isinstance(stmt, ast.Raise) and stmt.exc is not None:
                return [stmt.exc]
            return []

        def walk(stmts: list[ast.stmt], locked: bool) -> None:
            for stmt in stmts:
                note_alias(stmt)
                inner_locked = locked
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    if any(_is_lock_expr(i.context_expr) for i in stmt.items):
                        inner_locked = True
                    walk(stmt.body, inner_locked)
                    continue
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # nested helpers are checked where they run
                if not locked:
                    desc = mutation_of(stmt)
                    if desc is None:
                        for expr in own_exprs(stmt):
                            for sub in ast.walk(expr):
                                if isinstance(sub, ast.Call):
                                    desc = mutation_of(sub)
                                    if desc is not None:
                                        break
                            if desc is not None:
                                break
                    if desc is not None:
                        out.append(self.finding(
                            module, stmt,
                            f"{func.name} {desc} outside `with self._lock` — "
                            "take the state lock (or waive with the "
                            "documented reason if provably single-threaded)",
                        ))
                        continue
                # recurse into compound statements, preserving lock state
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if sub:
                        walk(sub, locked)
                for handler in getattr(stmt, "handlers", []) or []:
                    walk(handler.body, locked)

        walk(func.body, locked=False)
