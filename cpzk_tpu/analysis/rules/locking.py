"""LOCK-001: ServerState registry mutations stay inside the owning lock.

``ServerState`` splits its five registries into independently-locked
shards keyed by user hash (see its module docstring — the reference's
five RwLocks deadlock under inconsistent ordering; the pre-shard design's
single global lock serialized distinct users).  That design only holds if
every mutation site takes the OWNING shard's lock; Rust's ``MutexGuard``
proves it in types, here it is one forgotten ``async with shard.lock``
away from a lost update.  This rule walks every method of any class named
``ServerState`` (real or fixture) and flags mutations of the protected
maps — reached through ``self`` (the legacy single-lock shape) or through
a *shard alias* (a local bound from ``self._shards[...]``,
``self._shard_for_user(...)``, or ``for shard in self._shards``) — and
WAL appends, whose ordering contract is "append under the mutating
shard's lock", that are not lexically inside a ``with`` holding the right
lock:

- ``self._users[...] = ...``        needs ``with self._lock``;
- ``shard._users[...] = ...``       needs ``with shard.lock`` for that
  SAME alias — holding shard A's lock does not license mutating shard B;
- ``self._journal_append(...)``     needs any held state/shard lock (the
  append itself has no owning shard; the contract is that it happens
  inside the mutation's critical section).

``__init__`` is exempt (the instance is not yet shared).  The documented
single-threaded boot paths (``replay_journal_record``, ``restore``) and
the append funnel carry inline waivers with their reasons rather than an
engine special case.
"""

from __future__ import annotations

import ast

from ..engine import Finding, Module, Rule, register

#: The five registries the shard locks guard, plus the journal hook.
PROTECTED_ATTRS = frozenset({
    "_users", "_sessions", "_challenges", "_user_challenges",
    "_user_sessions",
})
#: Container methods that mutate in place.
MUTATORS = frozenset({
    "pop", "popitem", "setdefault", "clear", "update", "append", "remove",
    "extend", "insert", "add", "discard",
})
#: The maps whose .get()/.setdefault() hand back a *mutable member list*
#: — an alias to protected state, unlike the dataclass values in _users.
CONTAINER_MAPS = frozenset({"_user_challenges", "_user_sessions"})
#: Journal-append calls (WAL order must equal application order, which
#: only holds when the append happens under the mutating shard's lock).
JOURNAL_CALLS = frozenset({"_journal_append"})
#: self-attribute accesses that yield a shard: ``self._shards[i]`` and
#: calls of ``self._shard_for_user(...)`` / any ``self._shard*`` helper.
SHARDS_ATTR = "_shards"


def _is_self_attr(node: ast.expr, attrs: frozenset[str]) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr in attrs
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


def _shard_expr_source(node: ast.expr) -> bool:
    """Whether ``node`` evaluates to a shard: ``self._shards[...]`` or a
    ``self._shard*(...)`` helper call."""
    if (
        isinstance(node, ast.Subscript)
        and _is_self_attr(node.value, frozenset({SHARDS_ATTR}))
    ):
        return True
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr.startswith("_shard")
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == "self"
    ):
        return True
    return False


@register
class StateLockDiscipline(Rule):
    id = "LOCK-001"
    summary = (
        "ServerState map mutations and WAL appends only under the owning "
        "state/shard lock"
    )
    rationale = (
        "per-shard asyncio locks guard the five registries by design; a "
        "mutation outside the owning shard's lock (or under another "
        "shard's) reorders against concurrent handlers and desyncs the "
        "WAL from in-memory application order"
    )

    def check(self, module: Module) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef) and node.name == "ServerState":
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        if item.name == "__init__":
                            continue
                        self._check_method(module, item, out)
        return out

    def _check_method(
        self, module: Module,
        func: ast.FunctionDef | ast.AsyncFunctionDef,
        out: list[Finding],
    ) -> None:
        aliases: set[str] = set()        # locals aliasing a protected container
        shard_aliases: set[str] = set()  # locals bound to a StateShard
        # alias name -> owning lock name ("self" or a shard alias): member
        # lists pulled out of a shard's container map are owned by that
        # shard's lock
        alias_owner: dict[str, str] = {}

        def owner_of(expr: ast.expr) -> str | None:
            """The lock owner guarding ``expr`` when it is protected state:
            "self", a shard alias name, or None (not protected)."""
            if _is_self_attr(expr, PROTECTED_ATTRS):
                return "self"
            if (
                isinstance(expr, ast.Attribute)
                and expr.attr in PROTECTED_ATTRS
                and isinstance(expr.value, ast.Name)
                and expr.value.id in shard_aliases
            ):
                return expr.value.id
            if isinstance(expr, ast.Name) and expr.id in aliases:
                return alias_owner.get(expr.id, "self")
            return None

        def note_alias(stmt: ast.stmt) -> None:
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                # for shard in self._shards: ...
                if (
                    isinstance(stmt.target, ast.Name)
                    and _is_self_attr(stmt.iter, frozenset({SHARDS_ATTR}))
                ):
                    shard_aliases.add(stmt.target.id)
                return
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                return
            target = stmt.targets[0]
            if not isinstance(target, ast.Name):
                return
            value = stmt.value
            # shard = self._shards[i] / self._shard_for_user(uid)
            if _shard_expr_source(value):
                shard_aliases.add(target.id)
                return
            # per_user = self._user_sessions  (whole-map alias, legacy)
            if _is_self_attr(value, PROTECTED_ATTRS):
                aliases.add(target.id)
                alias_owner[target.id] = "self"
            # per_user = <owner>._user_sessions.setdefault/get(...)  (member
            # list — owned by whichever lock guards the container map)
            elif (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr in ("get", "setdefault")
                and isinstance(value.func.value, ast.Attribute)
                and value.func.value.attr in CONTAINER_MAPS
                and isinstance(value.func.value.value, ast.Name)
                and (
                    value.func.value.value.id == "self"
                    or value.func.value.value.id in shard_aliases
                )
            ):
                aliases.add(target.id)
                alias_owner[target.id] = (
                    "self"
                    if value.func.value.value.id == "self"
                    else value.func.value.value.id
                )

        def mutation_of(stmt_or_expr: ast.AST) -> tuple[str, str] | None:
            """(description, required lock owner) when the node mutates
            protected state, else None.  Owner "*" means any held state
            lock satisfies the contract (journal appends)."""
            node = stmt_or_expr
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    if _is_self_attr(t, PROTECTED_ATTRS):
                        return f"rebinds self.{t.attr}", "self"
                    if (
                        isinstance(t, ast.Attribute)
                        and t.attr in PROTECTED_ATTRS
                        and isinstance(t.value, ast.Name)
                        and t.value.id in shard_aliases
                    ):
                        return f"rebinds {t.value.id}.{t.attr}", t.value.id
                    if isinstance(t, ast.Subscript):
                        owner = owner_of(t.value)
                        if owner is not None:
                            return "subscript-assigns into a protected map", owner
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        owner = owner_of(t.value)
                        if owner is not None:
                            return "deletes from a protected map", owner
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute):
                    if f.attr in MUTATORS:
                        owner = owner_of(f.value)
                        if owner is not None:
                            return (
                                f"calls .{f.attr}() on a protected container",
                                owner,
                            )
                    if (
                        f.attr in JOURNAL_CALLS
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "self"
                    ):
                        return "appends to the journal", "*"
                    if (
                        f.attr == "append"
                        and _is_self_attr(f.value, frozenset({"journal"}))
                    ):
                        return "appends to the journal", "*"
            return None

        def own_exprs(stmt: ast.stmt) -> list[ast.expr]:
            """Expression trees attached directly to this statement —
            expressions cannot contain statements, so scanning them never
            leaks into a nested (possibly locked) block."""
            if isinstance(stmt, ast.Expr):
                return [stmt.value]
            if isinstance(stmt, (ast.Assign, ast.AugAssign)):
                return [stmt.value]
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                return [stmt.value]
            if isinstance(stmt, (ast.If, ast.While)):
                return [stmt.test]
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                return [stmt.iter]
            if isinstance(stmt, ast.Raise) and stmt.exc is not None:
                return [stmt.exc]
            return []

        def locks_of(stmt: ast.With | ast.AsyncWith) -> set[str]:
            """Lock owners this with-statement acquires: "self" for
            ``self.*_lock``, the alias name for ``<shard>.lock``."""
            owners: set[str] = set()
            for item in stmt.items:
                e = item.context_expr
                if (
                    isinstance(e, ast.Attribute)
                    and isinstance(e.value, ast.Name)
                ):
                    if e.value.id == "self" and e.attr.endswith("_lock"):
                        owners.add("self")
                    elif (
                        e.value.id in shard_aliases
                        and (e.attr == "lock" or e.attr.endswith("_lock"))
                    ):
                        owners.add(e.value.id)
            return owners

        def check_node(stmt: ast.stmt, held: frozenset[str]) -> bool:
            """Flag the statement if it mutates outside the owning lock;
            returns whether a finding was emitted."""
            hit = mutation_of(stmt)
            if hit is None:
                for expr in own_exprs(stmt):
                    for sub in ast.walk(expr):
                        if isinstance(sub, ast.Call):
                            hit = mutation_of(sub)
                            if hit is not None:
                                break
                    if hit is not None:
                        break
            if hit is None:
                return False
            desc, owner = hit
            if owner == "*":
                ok = bool(held)
                want = "a state/shard lock"
            else:
                ok = owner in held
                want = (
                    "`with self._lock`" if owner == "self"
                    else f"`with {owner}.lock`"
                )
            if ok:
                return False
            out.append(self.finding(
                module, stmt,
                f"{func.name} {desc} outside {want} — take the owning "
                "lock (or waive with the documented reason if provably "
                "single-threaded)",
            ))
            return True

        def walk(stmts: list[ast.stmt], held: frozenset[str]) -> None:
            for stmt in stmts:
                note_alias(stmt)
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    walk(stmt.body, held | locks_of(stmt))
                    continue
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # nested helpers are checked where they run
                if check_node(stmt, held):
                    continue
                # recurse into compound statements, preserving lock state
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if sub:
                        walk(sub, held)
                for handler in getattr(stmt, "handlers", []) or []:
                    walk(handler.body, held)

        walk(func.body, frozenset())
