"""PROC-001: spawn-context Process targets and args must survive pickling.

The ingest fleet (``server/ingest.py``) spawns its listener shards with
the **spawn** multiprocessing context — the only start method that is
safe under an asyncio parent (fork duplicates the event loop, lock
states, and gRPC's internal threads mid-flight).  Spawn pickles the
target callable and every argument into the child.  That contract has
two failure shapes, both discovered at runtime in the child, not at the
call site:

- an **unpicklable target**: a lambda, a nested ``def`` (pickled by
  qualified name — unreachable from the child), or a bound method whose
  instance drags the whole parent object graph (the supervisor holds
  asyncio servers, sockets, and tasks) into the pickle;
- **spawn-unsafe arguments**: locks/conditions/semaphores, event loops,
  sockets, or open file objects — either unpicklable outright or, worse,
  picklable-but-meaningless in the child (a ``threading.Lock`` state).

This rule checks every ``Process(target=..., args=...)`` call site
lexically: the target must resolve to a module-level function, and no
argument may be ``self`` or a local that was bound from a known
spawn-unsafe constructor (``threading.Lock`` / ``RLock`` / ``Condition``
/ ``Semaphore`` / ``Event``, ``asyncio.get_event_loop`` /
``get_running_loop`` / ``new_event_loop``, ``socket.socket`` /
``create_connection``, ``open`` / ``os.open``) or such a constructor
called inline.  Primitives, strings, dicts of config values — the shape
``run_shard`` takes — pass untouched.
"""

from __future__ import annotations

import ast

from ..contexts import ContextInference, FuncInfo, call_name
from ..engine import Finding, Module, Rule, register

#: Constructor call names whose results must never cross a spawn boundary.
UNSAFE_CONSTRUCTORS = frozenset({
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore", "Event",
    "get_event_loop", "get_running_loop", "new_event_loop",
    "socket", "create_connection", "socketpair",
    "open",
})
_UNSAFE_KIND = {
    "Lock": "lock", "RLock": "lock", "Condition": "lock",
    "Semaphore": "lock", "BoundedSemaphore": "lock", "Event": "lock",
    "get_event_loop": "event loop", "get_running_loop": "event loop",
    "new_event_loop": "event loop",
    "socket": "socket", "create_connection": "socket",
    "socketpair": "socket",
    "open": "open file",
}


@register
class SpawnSafeProcess(Rule):
    id = "PROC-001"
    summary = (
        "multiprocessing Process targets are module-level functions with "
        "picklable, spawn-safe args"
    )
    rationale = (
        "spawn pickles the target and every arg into the child: lambdas/"
        "nested defs/bound methods fail (or drag the parent's asyncio "
        "graph along), and locks/sockets/loops/open fds are meaningless "
        "on the other side — the failure surfaces in the child at "
        "runtime, not at the call site"
    )

    def check(self, module: Module) -> list[Finding]:
        out: list[Finding] = []
        inference = module.inference
        if inference is None:  # direct-constructed Module (tests)
            inference = ContextInference(module.tree)
            inference.run()
        # node -> enclosing FuncInfo, for resolving nested-def targets
        scope_of: dict[ast.AST, FuncInfo | None] = {}

        def assign_scopes(node: ast.AST, scope: FuncInfo | None) -> None:
            for child in ast.iter_child_nodes(node):
                child_scope = scope
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    child_scope = inference.by_node.get(child, scope)
                scope_of[child] = child_scope
                assign_scopes(child, child_scope)

        assign_scopes(module.tree, None)

        # local name -> unsafe kind, per enclosing function (lexical scan
        # in source order is enough: spawn sites follow their bindings)
        unsafe_locals: dict[tuple[int, str], str] = {}
        for node, scope in scope_of.items():
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                name = call_name(node.value.func)
                if name in UNSAFE_CONSTRUCTORS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            unsafe_locals[(id(scope), t.id)] = (
                                _UNSAFE_KIND[name]
                            )

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node.func) != "Process":
                continue
            self._check_spawn(module, node, inference, scope_of, unsafe_locals, out)
        return out

    def _check_spawn(
        self, module: Module, call: ast.Call, inference: ContextInference,
        scope_of: dict, unsafe_locals: dict, out: list[Finding],
    ) -> None:
        scope = scope_of.get(call)
        target = None
        arg_exprs: list[ast.expr] = []
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
            elif kw.arg in ("args", "kwargs"):
                arg_exprs.append(kw.value)

        if target is not None:
            self._check_target(module, call, target, inference, scope, out)
        for expr in arg_exprs:
            self._check_args(module, expr, scope, unsafe_locals, out)

    def _check_target(
        self, module: Module, call: ast.Call, target: ast.expr,
        inference: ContextInference, scope, out: list[Finding],
    ) -> None:
        if isinstance(target, ast.Lambda):
            out.append(self.finding(
                module, call,
                "Process target is a lambda — spawn pickles the target "
                "by qualified name and a lambda has none; hoist it to a "
                "module-level function",
            ))
            return
        if isinstance(target, ast.Attribute):
            # self.method / obj.method: the bound instance rides the pickle
            out.append(self.finding(
                module, call,
                f"Process target `{ast.unparse(target)}` is a bound "
                "method — spawn pickles the whole instance (locks, "
                "sockets, event loops included); use a module-level "
                "function taking plain-data args",
            ))
            return
        if isinstance(target, ast.Name):
            info = inference.resolve(target, scope)
            if info is not None and info.parent is not None:
                out.append(self.finding(
                    module, call,
                    f"Process target `{target.id}` is a nested def — "
                    "spawn pickles by qualified name, which the child "
                    "cannot import; hoist it to module level",
                ))

    def _check_args(
        self, module: Module, expr: ast.expr, scope,
        unsafe_locals: dict, out: list[Finding],
    ) -> None:
        # `self.host` is a plain attribute READ (the value pickles on its
        # own) — only a bare `self` element ships the instance.  Collect
        # the attribute-root Name nodes so they are skipped below.
        attr_roots = {
            id(sub.value)
            for sub in ast.walk(expr)
            if isinstance(sub, ast.Attribute) and isinstance(sub.value, ast.Name)
        }
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and id(sub) not in attr_roots:
                if sub.id == "self":
                    out.append(self.finding(
                        module, sub,
                        "Process args include `self` — spawn pickles the "
                        "whole instance and every unpicklable thing it "
                        "holds; pass the plain-data fields instead",
                    ))
                else:
                    kind = unsafe_locals.get((id(scope), sub.id))
                    if kind is not None:
                        out.append(self.finding(
                            module, sub,
                            f"Process args include `{sub.id}`, a {kind} — "
                            "spawn-unsafe across the process boundary; "
                            "pass plain data and rebuild it in the child",
                        ))
            elif isinstance(sub, ast.Call):
                name = call_name(sub.func)
                if name in UNSAFE_CONSTRUCTORS:
                    out.append(self.finding(
                        module, sub,
                        f"Process args construct a {_UNSAFE_KIND[name]} "
                        "inline — spawn-unsafe across the process "
                        "boundary; pass plain data and rebuild it in "
                        "the child",
                    ))
