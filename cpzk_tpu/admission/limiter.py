"""Per-client fair admission: keyed token buckets in an LRU-bounded table.

The global :class:`~cpzk_tpu.server.config.RateLimiter` treats every
caller as one aggregate, so a single abusive client starves everyone
(DAGOR, SoCC '18, calls this out as the first thing fair overload control
must fix).  :class:`KeyedTokenBuckets` keeps one token bucket per client
key instead — same fractional-refill arithmetic as the global limiter —
bounded by an LRU table so the *keyspace itself* cannot be used for a
memory DoS: an attacker minting fresh keys evicts only least-recently-seen
buckets (each eviction hands the evicted key a fresh burst at its next
request, which is why the global bucket stays on as a backstop).

Client keys come from :func:`client_key`: the ``cpzk-client-id`` gRPC
metadata tag when present (self-identifying clients, and deployments
behind an L7 proxy where the peer address is the proxy), else the gRPC
peer host.  A forged or rotated client-id only moves a caller between
buckets in the LRU-bounded table — it never widens the global bucket.

``requests_per_minute == 0`` means per-client limiting is **disabled**
(the unset state; negative values are rejected by config validation) —
unlike the global ``[rate_limit]`` bucket, where ``0`` is invalid because
a server that admits nothing is a misconfiguration, not a policy.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

#: Metadata tag a client may send to self-identify for fair admission.
CLIENT_ID_KEY = "cpzk-client-id"

#: Keys are truncated to this before entering the table (arbitrary
#: metadata must not become an allocation primitive).
MAX_KEY_LEN = 128


def client_key(context) -> str:
    """Fair-admission key of one RPC: the ``cpzk-client-id`` metadata tag
    when present, else the gRPC peer host (port stripped — one TCP
    connection churn must not mint fresh buckets).  Tolerates hand-rolled
    test contexts without metadata/peer; never raises."""
    try:
        for key, value in context.invocation_metadata() or ():
            if str(key).lower() == CLIENT_ID_KEY:
                if isinstance(value, bytes):
                    value = value.decode("utf-8", "replace")
                return ("id:" + str(value))[:MAX_KEY_LEN]
    except Exception:
        pass
    try:
        peer = str(context.peer() or "")
    except Exception:
        peer = ""
    if not peer:
        return "peer:unknown"
    # "ipv4:1.2.3.4:56789" / "ipv6:[::1]:56789" / "unix:/path" — drop the
    # trailing ephemeral port for the socket families that carry one
    if peer.startswith(("ipv4:", "ipv6:")) and ":" in peer[5:]:
        peer = peer.rsplit(":", 1)[0]
    return ("peer:" + peer)[:MAX_KEY_LEN]


class KeyedTokenBuckets:
    """LRU-bounded table of per-key token buckets.

    :meth:`check` returns ``None`` when the key is admitted and the
    retry-after estimate in seconds (time until one token refills) when
    it is over its rate.  The table holds at most ``max_keys`` buckets;
    the least-recently-*seen* key is evicted first.  Thread-safe (the
    admission controller is also driven from fuzz harnesses and tests
    outside the event loop).
    """

    def __init__(
        self,
        requests_per_minute: int,
        burst: int,
        max_keys: int = 1024,
        clock=time.monotonic,
    ):
        self.rate = max(0, int(requests_per_minute))
        self.burst = max(1, int(burst))
        self.max_keys = max(1, int(max_keys))
        self._clock = clock
        self._lock = threading.Lock()
        # key -> [tokens, last_update]; most-recently-seen at the end
        self._table: OrderedDict[str, list[float]] = OrderedDict()
        self.evictions = 0

    @property
    def enabled(self) -> bool:
        return self.rate > 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._table)

    def check(self, key: str, now: float | None = None) -> float | None:
        """Admit (``None``) or reject (retry-after seconds) one request
        from ``key`` at ``now`` (defaults to the injected clock)."""
        if not self.enabled:
            return None
        key = str(key)[:MAX_KEY_LEN]
        if now is None:
            now = self._clock()
        per_s = self.rate / 60.0
        with self._lock:
            bucket = self._table.pop(key, None)
            if bucket is None:
                bucket = [float(self.burst), now]
            self._table[key] = bucket
            while len(self._table) > self.max_keys:
                self._table.popitem(last=False)
                self.evictions += 1
            tokens, last = bucket
            tokens = min(
                tokens + max(0.0, now - last) * per_s, float(self.burst)
            )
            bucket[1] = now
            if tokens >= 1.0:
                bucket[0] = tokens - 1.0
                return None
            bucket[0] = tokens
            return (1.0 - tokens) / per_s
