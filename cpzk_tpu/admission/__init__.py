"""Adaptive overload control: per-client fair admission, priority-aware
shedding, and server retry-pushback.

The admission subsystem sits in front of every RPC handler:

- :mod:`.limiter` — :class:`KeyedTokenBuckets`, per-client token buckets
  in an LRU-bounded table (key = ``cpzk-client-id`` metadata tag, else
  gRPC peer host), so one abusive client exhausts its own bucket instead
  of the global one;
- :mod:`.controller` — :class:`AdmissionController`, DAGOR-style AIMD
  priority shedding driven by live batcher queue depth and ``queue_wait``
  stage latency, plus :meth:`~AdmissionController.retry_after_s` pushback
  sizing from the queue drain rate.

The service layer attaches every rejection's pushback as
``cpzk-retry-after-ms`` trailing metadata; the client-side
:class:`~cpzk_tpu.resilience.retry.RetryPolicy` prefers that pushback
over its own jittered backoff (gRFC A6 semantics).  See
``docs/operations.md`` §"Overload & admission".
"""

from __future__ import annotations

from .controller import (
    MIN_LEVEL,
    N_TIERS,
    RETRY_PUSHBACK_KEY,
    TIER_CHALLENGE,
    TIER_NAMES,
    TIER_REGISTER,
    TIER_VERIFY,
    AdmissionController,
    Rejection,
    classify,
)
from .limiter import CLIENT_ID_KEY, KeyedTokenBuckets, client_key

__all__ = [
    "AdmissionController",
    "CLIENT_ID_KEY",
    "KeyedTokenBuckets",
    "MIN_LEVEL",
    "N_TIERS",
    "RETRY_PUSHBACK_KEY",
    "Rejection",
    "TIER_CHALLENGE",
    "TIER_NAMES",
    "TIER_REGISTER",
    "TIER_VERIFY",
    "classify",
    "client_key",
]
