"""Priority-aware adaptive admission control (DAGOR-style).

The serving plane already measures its own load — batcher queue depth and
in-flight count (``server/batching.py``) and the per-entry ``queue_wait``
stage latency (observability subsystem).  :class:`AdmissionController`
turns those live signals into an **admission level**: a float in
``[1.0, N_TIERS]`` where an RPC of priority tier ``t`` is admitted iff
``t < level``.  The level moves by AIMD — multiplicative decrease on an
overload signal (queue utilization above ``high_watermark`` or average
queue wait above ``target_queue_wait_ms``), additive increase while
healthy — so the lowest-priority tiers shed first and re-admit last,
instead of today's all-or-nothing "Server overloaded" abort.

Priority tiers (lower = more important):

- tier 0 ``verify`` — ``VerifyProof`` / ``VerifyProofBatch``: an
  in-flight login; its challenge is already consumed, so shedding it
  wastes work the user cannot retry.
- tier 1 ``challenge`` — ``CreateAuthenticationChallenge``: starts a
  login; cheap, but shedding it merely delays the login.
- tier 2 ``register`` — ``Register`` / ``RegisterBatch``: the deferrable
  tier; registrations retry cleanly.

The level floor is 1.0: the adaptive tier never sheds ``verify`` —
extreme overload still reaches VerifyProof only through the per-client
buckets, the global bucket, and batcher backpressure, all of which answer
with pushback.  This is also what makes the acceptance invariant ("no
VerifyProof rejected while lower tiers are still admitted") structural
rather than tuned.

Every rejection carries a ``retry_after_s`` sized from the batcher's
current queue depth and observed drain rate, which the service layer
attaches as ``cpzk-retry-after-ms`` trailing metadata (gRFC A6 server
pushback) and the client retry policy honors in place of its own jitter.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from ..resilience.retry import RETRY_PUSHBACK_KEY  # noqa: F401  (re-export)
from ..server import metrics
from .limiter import KeyedTokenBuckets

#: Priority tiers, lowest number = most important.
TIER_VERIFY = 0
TIER_CHALLENGE = 1
TIER_REGISTER = 2
N_TIERS = 3

TIER_NAMES = {TIER_VERIFY: "verify", TIER_CHALLENGE: "challenge",
              TIER_REGISTER: "register"}

_RPC_TIERS = {
    "VerifyProof": TIER_VERIFY,
    "VerifyProofBatch": TIER_VERIFY,
    "CreateChallenge": TIER_CHALLENGE,
    "Register": TIER_REGISTER,
    "RegisterBatch": TIER_REGISTER,
}

#: The adaptive level never drops below this: tier-0 RPCs are exempt from
#: priority shedding (see module docstring).
MIN_LEVEL = 1.0


def classify(rpc) -> int:
    """Priority tier of an RPC name.  Total over arbitrary input (the
    fuzz invariant): unknown or non-string names land in the lowest
    priority tier rather than raising."""
    try:
        return _RPC_TIERS.get(str(rpc), TIER_REGISTER)
    except Exception:
        return TIER_REGISTER


@dataclass
class Rejection:
    """One shed decision: why, the status message, and the pushback."""

    reason: str  # "per_client" | "priority"
    message: str
    retry_after_s: float
    tier: int


class AdmissionController:
    """Keyed fair limiting + adaptive priority shedding + pushback sizing.

    ``batcher`` (a :class:`~cpzk_tpu.server.batching.DynamicBatcher`, or
    None on the inline CPU path) supplies the live load signals and the
    drain rate behind :meth:`retry_after_s`.  ``clock`` and ``signals``
    are injectable for deterministic tests: ``signals()`` must return
    ``(queue_utilization, avg_queue_wait_s)``.
    """

    def __init__(self, settings, batcher=None, clock=time.monotonic,
                 signals=None):
        self.settings = settings
        self.batcher = batcher
        self._clock = clock
        self._signals = signals
        self.buckets = KeyedTokenBuckets(
            settings.per_client_rpm,
            settings.per_client_burst,
            max_keys=settings.max_clients,
            clock=clock,
        )
        self.level = float(N_TIERS)  # boot admitting everything
        self.level_cap = float(N_TIERS)  # fleet-controller bias: the AIMD
                                         # level can recover only up to
                                         # this while an SLO burn page is
                                         # shedding load ahead of cascade
        self._lock = threading.Lock()
        self._last_adjust = clock()
        self._last_wait_count, self._last_wait_sum = metrics.read_histogram(
            "tpu.batch.queue_wait"
        )
        self._last_util = 0.0
        self._last_wait_s = 0.0
        self._last_shed_event = 0.0
        metrics.gauge("admission.level").set(self.level)

    # -- load signals -------------------------------------------------------

    def _read_signals(self) -> tuple[float, float]:
        """(queue utilization in [0,1], avg queue_wait seconds since the
        last adjustment) from the injected provider or the live batcher +
        stage-latency histogram."""
        if self._signals is not None:
            return self._signals()
        util = 0.0
        if self.batcher is not None:
            depth, capacity = self.batcher.load_snapshot()
            util = depth / capacity if capacity > 0 else 0.0
        count, total = metrics.read_histogram("tpu.batch.queue_wait")
        d_count = count - self._last_wait_count
        d_sum = total - self._last_wait_sum
        self._last_wait_count, self._last_wait_sum = count, total
        wait = d_sum / d_count if d_count > 0 else 0.0
        return util, wait

    def _maybe_adjust(self, now: float) -> None:
        s = self.settings
        with self._lock:
            if now - self._last_adjust < s.adjust_interval_ms / 1000.0:
                return
            self._last_adjust = now
            util, wait = self._read_signals()
            self._last_util, self._last_wait_s = util, wait
            overloaded = (
                util >= s.high_watermark
                or wait * 1000.0 >= s.target_queue_wait_ms
            )
            healthy = (
                util <= s.low_watermark
                and wait * 1000.0 < s.target_queue_wait_ms
            )
            old = self.level
            if overloaded:
                self.level = max(MIN_LEVEL, self.level * s.decrease_factor)
            elif healthy:
                self.level = min(float(N_TIERS), self.level + s.increase_step)
            self.level = min(self.level, self.level_cap)
            changed = self.level != old
        if changed:
            metrics.gauge("admission.level").set(self.level)
            from ..observability import get_tracer

            get_tracer().record_event(
                "admission_level",
                old=round(old, 3), new=round(self.level, 3),
                utilization=round(util, 3),
                queue_wait_ms=round(wait * 1000.0, 3),
            )

    def set_level_cap(self, cap: float) -> float:
        """Clamp the admission level's recovery ceiling (the fleet
        controller's burn-page actuator).  The cap itself is clamped to
        ``[MIN_LEVEL, N_TIERS]`` — the controller can never bias tier-0
        logins out — and an already-higher level drops to it immediately.
        Returns the applied cap."""
        cap = min(float(N_TIERS), max(MIN_LEVEL, float(cap)))
        with self._lock:
            self.level_cap = cap
            old = self.level
            self.level = min(self.level, cap)
            changed = self.level != old
        if changed:
            metrics.gauge("admission.level").set(self.level)
        return cap

    # -- admission ----------------------------------------------------------

    def admit(self, rpc: str, key: str) -> Rejection | None:
        """One admission decision: ``None`` admits; a :class:`Rejection`
        tells the service layer what to shed with.  Never raises on
        arbitrary ``rpc``/``key`` input (fuzz invariant)."""
        now = self._clock()
        self._maybe_adjust(now)
        tier = classify(rpc)
        retry_after = self.buckets.check(key, now=now)
        metrics.gauge("admission.clients").set(len(self.buckets))
        if retry_after is not None:
            metrics.counter("admission.shed.per_client").inc()
            self._shed_event(now, rpc, tier, "per_client", key)
            return Rejection(
                reason="per_client",
                message="Per-client rate limit exceeded",
                retry_after_s=self._clamp(retry_after),
                tier=tier,
            )
        if tier >= self.level:
            metrics.counter("admission.shed.priority").inc()
            self._shed_event(now, rpc, tier, "priority", key)
            return Rejection(
                reason="priority",
                message=(
                    "Server overloaded: shedding "
                    f"{TIER_NAMES.get(tier, tier)}-tier requests"
                ),
                retry_after_s=self.retry_after_s(),
                tier=tier,
            )
        metrics.counter("admission.admitted").inc()
        return None

    # -- pushback -----------------------------------------------------------

    def _clamp(self, seconds: float) -> float:
        s = self.settings
        return min(
            s.retry_after_max_ms / 1000.0,
            max(s.retry_after_min_ms / 1000.0, seconds),
        )

    def retry_after_s(self) -> float:
        """Server pushback sized from the current queue drain rate: how
        long until the backlog ahead of a retry would clear.  Falls back
        to one batch window's worth of wait when no drain has been
        observed yet, and to the configured minimum off the batched
        path."""
        batcher = self.batcher
        if batcher is None:
            return self._clamp(0.0)
        depth, _ = batcher.load_snapshot()
        rate = batcher.drain_rate()
        if rate > 0.0:
            return self._clamp(depth / rate)
        est = batcher.window * (1.0 + depth / max(1, batcher.max_batch))
        return self._clamp(est)

    # -- observability ------------------------------------------------------

    def _shed_event(self, now, rpc, tier, reason, key) -> None:
        """Shed events land in the trace ring, rate-limited to one per
        adjust interval so an overload storm cannot evict every real
        trace from the ring."""
        interval = self.settings.adjust_interval_ms / 1000.0
        with self._lock:
            if now - self._last_shed_event < interval:
                return
            self._last_shed_event = now
        from ..observability import get_tracer

        get_tracer().record_event(
            "admission_shed",
            rpc=str(rpc)[:64], tier=tier, reason=reason, key=str(key)[:64],
            level=round(self.level, 3),
        )

    def snapshot(self) -> dict:
        """Operator view behind the REPL ``/overload``."""
        depth, capacity, rate = 0, 0, 0.0
        if self.batcher is not None:
            depth, capacity = self.batcher.load_snapshot()
            rate = self.batcher.drain_rate()
        admitted_tiers = [
            TIER_NAMES[t] for t in range(N_TIERS) if t < self.level
        ]
        return {
            "level": self.level,
            "level_cap": self.level_cap,
            "admitted_tiers": admitted_tiers,
            "clients": len(self.buckets),
            "max_clients": self.buckets.max_keys,
            "evictions": self.buckets.evictions,
            "per_client_rpm": self.buckets.rate,
            "queue_depth": depth,
            "queue_capacity": capacity,
            "drain_rate": rate,
            "retry_after_ms": self.retry_after_s() * 1000.0,
            "utilization": self._last_util,
            "queue_wait_ms": self._last_wait_s * 1000.0,
            "shed_per_client": metrics.read("admission.shed.per_client"),
            "shed_priority": metrics.read("admission.shed.priority"),
            "shed_global": metrics.read("admission.shed.global"),
            "admitted": metrics.read("admission.admitted"),
        }
