"""Resilience subsystem: self-healing failover, retries, fault injection.

Three pieces, each independently usable:

- :mod:`.breaker` — a thread-safe circuit breaker with half-open probe
  recovery.  :class:`~cpzk_tpu.protocol.batch.FailoverBackend` drives it so
  a TPU device loss degrades to the CPU fallback and then *heals* (probe
  batch re-validated against the fallback ground truth) instead of staying
  degraded until an operator runs ``reset()``.
- :mod:`.retry` — client-side exponential backoff with full jitter and a
  shared retry budget (gRPC A6-style), used by
  :class:`~cpzk_tpu.client.AuthClient` for idempotent-safe RPCs only.
- :mod:`.faults` — a seeded, deterministic :class:`FaultPlan` plus backend
  and snapshot-I/O injectors so the failure paths above are *exercised* by
  tests (``tests/test_chaos.py``) rather than assumed.

``faults`` pulls in :mod:`cpzk_tpu.protocol.batch`, which itself lazily
constructs breakers — so this package eagerly exports only the
dependency-free modules and resolves the rest on attribute access.
"""

from __future__ import annotations

from .breaker import BreakerState, CircuitBreaker
from .retry import RETRY_PUSHBACK_KEY, RetryBudget, RetryPolicy

__all__ = [
    "BreakerState",
    "CircuitBreaker",
    "RETRY_PUSHBACK_KEY",
    "RetryBudget",
    "RetryPolicy",
    "CrashPoint",
    "FaultPlan",
    "FaultInjectionBackend",
    "InjectedFault",
    "SnapshotFaults",
    "WAL_CRASH_POINTS",
]


def __getattr__(name: str):
    if name in (
        "CrashPoint",
        "FaultPlan",
        "FaultInjectionBackend",
        "InjectedFault",
        "SnapshotFaults",
        "WAL_CRASH_POINTS",
    ):
        from . import faults

        return getattr(faults, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
