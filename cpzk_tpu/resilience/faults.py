"""Deterministic fault injection for the serving stack.

A :class:`FaultPlan` is a seeded, declarative schedule of device and I/O
faults; :class:`FaultInjectionBackend` applies it to any
:class:`~cpzk_tpu.protocol.batch.VerifierBackend` (raise-after-N-batches,
intermittent flapping, per-batch latency spikes), and
:class:`SnapshotFaults` injects ``OSError`` mid-``write()`` into
:meth:`~cpzk_tpu.server.state.ServerState.snapshot`, and the WAL crash
points (:meth:`FaultPlan.crash_on`) schedule deterministic process-death
stand-ins at exact write sites inside
:class:`~cpzk_tpu.durability.wal.WriteAheadLog` (``pre_append`` /
``mid_frame`` / ``post_append_pre_fsync`` / ``pre_rename``).  Everything
is reproducible from the plan alone — same plan, same faults, same batch
indexes — so chaos tests (``tests/test_chaos.py``) and the durability
suite (``tests/test_durability.py``) assert exact outcomes instead of
sampling flaky timing windows.

Example::

    plan = (FaultPlan(seed=7)
            .fail_on(0)                  # first device batch raises
            .flap(period=3, fail=1, start=4, until=10)
            .latency(0.02, every=5)      # every 5th batch sleeps ~20ms
            .snapshot_errors(2))         # first two snapshot writes fail
    backend = FailoverBackend(FaultInjectionBackend(TpuBackend(), plan),
                              CpuBackend(), recovery_after_s=0.5)
"""

from __future__ import annotations

import os
import random
import threading
import time

from ..durability.wal import WAL_CRASH_POINTS, CrashPoint  # noqa: F401 (re-export)
from ..protocol.batch import VerifierBackend

#: Replication-plane crash sites (ISSUE 8): ``pre_ship`` (primary dies
#: before a segment leaves), ``mid_segment`` (primary dies mid-transfer —
#: the standby receives a torn segment and must reject it whole), and
#: ``pre_promote`` (standby dies at the promotion decision; a retried
#: promote must succeed).  Consulted by ``SegmentShipper`` and
#: ``StandbyReplica`` the same way the WAL sites are by ``WriteAheadLog``.
REPLICATION_CRASH_POINTS = ("pre_ship", "mid_segment", "pre_promote")

#: Fleet-split crash sites (one per split stage — see
#: ``cpzk_tpu/fleet/split.py`` SPLIT_CRASH_POINTS for the exact file
#: state each leaves behind).  Consulted by ``run_split(..., faults=)``;
#: the chaos suite SIGKILLs every stage through these and asserts both
#: partitions come back with a disjoint, exhaustive key set.
FLEET_CRASH_POINTS = (
    "pre_manifest", "pre_copy", "mid_copy",
    "pre_flip", "pre_drain", "pre_finish",
)

#: Coordinated-handover crash sites (ISSUE 18), one per protocol stage.
#: Primary side (``SegmentShipper.run_handover``): ``pre_handover_fence``
#: (nothing armed yet), ``post_handover_fence`` (write fence armed, tail
#: not shipped), ``pre_handover_promote`` (tail acked at the fence
#: watermark, promote instruction never sent), ``post_handover_promote``
#: (standby promoted, deposed-redirect mode not entered).  Standby side
#: (``StandbyReplica.handover``): ``pre_handover_ack`` (promote
#: instruction received, nothing done).  A crash at ANY of these must
#: degrade to ordinary lease failover — handover is an optimization of
#: the failure path, never a second consistency protocol.
HANDOVER_CRASH_POINTS = (
    "pre_handover_fence",
    "post_handover_fence",
    "pre_handover_promote",
    "post_handover_promote",
    "pre_handover_ack",
)

ALL_CRASH_POINTS = (
    WAL_CRASH_POINTS + REPLICATION_CRASH_POINTS + FLEET_CRASH_POINTS
    + HANDOVER_CRASH_POINTS
)


class InjectedFault(RuntimeError):
    """Deterministic injected device failure (stand-in for a TPU loss)."""


class FaultPlan:
    """Seeded, composable schedule of faults, keyed by batch index.

    Builder methods return ``self`` so plans read as one expression.  The
    seed only matters for the probabilistic/jittered knobs
    (:meth:`fail_probability`, latency jitter); the structural schedule
    (``fail_on`` / ``fail_range`` / ``flap``) is exact.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._fail_exact: set[int] = set()
        self._fail_ranges: list[tuple[int, int]] = []  # [start, stop)
        self._flaps: list[tuple[int, int, int, int]] = []  # (period, fail, start, stop)
        self._p_fail: list[tuple[float, int, int]] = []  # (p, start, stop)
        self._latency_s = 0.0
        self._latency_every = 0
        self._snapshot_errors = 0
        self._snapshot_lock = threading.Lock()
        # WAL crash points: site -> scheduled occurrence indexes, and the
        # per-site visit counters (shared lock with the snapshot budget)
        self._crash_points: dict[str, set[int]] = {}
        self._crash_seen: dict[str, int] = {}

    # -- builders ----------------------------------------------------------

    def fail_on(self, *batch_indexes: int) -> "FaultPlan":
        """Raise :class:`InjectedFault` on exactly these batch indexes."""
        self._fail_exact.update(batch_indexes)
        return self

    def fail_range(self, start: int, stop: int) -> "FaultPlan":
        """Raise on every batch index in ``[start, stop)`` — the
        raise-after-N-batches shape is ``fail_range(n, 10**9)``."""
        self._fail_ranges.append((start, stop))
        return self

    def fail_after(self, n: int) -> "FaultPlan":
        """Raise on every batch from index ``n`` onward (device gone for
        good — the permanent-loss scenario)."""
        return self.fail_range(n, 1 << 62)

    def flap(self, period: int, fail: int, start: int = 0,
             until: int = 1 << 62) -> "FaultPlan":
        """Intermittent flapping: within ``[start, until)``, batch ``i``
        raises when ``(i - start) % period < fail``."""
        if period < 1 or not 0 <= fail <= period:
            raise ValueError("flap requires period >= 1 and 0 <= fail <= period")
        self._flaps.append((period, fail, start, until))
        return self

    def fail_probability(self, p: float, start: int = 0,
                         until: int = 1 << 62) -> "FaultPlan":
        """Raise on batch ``i`` with probability ``p`` — deterministic in
        (seed, i), independent across indexes."""
        self._p_fail.append((p, start, until))
        return self

    def latency(self, seconds: float, every: int = 1) -> "FaultPlan":
        """Latency spike (~``seconds``, ±50% seeded jitter) on every
        ``every``-th batch."""
        self._latency_s = seconds
        self._latency_every = max(1, every)
        return self

    def snapshot_errors(self, n: int) -> "FaultPlan":
        """Fail the next ``n`` state-snapshot writes with ``OSError``
        (consumed by :class:`SnapshotFaults`)."""
        self._snapshot_errors = n
        return self

    def crash_on(self, point: str, occurrence: int = 0) -> "FaultPlan":
        """Schedule a :class:`CrashPoint` at the ``occurrence``-th visit of
        a WAL crash site (``pre_append`` / ``mid_frame`` /
        ``post_append_pre_fsync`` count once per append, in that order;
        ``pre_rename`` once per single-file compaction, ``pre_seal`` once
        per segment seal, ``pre_unlink`` once per covered-segment unlink
        under segmented compaction) or a replication site
        (``pre_ship`` / ``mid_segment`` once per shipped segment,
        ``pre_promote`` once per promotion attempt) or a handover stage
        (``HANDOVER_CRASH_POINTS`` — once per visit of that stage in
        ``SegmentShipper.run_handover`` / ``StandbyReplica.handover``) —
        the deterministic
        stand-in for the process dying at exactly that instruction.  Pass
        the plan as ``WriteAheadLog(..., faults=plan)`` /
        ``DurabilityManager(..., faults=plan)`` /
        ``SegmentShipper(..., faults=plan)`` /
        ``StandbyReplica(..., faults=plan)`` to arm it."""
        if point not in ALL_CRASH_POINTS:
            raise ValueError(
                f"unknown crash point {point!r}; one of {ALL_CRASH_POINTS}"
            )
        if occurrence < 0:
            raise ValueError("crash_on occurrence must be >= 0")
        self._crash_points.setdefault(point, set()).add(occurrence)
        return self

    # -- queries -----------------------------------------------------------

    def should_fail(self, batch_index: int) -> bool:
        i = batch_index
        if i in self._fail_exact:
            return True
        if any(start <= i < stop for start, stop in self._fail_ranges):
            return True
        for period, fail, start, stop in self._flaps:
            if start <= i < stop and (i - start) % period < fail:
                return True
        for p, start, stop in self._p_fail:
            if start <= i < stop and self._roll(i) < p:
                return True
        return False

    def latency_for(self, batch_index: int) -> float:
        if self._latency_s <= 0 or batch_index % self._latency_every:
            return 0.0
        return self._latency_s * (0.5 + self._roll(~batch_index))

    def take_snapshot_error(self) -> bool:
        with self._snapshot_lock:
            if self._snapshot_errors <= 0:
                return False
            self._snapshot_errors -= 1
            return True

    def take_crash(self, point: str) -> bool:
        """Visit one WAL crash site: bump its occurrence counter and report
        whether this visit was scheduled by :meth:`crash_on`."""
        with self._snapshot_lock:
            i = self._crash_seen.get(point, 0)
            self._crash_seen[point] = i + 1
            return i in self._crash_points.get(point, ())

    def _roll(self, key: int) -> float:
        return random.Random(f"{self.seed}:{key}").random()


class FaultInjectionBackend(VerifierBackend):
    """Wrap any backend with a :class:`FaultPlan`.

    Each ``verify_combined`` / ``verify_each`` call is one batch: the
    shared counter increments, the plan's latency spike (if any) is slept
    on the calling worker thread, then either :class:`InjectedFault` is
    raised or the call delegates to the wrapped backend.  The counter is
    lock-guarded (pipelined dispatches call from multiple threads) and
    ``batches_seen`` / ``faults_raised`` are exposed for assertions.
    """

    def __init__(self, inner: VerifierBackend, plan: FaultPlan):
        self.inner = inner
        self.plan = plan
        self.batches_seen = 0
        self.faults_raised = 0
        self._lock = threading.Lock()

    @property
    def prefers_combined(self) -> bool:  # type: ignore[override]
        return self.inner.prefers_combined

    @property
    def supports_deferred_decode(self) -> bool:  # type: ignore[override]
        return self.inner.supports_deferred_decode

    def _gate(self) -> None:
        with self._lock:
            i = self.batches_seen
            self.batches_seen += 1
        lat = self.plan.latency_for(i)
        if lat > 0:
            time.sleep(lat)
        if self.plan.should_fail(i):
            with self._lock:
                self.faults_raised += 1
            raise InjectedFault(f"injected device fault at batch {i}")

    def verify_combined(self, rows, beta) -> bool:
        self._gate()
        return self.inner.verify_combined(rows, beta)

    def verify_each(self, rows) -> list[int]:
        self._gate()
        return self.inner.verify_each(rows)


class SnapshotFaults:
    """Context manager: ``OSError`` mid-``write()`` during state snapshots.

    Patches ``os.fsync`` so the injected failure lands *after* the JSON
    document has been written to the unique tmp file but *before* it can
    be renamed over the previous snapshot — the worst-ordered crash the
    atomic-rename protocol must survive (previous snapshot stays intact,
    tmp debris is unlinked, ``_persist_dirty`` re-arms for the next
    sweep).  Only fsyncs on the snapshotting thread are candidates; calls
    beyond the plan's ``snapshot_errors`` budget pass through untouched.
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._orig_fsync = None

    def __enter__(self) -> "SnapshotFaults":
        self._orig_fsync = os.fsync

        def fsync(fd):
            if self.plan.take_snapshot_error():
                raise OSError(5, "injected I/O error mid-snapshot-write")
            return self._orig_fsync(fd)

        os.fsync = fsync
        return self

    def __exit__(self, *exc) -> None:
        os.fsync = self._orig_fsync
