"""Circuit breaker with half-open probe recovery.

State machine (the classic Nygard breaker, specialized for a primary/
fallback verifier pair where the fallback is *always* correct, just slow):

    CLOSED ──failure──▶ OPEN ──recovery_after_s──▶ HALF_OPEN
       ▲                  ▲                            │
       │                  └───────probe failed─────────┤
       └────────────────probe succeeded────────────────┘

- CLOSED: traffic routes to the primary (TPU).
- OPEN: traffic routes to the fallback; after ``recovery_after_s`` the
  next caller is granted a single *probe* and the breaker moves to
  HALF_OPEN.
- HALF_OPEN: exactly one probe is in flight; everyone else stays on the
  fallback.  The probe's outcome (decided by the caller — for verifier
  backends, primary output compared against fallback ground truth)
  either re-closes the breaker or re-opens it and restarts the timer.

Thread-safety: the serving layer's pipelined batcher calls backends from
multiple worker threads; every transition is lock-guarded and the probe
token is handed to exactly one caller.

The breaker knows nothing about verifiers — it is a generic routing/
bookkeeping core (see ``FailoverBackend`` for the verifier policy on top).
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Callable


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half-open"


#: Routing decisions handed out by :meth:`CircuitBreaker.acquire`.
ROUTE_PRIMARY = "primary"
ROUTE_PROBE = "probe"
ROUTE_FALLBACK = "fallback"


class CircuitBreaker:
    """Generic three-state breaker; see module docstring for semantics.

    ``recovery_after_s=None`` disables self-healing entirely (the breaker
    stays OPEN until :meth:`reset` — the legacy permanent-degradation
    behavior).  ``clock`` is injectable for deterministic tests.
    ``on_transition(old, new)`` fires outside the lock, at most once per
    actual state change — metrics/log hooks can't miss or double-count.
    """

    def __init__(
        self,
        recovery_after_s: float | None = 30.0,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Callable[[BreakerState, BreakerState], None] | None = None,
    ):
        if recovery_after_s is not None and recovery_after_s < 0:
            raise ValueError("recovery_after_s cannot be negative")
        self.recovery_after_s = recovery_after_s
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = BreakerState.CLOSED
        self._opened_at = 0.0  # clock time of the most recent -> OPEN
        self._degraded_since: float | None = None  # clock time we left CLOSED
        self._degraded_total = 0.0  # cumulative seconds spent non-CLOSED

    # -- observability -----------------------------------------------------

    @property
    def state(self) -> BreakerState:
        with self._lock:
            return self._state

    @property
    def degraded_seconds(self) -> float:
        """Cumulative wall seconds spent outside CLOSED (live-updating
        while degraded) — the ``tpu.backend.degraded_seconds`` gauge."""
        with self._lock:
            total = self._degraded_total
            if self._degraded_since is not None:
                total += max(0.0, self._clock() - self._degraded_since)
            return total

    # -- routing -----------------------------------------------------------

    def acquire(self) -> str:
        """Route one unit of work: ``"primary"`` (CLOSED), ``"probe"``
        (granted to exactly one caller once the OPEN cooldown elapses,
        transitioning to HALF_OPEN), or ``"fallback"``."""
        transition = None
        with self._lock:
            if self._state is BreakerState.CLOSED:
                return ROUTE_PRIMARY
            if (
                self._state is BreakerState.OPEN
                and self.recovery_after_s is not None
                and self._clock() - self._opened_at >= self.recovery_after_s
            ):
                transition = (self._state, BreakerState.HALF_OPEN)
                self._state = BreakerState.HALF_OPEN
        if transition is not None:
            self._fire(*transition)
            return ROUTE_PROBE
        return ROUTE_FALLBACK

    # -- outcomes ----------------------------------------------------------

    def record_failure(self) -> bool:
        """Primary failed on the CLOSED path.  Returns True for the caller
        that performed the CLOSED→OPEN transition (log/count exactly once
        even when pipelined batches fail concurrently)."""
        with self._lock:
            if self._state is not BreakerState.CLOSED:
                return False
            self._state = BreakerState.OPEN
            self._opened_at = self._clock()
            self._degraded_since = self._clock()
        self._fire(BreakerState.CLOSED, BreakerState.OPEN)
        return True

    def probe_succeeded(self) -> None:
        """HALF_OPEN probe matched ground truth: re-close."""
        with self._lock:
            if self._state is not BreakerState.HALF_OPEN:
                return
            self._state = BreakerState.CLOSED
            self._settle_degraded_locked()
        self._fire(BreakerState.HALF_OPEN, BreakerState.CLOSED)

    def probe_failed(self) -> None:
        """HALF_OPEN probe raised or disagreed: re-open, restart cooldown."""
        with self._lock:
            if self._state is not BreakerState.HALF_OPEN:
                return
            self._state = BreakerState.OPEN
            self._opened_at = self._clock()
        self._fire(BreakerState.HALF_OPEN, BreakerState.OPEN)

    def release_probe(self) -> None:
        """Hand an unused probe back (the caller couldn't evaluate it, e.g.
        the work unit wasn't probe-shaped): back to OPEN with the original
        cooldown timestamp, so the *next* caller probes immediately."""
        with self._lock:
            if self._state is not BreakerState.HALF_OPEN:
                return
            self._state = BreakerState.OPEN
            # _opened_at deliberately untouched: cooldown already served

    def reset(self) -> None:
        """Operator re-arm: back to CLOSED regardless of state."""
        with self._lock:
            old = self._state
            if old is BreakerState.CLOSED:
                return
            self._state = BreakerState.CLOSED
            self._settle_degraded_locked()
        self._fire(old, BreakerState.CLOSED)

    # -- internals ---------------------------------------------------------

    def _settle_degraded_locked(self) -> None:
        if self._degraded_since is not None:
            self._degraded_total += max(0.0, self._clock() - self._degraded_since)
            self._degraded_since = None

    def _fire(self, old: BreakerState, new: BreakerState) -> None:
        if self._on_transition is not None and old is not new:
            self._on_transition(old, new)
